"""ServingFleet: an admission router over N decode-server replicas.

PR 2–7 built a single-replica continuous-batching decode server
(paged KV, shared-prefix reuse, self-healing).  This module is the
thin scheduling/placement frontend that turns N of those into a
SERVING SYSTEM — the TensorFlow-paper split of a small stateful
scheduler over homogeneous compute workers, applied one level up from
the slot scheduler inside each ``GenerationServer``:

* **admission** — every ``submit(tenant=...)`` passes the per-tenant
  token buckets + concurrency/queue caps in
  :class:`~.tenancy.TenantAccountant` before it can touch a replica.
  A hot tenant saturating its bucket WAITS; a structurally-
  unadmittable request (cost above burst, queue cap hit) fails fast
  with :class:`~.errors.QuotaExceededError`; and because the dispatch
  pass walks ALL waiting requests each pass (not FIFO across
  tenants), a capped hot tenant cannot delay a cold tenant's
  admission beyond one scheduling pass;
* **SLO-aware dispatch** — waiting requests dispatch in
  (priority, earliest deadline, arrival) order — EDF within a
  priority class, reusing PR 3's per-request ``deadline_s`` plumbing
  end to end (the remaining budget rides into the replica, which
  enforces expiry mid-decode).  Requests whose deadline cannot be met
  even dispatched immediately (``est_token_s * n_new`` above the
  budget, or a non-positive budget) are rejected at submit with
  :class:`~.errors.DeadlineInfeasibleError` — no KV blocks burned on
  a request that must expire;
* **placement** — prefix-affinity first (route same-prefix requests
  to the replica whose cache is warm, via the bytes-verified
  ``prefix_warmth`` probe PR 7's chain hashes enable), least-loaded
  by free KV blocks otherwise (:mod:`~.placement`); unhealthy and
  draining replicas are never candidates (health-weighted dispatch
  off the same liveness the ``server_healthy`` gauge exposes);
* **lifecycle** — :meth:`ServingFleet.drain` rolls a replica out
  (admission stops, in-flight finishes; ``hard=True`` also migrates
  its work), :meth:`ServingFleet.kill` is the chaos-drill
  SIGKILL-equivalent, and LIVE MIGRATION closes ROADMAP item 4's
  remainder: when a replica dies or is drained hard, its queued AND
  in-flight requests re-place onto surviving replicas through the
  existing retry machinery (typed retryable errors +
  ``resilience.retry.backoff_delay`` jitter, bounded by
  ``migration_retries``) and complete byte-identical to offline
  ``generate()`` — greedy decode is deterministic, so a from-scratch
  re-decode on the survivor IS the same bytes;
* **elastic scale** (ISSUE 10) — :meth:`ServingFleet.add_replica`
  joins one more replica built from the founding config (it becomes a
  dispatch candidate only after its first successful ``stats()``) and
  :meth:`ServingFleet.remove_replica` scales in through the same
  drain→migrate machinery — the serving mirror of the training
  layer's N→M elastic resume;
* **disaggregated prefill/decode** (ISSUE 14) — ``roles`` assigns
  each replica ``"prefill"``/``"decode"``/``"unified"`` (default
  unified: existing fleets untouched).  Chunked prefill is
  compute-bound and decode memory-bound, and in a unified replica one
  long admission stalls every decoding stream behind its prefill.
  With roles split, the router classifies at admission (it already
  costs prompt+budget tokens): prompts >= ``prefill_threshold``
  tokens stage through a prefill replica
  (``GenerationServer.prefill_async`` — admit + chunked prefill +
  prefix-cache registration, no decode ticks), then the finished
  prefix hands off to a decode replica as a BLOCK TRANSFER through
  PR 7's table abstraction: ``export_prefix`` serializes the blocks
  (chain hashes + raw token bytes + K/V bytes), ``import_blocks``
  lands them on the target, and the decode admission restores them
  with one batched H2D and registers them device-resident — every
  later same-prefix admission maps them copy-free.  Greedy byte
  parity holds end to end (the restored bytes ARE the prefill
  replica's, and both replicas run identical prefill numerics), and a
  prefill replica dying mid-handoff re-places the request through the
  EXISTING migration machinery — reclassified against the surviving
  topology, completing byte-identical either way.

The fleet is in-process: replicas share the host, but a replica no
longer maps to at most one chip — ``devices=`` hands each replica its
own (disjoint) device slice and the server lays its tick over a
``data``/``tp`` mesh (ISSUE 17, ``parallel/mesh.py``), so ONE fleet
mixes single-chip and multi-chip replicas.  The router stays
placement-policy-only: a replica's span is invisible to admission,
affinity and migration (a tp=2 victim's requests re-place
byte-identically onto a single-chip survivor), and the per-replica
``fleet_replica_devices{replica=}`` gauge is the only router-side
trace of the topology.

Telemetry: ``fleet_requests_total{tenant=,outcome=}`` (admitted /
queued / rejected_quota / rejected_deadline / migrated — plus
terminal cancelled / expired / failed), ``fleet_replica_dispatch_
total{replica=,reason=}`` (affinity / least_loaded / failover),
``fleet_queue_wait_seconds{tenant=}``, ``fleet_replicas_healthy`` and
``fleet_queue_depth``.

Request-scoped TRACING (ISSUE 12): ``submit`` mints a trace id that
flows admission -> placement -> replica queue -> prefill -> decode ->
retire; every phase records a tracked span tagged ``trace=<id>``
(``telemetry.get_tracer().events_for_trace(id)`` is one request's
cross-component tree) and the same instrumentation observes
``fleet_request_phase_seconds{phase=}`` — TTFT decomposed into its
phases — plus ``fleet_edf_slack_seconds{tenant=}`` at dispatch, the
autoscaler's pressure signal.  ``demote_waiting`` is the autoscaler's
shed/defer actuator for batch-class tenants.
"""
from __future__ import annotations

import itertools
import logging
import os
import queue
import threading
import time
from typing import Dict, Iterable, List, Optional

import numpy as np

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.parallel.generation_server import GenerationServer
from deeplearning4j_tpu.resilience.errors import (CancelledError,
                                                  DeadlineExceededError,
                                                  RetryableServerError)
from deeplearning4j_tpu.resilience.retry import backoff_delay, retry_call
from deeplearning4j_tpu.serving.errors import (AdmissionRejectedError,
                                               DeadlineInfeasibleError,
                                               NoHealthyReplicaError,
                                               QuotaExceededError)
from deeplearning4j_tpu.serving.placement import (FAILOVER, HANDOFF,
                                                  PREFILL, ROLE_PREFILL,
                                                  ROLE_UNIFIED, ROLES,
                                                  choose_replica)
from deeplearning4j_tpu.serving.tenancy import TenantAccountant, TenantQuota

log = logging.getLogger("deeplearning4j_tpu")

_INF = float("inf")

_REQS = telemetry.counter(
    "fleet_requests_total",
    "fleet admission outcomes per tenant: admitted (first dispatch "
    "to a replica — a disagg request's prefill placement), queued "
    "(waited >= 1 pass on quota/capacity), rejected_quota, "
    "rejected_deadline (infeasible SLO), rejected_slo (admission-"
    "time burn projection / rung-4 shed), migrated (re-placed off a "
    "dead/drained replica), handed_off (a disagg request's decode "
    "placement carrying its exported prefix), cancelled, expired, "
    "failed", labelnames=("tenant", "outcome"))
_DISPATCH = telemetry.counter(
    "fleet_replica_dispatch_total",
    "requests dispatched per replica by placement reason: affinity "
    "(prefix-cache warm), least_loaded (most free KV blocks), "
    "failover (migration off a dead/drained replica)",
    labelnames=("replica", "reason"))
_QWAIT = telemetry.histogram(
    "fleet_queue_wait_seconds",
    "submit -> first dispatch per request, by tenant (the admission "
    "delay quotas and capacity impose — the fairness signal)",
    labelnames=("tenant",))
_REPL_HEALTHY = telemetry.gauge(
    "fleet_replicas_healthy",
    "replicas currently dispatchable (healthy, not dead, not "
    "draining) — a fleet balancer's aggregate health signal")
_REPL_DEVICES = telemetry.gauge(
    "fleet_replica_devices",
    "chips in each replica's device slice (ISSUE 17): 1 = single-chip "
    "replica, N = a mesh-sharded replica spanning N chips as one tp "
    "group", labelnames=("replica",))
_FLEET_QDEPTH = telemetry.gauge(
    "fleet_queue_depth",
    "requests waiting in the fleet router (intake + quota/capacity "
    "wait line; per-replica queues are counted by the replicas)")
# Request-phase decomposition (ISSUE 12): the SAME instrumentation
# that records each request's trace spans observes this family, so
# TTFT stops being one opaque number — admission wait, placement,
# replica queue, prefill and decode each carry their own series.
_PHASE = telemetry.histogram(
    "fleet_request_phase_seconds",
    "per-request phase wall times (the trace spans' durations): "
    "admission (submit -> first dispatch), placement (candidate "
    "ranking + replica handoff), total (submit -> retire); the "
    "replica-side queue/prefill/decode phases come from the decode "
    "server's half of the same family", labelnames=("phase",))
_EDF_SLACK = telemetry.histogram(
    "fleet_edf_slack_seconds",
    "remaining deadline budget at dispatch, per tenant — the EDF "
    "slack whose low percentiles collapsing toward 0 are the "
    "autoscaler's scale-up pressure", labelnames=("tenant",))
_SLO_DEFER = telemetry.counter(
    "fleet_slo_budget_deferrals_total",
    "waiting requests demoted behind within-budget tenants because "
    "their tenant's SLO error budget is exhausted (ISSUE 15: "
    "budget-exhausted batch work defers BEFORE any interactive "
    "tenant is shed)", labelnames=("tenant",))
# Production front door (ISSUE 18): admission-time SLO projection and
# degradation-ladder shaping, counted per tenant BEFORE any reserve —
# a rejected request costs the pool nothing, and the three outcomes
# partition every submit_async that reached the front door.
_ADMIT_OK = telemetry.counter(
    "fleet_admission_admitted_total",
    "requests admitted untouched by the SLO projection and the "
    "degradation ladder", labelnames=("tenant",))
_ADMIT_DEG = telemetry.counter(
    "fleet_admission_degraded_total",
    "requests admitted DEGRADED (n_new capped and/or forced greedy) "
    "by the SLO projection or the active degradation rung",
    labelnames=("tenant",))
_ADMIT_REJ = telemetry.counter(
    "fleet_admission_rejected_total",
    "requests rejected at admission with AdmissionRejectedError "
    "(projected budget overdraft, or rung 4 shedding the batch "
    "class) — zero replica cost, retry_after_s attached",
    labelnames=("tenant",))
# Tail-latency hedging (ISSUE 18): near-deadline interactive requests
# duplicate onto a second warm replica; first completion wins.
_HEDGE_LAUNCH = telemetry.counter(
    "fleet_hedges_launched_total",
    "hedge placements launched (a near-deadline request duplicated "
    "byte-identically onto a second warm replica, raced first-wins)")
_HEDGE_WON = telemetry.counter(
    "fleet_hedges_won_total",
    "hedge races the HEDGE placement won (the primary was cancelled "
    "and the hedge's bytes delivered)")
_HEDGE_CANCEL = telemetry.counter(
    "fleet_hedges_cancelled_total",
    "hedge races resolved by cancelling the loser — exactly one per "
    "resolved race, whichever side lost")

#: the per-host flight recorder (ISSUE 15): placement decisions,
#: migrations, handoffs and chaos kills land in the black-box ring a
#: postmortem bundle freezes
_FLIGHT = telemetry.get_flight_recorder()

#: intake sentinel that wakes the scheduler without meaning "stop"
_WAKE = object()

#: process-unique request trace ids (the pid makes them fleet-unique
#: across workers beaconing into one shared trace store)
_TRACE_SEQ = itertools.count()


def _mint_trace_id() -> str:
    return f"req-{os.getpid():x}-{next(_TRACE_SEQ):x}"


class _FleetRequest:
    """One request riding through the fleet.  ``result()`` blocks the
    caller; the fleet scheduler fills ``_result``/``_error``.  The
    handle survives migration: ``inner``/``replica`` point at the
    CURRENT placement and are rewritten when the request re-places off
    a dead replica."""

    __slots__ = ("prompt", "n_new", "eos_id", "seed", "sampling",
                 "tenant", "priority", "cost", "deadline", "t_submit",
                 "t_submit_m", "cancelled", "migrations", "replica",
                 "inner", "ttft", "trace_id", "spans", "stage",
                 "handoff", "prefill_replica", "hedge_inner",
                 "hedge_replica", "_t_hedge", "_t_dispatch",
                 "_not_before", "_migrate", "_quota_held",
                 "_queued_counted", "_migrating", "_budget_deferred",
                 "_result", "_error", "_event")

    def __init__(self, prompt, n_new, eos_id, seed, sampling, tenant,
                 priority, cost, deadline):
        self.trace_id = _mint_trace_id()
        self.spans = {}               # phase -> open telemetry.Span
        self.prompt = prompt
        self.n_new = n_new
        self.eos_id = eos_id
        self.seed = seed
        self.sampling = sampling
        self.tenant = tenant
        self.priority = priority
        self.cost = cost
        self.deadline = deadline      # absolute time.monotonic() or None
        self.t_submit = time.perf_counter()
        self.t_submit_m = time.monotonic()
        self.cancelled = False
        self.migrations = 0
        self.replica: Optional[int] = None
        self.inner = None             # the replica-side handle
        # disaggregated serving (ISSUE 14): ``stage`` is the NEXT
        # placement's kind — None (unclassified), "prefill" (route to
        # a prefill-role replica) or "decode"; ``handoff`` carries the
        # exported prefix payload between the stages (kept until the
        # request finishes, so a decode-replica death re-imports on
        # the survivor instead of re-prefilling)
        self.stage: Optional[str] = None
        self.handoff = None
        self.prefill_replica: Optional[int] = None
        # tail-latency hedge (ISSUE 18): a SECOND byte-identical
        # placement racing the primary; first completion wins and the
        # loser is cancelled through its replica-side handle
        self.hedge_inner = None
        self.hedge_replica: Optional[int] = None
        self._t_hedge = None          # hedge launch wall time (the
                                      # winner's ttft base when the
                                      # hedge wins)
        self.ttft = None              # submit -> first token of the
                                      # SUCCESSFUL attempt (queue wait
                                      # + any migration included)
        self._t_dispatch = None
        self._not_before = 0.0        # migration backoff gate
        self._migrate = False         # replica died / hard-drained
        self._quota_held = False      # bucket charged + concurrency
                                      # slot taken (kept across
                                      # migrations — one request, one
                                      # charge)
        self._queued_counted = False
        self._migrating = False       # next dispatch is a failover
        self._budget_deferred = False  # counted once per request when
                                       # its tenant's exhausted error
                                       # budget demotes it in line
        self._result = None
        self._error = None
        self._event = threading.Event()

    @property
    def emitted(self) -> int:
        """Tokens emitted by the CURRENT placement (0 while waiting)."""
        inner = self.inner
        return inner.emitted if inner is not None else 0

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until the request retires fleet-side; returns the
        full sequence (prompt + generated).  A ``TimeoutError`` leaves
        the request LIVE — ``cancel()`` releases it."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"fleet result not ready within {timeout}s (the "
                "request is still live; cancel() releases it)")
        if self._error is not None:
            raise self._error
        return self._result

    def cancel(self) -> bool:
        """Best-effort cancellation (queue entry or in-flight slot is
        released at the next scheduling pass).  False when already
        completed."""
        if self._event.is_set():
            return False
        self.cancelled = True
        inner = self.inner
        if inner is not None:
            inner.cancel()
        hedge = self.hedge_inner
        if hedge is not None:
            hedge.cancel()
        return True


class ServingFleet:
    """Admission router + lifecycle manager over ``n_replicas``
    in-process :class:`GenerationServer` replicas.

    >>> fleet = ServingFleet(net, n_replicas=2, n_slots=8,
    ...                      quotas={"free": TenantQuota(
    ...                          tokens_per_s=500, max_concurrent=2)})
    >>> out = fleet.submit(ids, n_new=64, tenant="free")  # blocking
    >>> h = fleet.submit_async(ids, n_new=64, deadline_s=2.0,
    ...                        priority=1)
    >>> out = h.result(); h.replica; h.migrations
    >>> fleet.drain(0); fleet.stats(); fleet.shutdown(drain=True)

    ``quotas`` maps tenant name -> :class:`TenantQuota`
    (``default_quota`` covers everyone else; the no-argument default
    is unlimited).  ``est_token_s`` is the per-token decode-time floor
    the deadline-feasibility screen uses (None disables the screen
    beyond "deadline already spent").  ``migration_retries`` bounds
    how many times one request may re-place off dying replicas before
    its last failure propagates; re-placements back off with the
    resilience layer's full-jitter ``backoff_delay``.

    ``roles`` (ISSUE 14) disaggregates the fleet: one
    ``"prefill"``/``"decode"``/``"unified"`` entry per replica
    (default all unified).  Prompts of at least ``prefill_threshold``
    tokens (default: two full KV blocks + 1) stage through a prefill
    replica and hand their finished prefix blocks off to a decode
    replica — byte-identical to a unified decode, with the long
    prefill off the decode replicas' tick path.  Pass
    ``host_tier_blocks`` (a server kwarg) to also spill evicted
    prefix blocks to host RAM on every replica.

    ``devices`` (ISSUE 17) gives each replica its own DEVICE SLICE —
    one entry per replica, ``None`` (default placement) or an
    explicit device list the replica mesh-shards across as one tp
    group (``GenerationServer(devices=...)``) — so one fleet mixes
    single-chip and multi-chip replicas.  Slices must be disjoint.
    The router itself stays placement-policy-only: affinity /
    least-loaded / failover ranking never looks at what a replica
    spans.

    The production front door (ISSUE 18): ``slo_engine`` +
    ``admission_control=True`` projects the tenant's SLO burn at
    ``submit`` — reject (typed
    :class:`~.errors.AdmissionRejectedError` with a server-advised
    ``retry_after_s``; ``submit(retries=)`` floors its backoff there)
    or degrade BEFORE any quota token or KV block is spent; an
    attached :class:`~.degrade.DegradeLadder`
    (:meth:`attach_degrade`) shapes admissions whenever its rung is
    elevated, flag or no flag.  ``hedge_slack_s`` arms tail-latency
    hedging: a decoding request whose deadline slack dips under it
    duplicates onto a second warm replica, first completion wins and
    the loser is cancelled, with ``hedge_budget`` bounding hedges to
    a fraction of admissions.  Remaining
    ``**server_kwargs`` construct the replicas (``speculative`` —
    draft-verified multi-token decode, whose per-replica acceptance
    rate surfaces through ``stats()`` — plus ``n_slots``,
    ``block_size``, ``tick_batch``, ...)."""

    def __init__(self, net, n_replicas: int = 2,
                 quotas: Optional[Dict[str, TenantQuota]] = None,
                 default_quota: Optional[TenantQuota] = None,
                 est_token_s: Optional[float] = None,
                 migration_retries: int = 3,
                 retry_backoff_s: float = 0.02,
                 poll_interval_s: float = 0.002,
                 dead_after_s: float = 1.0,
                 queue_limit: int = 4096,
                 roles: Optional[Iterable[str]] = None,
                 devices: Optional[Iterable] = None,
                 prefill_threshold: Optional[int] = None,
                 slo_engine=None,
                 admission_control: bool = False,
                 hedge_slack_s: Optional[float] = None,
                 hedge_budget: float = 0.25,
                 **server_kwargs):
        self.n_replicas = int(n_replicas)
        if self.n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        # per-replica roles (ISSUE 14 disaggregated prefill/decode) —
        # validated BEFORE any replica is constructed, so a bad config
        # leaks no scheduler threads
        if roles is None:
            role_list = [ROLE_UNIFIED] * self.n_replicas
        else:
            role_list = [str(r) for r in roles]
            if len(role_list) != self.n_replicas:
                raise ValueError(
                    f"roles has {len(role_list)} entries for "
                    f"n_replicas={self.n_replicas}")
            bad = [r for r in role_list if r not in ROLES]
            if bad:
                raise ValueError(f"unknown role(s) {bad}; each role "
                                 f"must be one of {ROLES}")
            if (ROLE_PREFILL in role_list
                    and all(r == ROLE_PREFILL for r in role_list)):
                raise ValueError(
                    "a prefill-only fleet cannot decode — at least "
                    "one replica needs role 'decode' or 'unified'")
        self._roles: List[str] = role_list
        # per-replica device slices (ISSUE 17 mesh-sharded serving):
        # one entry per replica — None (the process default device) or
        # an explicit device list the replica mesh-shards across.  The
        # router stays PLACEMENT-POLICY-ONLY: nothing downstream cares
        # what a replica spans — slices only size the replicas and the
        # fleet_replica_devices gauge.  Validated like roles, before
        # any replica is constructed; overlapping slices double-book a
        # chip's HBM and are refused.
        if devices is None:
            dev_list = [None] * self.n_replicas
        else:
            dev_list = [None if d is None else list(d) for d in devices]
            if len(dev_list) != self.n_replicas:
                raise ValueError(
                    f"devices has {len(dev_list)} slices for "
                    f"n_replicas={self.n_replicas}")
            seen = {}
            for i, slc in enumerate(dev_list):
                for d in (slc or ()):
                    key = (getattr(d, "platform", "?"),
                           getattr(d, "id", id(d)))
                    if key in seen:
                        raise ValueError(
                            f"device {key[0]}:{key[1]} appears in "
                            f"replica {seen[key]}'s and replica "
                            f"{i}'s slices — slices must be disjoint")
                    seen[key] = i
        self._devices: List = dev_list
        self.est_token_s = (float(est_token_s)
                            if est_token_s is not None else None)
        self.migration_retries = int(migration_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.poll_interval_s = float(poll_interval_s)
        self.dead_after_s = float(dead_after_s)
        # kept for elastic scale-out: add_replica() constructs
        # newcomers from the SAME net + config the founders got
        self._net = net
        self._server_kwargs = dict(server_kwargs)
        self._servers = [
            GenerationServer(net, **(dict(server_kwargs, devices=dev)
                                     if dev is not None
                                     else server_kwargs))
            for dev in dev_list]
        for i, dev in enumerate(dev_list):
            _REPL_DEVICES.labels(replica=str(i)).set(
                len(dev) if dev is not None else 1)
        # disagg classification bar: prompts at least this long (>= 2
        # full KV blocks by default) route through a prefill replica
        # when one is live; shorter prompts always go direct — their
        # prefill is too cheap to be worth a handoff round trip
        self.prefill_threshold = (
            int(prefill_threshold) if prefill_threshold is not None
            else 2 * self._servers[0].block_size + 1)
        self._acct = TenantAccountant(default_quota, quotas)
        # SLO error-budget engine (ISSUE 15): when attached (here or
        # via attach_slo), each dispatch pass reads its exhausted-
        # tenant set and demotes those tenants' waiting work WITHIN
        # its priority class — budget-exhausted batch traffic defers
        # before any interactive tenant would be shed
        self._slo = slo_engine
        # production front door (ISSUE 18).  admission_control=True
        # makes every submit consult the engine's SLO projection
        # BEFORE any reserve (admit / degrade / reject with retry-
        # after) — opt-in, because an attached engine alone must not
        # start reshaping fleets that only wanted dispatch-order
        # deferral.  The degradation ladder attaches via
        # attach_degrade and shapes admission whenever its rung > 0.
        self.admission_control = bool(admission_control)
        self._degrade = None
        # tail-latency hedging: a deadline-carrying interactive
        # request whose remaining budget falls under hedge_slack_s
        # duplicates onto a second warm replica (byte-identical
        # re-place, raced first-wins).  None disables.  hedge_budget
        # bounds concurrent hedges to a fraction of the flight — the
        # defense must not amplify the overload it defends against.
        self.hedge_slack_s = (None if hedge_slack_s is None
                              else float(hedge_slack_s))
        if self.hedge_slack_s is not None and self.hedge_slack_s <= 0:
            raise ValueError("hedge_slack_s must be > 0 (or None to "
                             "disable hedging)")
        self.hedge_budget = float(hedge_budget)
        if not 0.0 <= self.hedge_budget <= 1.0:
            raise ValueError("hedge_budget must be in [0, 1]")
        # fleet scheduler state: everything below mutates ONLY under
        # _lock (the GenerationServer discipline, one level up)
        self._lock = threading.RLock()
        self._intake: "queue.Queue" = queue.Queue(maxsize=int(queue_limit))
        self._waiting: List[_FleetRequest] = []
        self._inflight: List[_FleetRequest] = []
        self._dead = set()
        self._draining = set()
        self._joining = set()     # added replicas not yet dispatchable
        self._removed = set()     # scaled-in replicas (never candidates)
        self._unhealthy_since: Dict[int, float] = {}
        self._shutdown = False
        self._drain_mode = False
        _REPL_HEALTHY.set(self.n_replicas)
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # -- public API ----------------------------------------------------
    def submit_async(self, prompt_ids, n_new: int, tenant: str = "default",
                     eos_id: Optional[int] = None, seed: int = 0,
                     priority: int = 0,
                     deadline_s: Optional[float] = None,
                     sampling: Optional[dict] = None,
                     trace_id: Optional[str] = None) -> _FleetRequest:
        """Enqueue one request under ``tenant``'s quota; returns a
        handle whose ``result()`` blocks.  ``priority`` orders
        dispatch (lower = sooner); within a priority class requests
        dispatch earliest-deadline-first.  ``deadline_s`` bounds total
        residence (fleet queue wait included) and is feasibility-
        screened HERE — an unmeetable deadline raises
        :class:`DeadlineInfeasibleError` before any replica state is
        touched.  Structurally-unadmittable quota violations raise
        :class:`QuotaExceededError` the same way.

        ``trace_id`` CONTINUES an existing trace instead of minting
        one (ISSUE 13) — the cross-host handoff path: a request
        migrating in from another host's fleet keeps its origin trace
        id, its local root span is named ``request/handoff``, and the
        aggregator's ``FleetTraceStore`` stitches this host's
        fragment under the origin host's submit->retire root."""
        with self._lock:
            if self._shutdown:
                raise RuntimeError("ServingFleet has been shut down")
        prompt = np.asarray(prompt_ids, np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError("prompt_ids must be a non-empty 1-D int "
                             f"array, got shape {prompt.shape}")
        n_new = int(n_new)
        if n_new < 1:
            raise ValueError("n_new must be >= 1")
        max_len = self._servers[0].max_len
        if len(prompt) + n_new > max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + n_new ({n_new}) exceeds the "
                f"replica cache length ({max_len})")
        tenant = str(tenant)
        # production front door (ISSUE 18): the SLO projection and
        # the degradation ladder run BEFORE the reserve, the cost
        # computation and the feasibility screen — a reject burns no
        # quota, no blocks, no prefill, and the shaped (capped /
        # greedy) request is what everything downstream costs.
        with self._lock:
            slo = self._slo if self.admission_control else None
            ladder = self._degrade
        degraded = False
        if slo is not None and hasattr(slo, "admission_decision"):
            verdict = slo.admission_decision(tenant)
            if verdict["decision"] == "reject":
                _ADMIT_REJ.labels(tenant=tenant).inc()
                _REQS.labels(tenant=tenant,
                             outcome="rejected_slo").inc()
                raise AdmissionRejectedError(
                    tenant, verdict["retry_after_s"],
                    verdict["projected_burn"],
                    reason=f"SLO {verdict['slo']} projects the "
                           "budget overdraft deepening")
            if verdict["decision"] == "degrade":
                capped = max(1, n_new // 2)
                degraded = degraded or capped < n_new
                n_new = capped
        if ladder is not None:
            n_new, sampling, shape = ladder.shape_admission(
                tenant, n_new, sampling)
            if shape == "reject":
                _ADMIT_REJ.labels(tenant=tenant).inc()
                _REQS.labels(tenant=tenant,
                             outcome="rejected_slo").inc()
                raise AdmissionRejectedError(
                    tenant, ladder.shed_retry_after_s,
                    ladder.state()["burn"],
                    reason=f"degradation rung {ladder.rung()} sheds "
                           "the batch class")
            degraded = degraded or shape == "degraded"
        if ladder is not None or slo is not None:
            (_ADMIT_DEG if degraded else _ADMIT_OK).labels(
                tenant=tenant).inc()
        cost = float(len(prompt) + n_new)
        if deadline_s is not None:
            deadline_s = float(deadline_s)
            floor = (self.est_token_s or 0.0) * n_new
            if deadline_s <= 0 or floor > deadline_s:
                _REQS.labels(tenant=tenant,
                             outcome="rejected_deadline").inc()
                raise DeadlineInfeasibleError(
                    f"deadline_s={deadline_s:g} cannot be met: the "
                    f"decode-time floor for n_new={n_new} is "
                    f"{floor:g}s (est_token_s="
                    f"{self.est_token_s}) — rejected before burning "
                    "blocks")
        reason = self._acct.reserve_queued(tenant, cost)
        if reason is not None:
            _REQS.labels(tenant=tenant, outcome="rejected_quota").inc()
            raise QuotaExceededError(reason)
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        req = _FleetRequest(prompt, n_new,
                            None if eos_id is None else int(eos_id),
                            int(seed), sampling, tenant, int(priority),
                            cost, deadline)
        # the request's trace is born HERE: a root span covering the
        # whole fleet residence plus the admission phase, both tagged
        # with the minted trace id every later component (placement,
        # replica queue/prefill/decode) stamps its own spans with —
        # one submit -> retire tree per request in the trace viewer.
        # A handed-off request keeps its ORIGIN id and roots its local
        # fragment at request/handoff, so the fleet trace store sees
        # one tree, not two roots.
        tracer = telemetry.get_tracer()
        if trace_id is not None:
            req.trace_id = str(trace_id)
        req.spans["request"] = tracer.begin(
            "request" if trace_id is None else "request/handoff",
            trace=req.trace_id, tenant=tenant,
            n_new=n_new, priority=int(priority))
        req.spans["admission"] = tracer.begin(
            "request/admission", trace=req.trace_id, tenant=tenant)
        while True:
            try:
                self._intake.put(req, timeout=0.1)
                break
            except queue.Full:
                with self._lock:
                    down = self._shutdown
                if down:
                    self._acct.drop_queued(tenant)
                    for sp in (req.spans.pop(p, None)
                               for p in ("admission", "request")):
                        if sp is not None:
                            sp.end(outcome="rejected")
                    raise RuntimeError(
                        "ServingFleet has been shut down") from None
        with self._lock:
            dead = self._shutdown and not self._worker.is_alive()
        if dead:
            # raced shutdown(): the put may have landed after the
            # scheduler's final drain — fail leftovers ourselves
            self._fail_leftovers()
        return req

    def submit(self, prompt_ids, n_new: int, tenant: str = "default",
               eos_id: Optional[int] = None, seed: int = 0,
               priority: int = 0, timeout: Optional[float] = None,
               deadline_s: Optional[float] = None,
               sampling: Optional[dict] = None,
               retries: int = 0) -> np.ndarray:
        """Blocking ``submit_async().result()``.  ``retries``
        re-submits after a ``RetryableServerError`` (e.g. the whole
        fleet was momentarily unhealthy) or an
        ``AdmissionRejectedError`` through the existing ``retry_call``
        machinery with full-jitter backoff — an admission rejection's
        ``retry_after_s`` is honored as the FLOOR of the next sleep
        (the server-advised recovery slope outranks blind
        exponential; jitter still spreads callers above it)."""

        def attempt():
            return self.submit_async(
                prompt_ids, n_new, tenant=tenant, eos_id=eos_id,
                seed=seed, priority=priority, deadline_s=deadline_s,
                sampling=sampling).result(timeout)

        if retries <= 0:
            return attempt()
        return retry_call(
            attempt, retries=int(retries),
            base_delay=self.retry_backoff_s,
            retry_on=(RetryableServerError, AdmissionRejectedError),
            delay_floor=lambda e: getattr(e, "retry_after_s", 0.0),
            op="serving_fleet.submit")

    def drain(self, replica: int, hard: bool = False) -> None:
        """Roll ``replica`` out of dispatch: admission to it stops
        (placement never picks a draining replica) and its own
        admission closes (``GenerationServer.drain``).  Default: work
        already on it finishes there.  ``hard=True`` additionally
        MIGRATES its queued and in-flight requests to surviving
        replicas (each completes byte-identical to offline
        ``generate()`` — greedy decode is deterministic, so the
        survivor's from-scratch decode is the same bytes)."""
        idx = self._check_replica(replica)
        with self._lock:
            self._draining.add(idx)
        self._servers[idx].drain()
        if hard:
            self._mark_migrate(idx)
        self._wake()

    def kill(self, replica: int, timeout: float = 10.0) -> None:
        """SIGKILL-equivalent replica death (chaos drills and tests):
        the replica is marked dead, hard-stopped, and every request
        that was queued on or in flight at it migrates to surviving
        replicas and completes byte-identical to offline
        ``generate()``."""
        idx = self._check_replica(replica)
        with self._lock:
            already = idx in self._dead
            self._dead.add(idx)
        if not already:
            # the kill IS a crash drill: freeze the black box NOW,
            # while the victim's in-flight requests' spans are still
            # open — the bundle is the forensic record the migration
            # then outruns.  First kill only: a repeated kill of a
            # corpse must not bury the real crash bundle under an
            # empty post-recovery one.
            _FLIGHT.record("chaos_kill", replica=idx)
            _FLIGHT.request_dump(f"chaos_kill: replica {idx}")
        self._mark_migrate(idx)
        if not already:
            # hard stop: in-flight handles fail immediately (the
            # migration trigger); no graceful drain, like a real kill
            self._servers[idx].shutdown(drain=False, timeout=timeout)
        self._wake()

    def add_replica(self, role: str = ROLE_UNIFIED,
                    devices=None) -> int:
        """LIVE SCALE-OUT: construct one more replica from the fleet's
        founding ``net`` + server config and join it; returns its
        index.  ``role`` slots it into the disagg topology (default
        unified); ``devices`` gives the newcomer its own device slice
        (a scaled-out replica may span chips the founders did not —
        ONE fleet mixes single- and multi-chip replicas).  The
        newcomer enters the dispatch candidate set —
        and the prefix-affinity probe — only after its FIRST
        successful ``stats()`` (observed by the scheduler's health
        sweep): a replica still constructing must not catch traffic it
        cannot report on, and ``fleet_replicas_healthy`` only rises
        when it actually becomes dispatchable."""
        role = str(role)
        if role not in ROLES:
            raise ValueError(f"unknown role {role!r}; must be one of "
                             f"{ROLES}")
        with self._lock:
            if self._shutdown:
                raise RuntimeError("ServingFleet has been shut down")
        # constructed OUTSIDE the lock: replica construction allocates
        # the KV pool and may compile — the fleet must keep serving
        dev = None if devices is None else list(devices)
        srv = GenerationServer(
            self._net, **(dict(self._server_kwargs, devices=dev)
                          if dev is not None else self._server_kwargs))
        with self._lock:
            if self._shutdown:
                down = True
            else:
                down = False
                idx = len(self._servers)
                self._servers.append(srv)
                self._roles.append(role)
                self._devices.append(dev)
                self.n_replicas += 1
                self._joining.add(idx)
                _REPL_DEVICES.labels(replica=str(idx)).set(
                    len(dev) if dev is not None else 1)
        if down:
            srv.shutdown(drain=False)
            raise RuntimeError("ServingFleet has been shut down")
        log.info("ServingFleet: replica %d constructed; joins the "
                 "dispatch set after its first successful stats()", idx)
        self._wake()
        return idx

    def remove_replica(self, replica: int, timeout: float = 30.0) -> None:
        """LIVE SCALE-IN: roll ``replica`` out through the existing
        drain→migrate machinery — admission to it stops, its queued
        and in-flight requests re-place onto the survivors (completing
        byte-identical), and once its work has left, the underlying
        server stops.  The index stays allocated (indices are stable
        identities requests and telemetry reference) but never becomes
        a candidate again.  Unknown indices raise ``ValueError``."""
        idx = self._check_replica(replica)
        with self._lock:
            if idx in self._removed:
                return
            roles = list(self._roles)
            if roles[idx] != ROLE_PREFILL:
                # the constructor's >=1-decode-capable invariant must
                # survive scale-in too: removing the last live decode
                # replica would brick the fleet (a surviving prefill
                # replica cannot complete anything) — refuse, like the
                # role validation at construction
                others = [i for i in range(len(self._servers))
                          if i != idx and i not in self._removed
                          and i not in self._dead
                          and roles[i] != ROLE_PREFILL]
                if not others:
                    raise ValueError(
                        f"replica {idx} is the last live "
                        "decode-capable replica — removing it would "
                        "leave the fleet unable to decode (add a "
                        "decode/unified replica first)")
            self._removed.add(idx)
            self._joining.discard(idx)
        self.drain(idx, hard=True)
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            with self._lock:
                busy = any(r.replica == idx for r in self._inflight)
            if not busy:
                break
            time.sleep(self.poll_interval_s)
        try:
            self._servers[idx].shutdown(drain=False, timeout=timeout)
        except Exception:
            log.exception("removed replica %d shutdown failed", idx)
        self._wake()

    def attach_slo(self, engine) -> None:
        """Attach (or replace; None detaches) the SLO error-budget
        engine consulted by every dispatch pass (ISSUE 15) — see
        ``slo_engine=`` on the constructor."""
        with self._lock:
            self._slo = engine

    def attach_degrade(self, ladder) -> None:
        """Attach (or replace; None detaches) the degradation ladder
        (ISSUE 18): every admission is shaped through its current
        rung, and rung changes actuate through
        :meth:`apply_degrade`."""
        with self._lock:
            self._degrade = ladder

    def apply_degrade(self, max_n_new_factor: Optional[float] = None,
                      min_n_new: int = 1, force_greedy: bool = False,
                      draft_k_cap: Optional[int] = None,
                      spec: bool = True,
                      shed_tenants: Iterable[str] = ()) -> None:
        """Actuate one degradation-ladder policy on the LIVE fleet
        (new admissions are shaped separately, per request): cap the
        wait lines' ``n_new`` budgets, flip waiting work to greedy,
        cap each replica's speculative draft depth
        (``shrink_draft_k``), suspend/resume speculative decoding per
        replica, and shed the named tenants' waiting requests.
        Idempotent — the ladder calls it once per rung change with the
        FULL nested policy, so re-applying a rung is harmless."""
        shed = tuple(str(t) for t in shed_tenants)
        demoted = 0
        with self._lock:
            # wait-line demotion under the fleet lock: the dispatch
            # pass reads n_new/sampling/cost under the same lock, so
            # a request is either shaped HERE or dispatched with its
            # old budget — never half of each
            for req in self._waiting:
                if max_n_new_factor is not None:
                    capped = max(max(1, int(min_n_new)),
                                 int(req.n_new
                                     * float(max_n_new_factor)))
                    if capped < req.n_new:
                        req.n_new = capped
                        req.cost = float(len(req.prompt) + req.n_new)
                        demoted += 1
                if force_greedy:
                    temp = (req.sampling or {}).get("temperature",
                                                    None)
                    if temp is None or float(temp) > 0.0:
                        req.sampling = {"temperature": 0.0}
                        demoted += 1
            servers = list(self._servers)
            dead = set(self._dead) | set(self._removed)
        for i, srv in enumerate(servers):
            if i in dead:
                continue
            try:
                srv.set_spec_enabled(spec)
                srv.set_draft_k_cap(draft_k_cap)
                demoted += srv.demote_waiting(
                    n_new_factor=max_n_new_factor,
                    force_greedy=force_greedy)
            except Exception:
                log.exception("degrade actuation on replica %d "
                              "failed", i)
        if shed:
            demoted += self.demote_waiting(shed, cancel=True)
        if demoted:
            self._wake()

    def demote_waiting(self, tenants: Iterable[str],
                       priority: Optional[int] = None,
                       cancel: bool = False) -> int:
        """Load-shedding hooks for the autoscaler's batch-before-
        interactive policy, applied to the WAIT LINE only (in-flight
        work is never touched):

        * ``priority=N`` DEFERS: every waiting request of the named
          tenants whose priority is better (lower) than ``N`` is
          demoted to ``N``, so interactive traffic dispatches first
          while the batch work keeps its place in line;
        * ``cancel=True`` SHEDS: the named tenants' waiting requests
          are cancelled outright (their callers see
          ``CancelledError``; quota charges are refunded by the
          normal cancel accounting).

        Returns how many requests were demoted/cancelled."""
        tenants = {str(t) for t in tenants}
        hit: List[_FleetRequest] = []
        with self._lock:
            for req in self._waiting:
                if req.tenant not in tenants:
                    continue
                if cancel:
                    hit.append(req)
                elif priority is not None and req.priority < int(priority):
                    req.priority = int(priority)
                    hit.append(req)
        if cancel:
            for req in hit:
                req.cancel()
            if hit:
                self._wake()
        return len(hit)

    def stats(self) -> dict:
        """Fleet snapshot: per-replica ``GenerationServer.stats()``
        (plus fleet-side ``dead``/``draining``/``joining``/``removed``
        flags), wait-line and in-flight depths, dispatchable-replica
        count, and the per-tenant accounting view."""
        with self._lock:
            servers = list(self._servers)
            roles = list(self._roles)
            dead = set(self._dead)
            draining = set(self._draining)
            joining = set(self._joining)
            removed = set(self._removed)
            waiting = len(self._waiting)
            inflight = len(self._inflight)
        replicas = []
        for i, srv in enumerate(servers):
            st = srv.stats()
            st["role"] = roles[i]
            st["dead"] = i in dead
            st["draining"] = bool(st["draining"]) or i in draining
            st["joining"] = i in joining
            st["removed"] = i in removed
            replicas.append(st)
        healthy = sum(1 for st in replicas
                      if st["healthy"] and not st["dead"]
                      and not st["draining"] and not st["joining"]
                      and not st["removed"])
        return {"replicas": replicas, "waiting": waiting,
                "inflight": inflight, "healthy_replicas": healthy,
                "tenants": self._acct.snapshot()}

    def replica(self, idx: int) -> GenerationServer:
        """The underlying replica (tests / advanced introspection)."""
        return self._servers[self._check_replica(idx)]

    def shutdown(self, drain: bool = False, timeout: float = 30.0):
        """Stop the fleet.  Default: waiting and in-flight requests
        fail with RuntimeError.  ``drain=True``: admission closes but
        everything already submitted runs to completion (including
        any pending migrations) before the scheduler and the replicas
        exit."""
        with self._lock:
            self._drain_mode = bool(drain)
            self._shutdown = True
            worker = self._worker
        self._intake.put(None)
        worker.join(timeout=timeout)
        if worker.is_alive():
            log.warning("ServingFleet scheduler did not exit within "
                        "%.3gs (drain=%s); failing its in-flight "
                        "requests", timeout, drain)
            self._fail_all(RuntimeError(
                "ServingFleet shut down while the scheduler was "
                "unresponsive"))
        with self._lock:
            servers = list(self._servers)
        for i, srv in enumerate(servers):
            # dead replicas included: a kill() already shut its server
            # down (GenerationServer.shutdown is idempotent), but an
            # ORGANICALLY-dead one still owns a watchdog thread and
            # queued leftovers that must be stopped and failed — the
            # fleet marking it dead never stopped the server itself
            try:
                srv.shutdown(drain=drain, timeout=timeout)
            except Exception:
                log.exception("replica %d shutdown failed", i)
        self._fail_leftovers()
        _REPL_HEALTHY.set(0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # -- internals -----------------------------------------------------
    def _check_replica(self, idx: int) -> int:
        idx = int(idx)
        with self._lock:
            n = len(self._servers)
        if not 0 <= idx < n:
            raise ValueError(f"replica {idx} out of range [0, {n})")
        return idx

    def _wake(self) -> None:
        """Nudge a sleeping scheduler without enqueueing work."""
        try:
            self._intake.put_nowait(_WAKE)
        except queue.Full:
            pass                     # a full intake is awake already

    def _mark_migrate(self, idx: int) -> None:
        """Flag every in-flight request on ``idx`` for migration and
        cancel its replica-side handle (the handle failing is what
        hands the request back to the dispatch pass)."""
        with self._lock:
            victims = [r for r in self._inflight if r.replica == idx]
            for req in victims:
                req._migrate = True
            hedged = [r for r in self._inflight
                      if r.hedge_replica == idx]
        for req in victims:
            inner = req.inner
            if inner is not None:
                inner.cancel()
        for req in hedged:
            # the HEDGE placement died with the replica: resolve its
            # race — the primary races on alone
            self._drop_hedge(req)

    def _fail_leftovers(self) -> None:
        """Drain and fail intake entries once the scheduler is gone."""
        err = RuntimeError("ServingFleet shut down with the request "
                           "in flight")
        while True:
            try:
                item = self._intake.get_nowait()
            except queue.Empty:
                return
            if isinstance(item, _FleetRequest):
                self._acct.drop_queued(item.tenant)
                for sp in (item.spans.pop(p, None)
                           for p in ("admission", "request")):
                    if sp is not None:
                        sp.end(outcome="failed")
                item._error = err
                item._event.set()

    def _fail_all(self, err) -> None:
        with self._lock:
            victims = self._waiting + self._inflight
            self._waiting = []
            self._inflight = []
        for req in victims:
            self._drop_hedge(req, "failed")
            inner = req.inner
            if inner is not None:
                inner.cancel()
                inner.close_spans("failed")
            if req._quota_held:
                self._acct.release(req.tenant)
            else:
                self._acct.drop_queued(req.tenant)
            for sp in (req.spans.pop(p, None)
                       for p in ("admission", "request")):
                if sp is not None:
                    sp.end(outcome="failed")
            req._error = err
            req._event.set()
        _FLEET_QDEPTH.set(self._intake.qsize())

    def _finish(self, req: _FleetRequest, result=None, error=None,
                outcome: Optional[str] = None) -> None:
        """Terminal accounting for one request (already removed from
        the wait/flight lists by the caller)."""
        if req._quota_held:
            self._acct.release(req.tenant)
            if req._t_dispatch is None:
                # charged at the quota gate but never dispatched to
                # any replica: the tokens bought nothing — refund
                self._acct.refund(req.tenant, req.cost)
        else:
            self._acct.drop_queued(req.tenant)
        if outcome:
            _REQS.labels(tenant=req.tenant, outcome=outcome).inc()
        # the handle outlives the request (callers hold it for
        # .ttft/.replica): drop the exported K/V payload now — its
        # re-import-on-migration purpose ends at terminal state
        req.handoff = None
        if error is not None:
            req._error = error
        else:
            req._result = result
            inner = req.inner
            if (req._t_dispatch is not None and inner is not None
                    and inner.ttft is not None):
                req.ttft = (req._t_dispatch - req.t_submit) + inner.ttft
        # close the request's remaining trace spans (root span
        # included) wherever this runs — the scheduler thread normally,
        # but also shutdown/teardown paths; cross-thread end is what
        # the tracked-span API exists for
        final = outcome or ("ok" if error is None else "error")
        for sp in (req.spans.pop(p, None)
                   for p in ("admission", "request")):
            if sp is not None:
                sp.end(outcome=final)
        _PHASE.labels(phase="total").observe(
            time.perf_counter() - req.t_submit)
        req._event.set()

    # -- scheduler passes (scheduler thread only) ----------------------
    def _ingest(self, item, stop: bool) -> bool:
        """Returns the updated stop flag."""
        if item is None:
            return True
        if item is _WAKE:
            return stop
        with self._lock:
            self._waiting.append(item)
        return stop

    def _sweep_health(self, now: float) -> None:
        """Declare replicas dead after ``dead_after_s`` of continuous
        unhealthiness (a watchdog recovery flickers for milliseconds —
        that must not trigger a migration storm), trigger migration
        for their in-flight work, and promote JOINING replicas into
        the dispatch set on their first successful ``stats()``."""
        with self._lock:
            servers = list(self._servers)
            joining = sorted(self._joining)
        for i in joining:
            # the join gate: one successful lock-consistent snapshot
            # proves the newcomer can answer the placement questions
            # (free blocks, warmth) dispatch will ask it
            try:
                st = servers[i].stats()
            except Exception:       # pragma: no cover - defensive
                continue
            if st["healthy"]:
                with self._lock:
                    self._joining.discard(i)
                log.info("ServingFleet: replica %d reported healthy "
                         "stats — joined the dispatch set", i)
        newly_dead = []
        for i, srv in enumerate(servers):
            with self._lock:
                if i in self._dead or i in self._removed \
                        or i in self._joining:
                    continue
            if srv.healthy():
                with self._lock:
                    self._unhealthy_since.pop(i, None)
                continue
            with self._lock:
                t0 = self._unhealthy_since.setdefault(i, now)
                if now - t0 >= self.dead_after_s:
                    self._dead.add(i)
                    newly_dead.append(i)
        for i in newly_dead:
            log.warning("ServingFleet: replica %d unhealthy for "
                        ">= %.3gs — declaring it dead and migrating "
                        "its requests", i, self.dead_after_s)
            self._mark_migrate(i)
        with self._lock:
            n_up = sum(1 for i in range(len(self._servers))
                       if i not in self._dead
                       and i not in self._draining
                       and i not in self._removed
                       and i not in self._joining
                       and i not in self._unhealthy_since)
        _REPL_HEALTHY.set(n_up)

    def _reap_waiting(self, now: float) -> None:
        """Cancelled / deadline-expired requests leave the wait line."""
        with self._lock:
            keep, victims = [], []
            for req in self._waiting:
                if req.cancelled:
                    victims.append((req, "cancelled", CancelledError(
                        "fleet request cancelled")))
                elif req.deadline is not None and now > req.deadline:
                    victims.append((req, "expired",
                                    DeadlineExceededError(
                                        "fleet request deadline "
                                        "elapsed before dispatch")))
                else:
                    keep.append(req)
            self._waiting = keep
        for req, outcome, err in victims:
            self._finish(req, error=err, outcome=outcome)

    def _count_queued(self, req: _FleetRequest) -> None:
        """First wait — quota OR capacity — counts the queued outcome
        (once per request; the label means 'waited >= 1 pass')."""
        if not req._queued_counted:
            req._queued_counted = True
            _REQS.labels(tenant=req.tenant, outcome="queued").inc()

    def _dispatch_pass(self, now: float) -> int:
        """Walk the wait line in (priority, deadline, arrival) order
        and dispatch everything quota + capacity allow.  Returns the
        number dispatched.

        Cost discipline: the quota gate runs FIRST (a blocked
        tenant's backlog must cost zero replica traffic), and replica
        ``stats()`` snapshots are taken ONCE per pass — a long wait
        line must not hammer every replica's lock per request.
        Intra-pass dispatches fold back in via ``extra_load`` so
        least-loaded placement still spreads within one pass; only
        the per-request prefix-warmth probe touches a replica per
        waiting request, and only after its quota cleared.

        DISAGG classification (ISSUE 14) happens here, where the
        router already costs the prompt: a request whose prompt is at
        least ``prefill_threshold`` tokens — and whose prefix is not
        already warm on a decode-capable replica — stages through a
        prefill-role replica first (``stage="prefill"``); everything
        else decodes direct.  Prefill replicas never take decode
        traffic, decode replicas never take prefill stages, unified
        replicas take only decode/direct traffic (a unified replica
        IS its own prefill)."""
        budget_deferred: List[str] = []
        with self._lock:
            if not self._waiting:
                return 0
            # SLO budget defer (ISSUE 15): tenants whose error budget
            # is exhausted sort BEHIND within-budget tenants of the
            # same priority class — their backlog waits out the burn
            # instead of forcing the autoscaler to shed interactive
            # work.  The engine lock is a leaf (it never calls back
            # into the fleet), so the nested read cannot deadlock.
            slo = self._slo
            exhausted = (slo.exhausted_tenants()
                         if slo is not None else frozenset())
            if exhausted:
                for req in self._waiting:
                    if req.tenant in exhausted \
                            and not req._budget_deferred:
                        req._budget_deferred = True
                        budget_deferred.append(req.tenant)
            line = sorted(self._waiting,
                          key=lambda r: (r.priority,
                                         r.tenant in exhausted,
                                         r.deadline if r.deadline
                                         is not None else _INF,
                                         r.t_submit_m))
            n = len(self._servers)
            roles = list(self._roles)
            # terminal only when nothing can EVER take the work: every
            # non-removed DECODE-CAPABLE replica is dead and no
            # newcomer is joining (a fleet of surviving prefill-only
            # replicas cannot complete anything either)
            all_dead = (not self._joining
                        and all(i in self._dead or i in self._removed
                                or roles[i] == ROLE_PREFILL
                                for i in range(n)))
            cand = [i for i in range(n)
                    if i not in self._dead and i not in self._draining
                    and i not in self._removed
                    and i not in self._joining]
        for t in budget_deferred:
            _SLO_DEFER.labels(tenant=t).inc()
        pre_cand = [i for i in cand if roles[i] == ROLE_PREFILL]
        base, pbase = {}, {}
        for i in cand:
            st = self._servers[i].stats()
            if st["healthy"] and not st["draining"]:
                (pbase if roles[i] == ROLE_PREFILL else base)[i] = st
        extra_load = {i: 0 for i in (*base, *pbase)}
        extra_blocks = {i: 0 for i in (*base, *pbase)}
        # blocks claimed this pass (free_blocks is a snapshot —
        # without the compensation, one stale count piles a whole
        # burst onto one replica)
        n_dispatched = 0
        for req in line:
            if now < req._not_before:
                continue             # migration backoff
            if req.cancelled or (req.deadline is not None
                                 and now > req.deadline):
                continue             # next reap pass collects it
            if all_dead:
                with self._lock:
                    if req in self._waiting:
                        self._waiting.remove(req)
                self._finish(req, error=NoHealthyReplicaError(
                    "every decode-capable fleet replica is dead — "
                    "the request was never applied; safe to retry"),
                    outcome="failed")
                continue
            if not req._quota_held:
                if not self._acct.try_dispatch(req.tenant, req.cost,
                                               now):
                    self._count_queued(req)
                    continue
                req._quota_held = True
            warmths = None           # classification probes, reused
                                     # by the views below (one hash
                                     # walk per replica per request)
            if req.stage is None:
                req.stage = "decode"
                if (pre_cand and req.handoff is None
                        and len(req.prompt) >= self.prefill_threshold):
                    # block_size is a static server attribute (all
                    # replicas share the founding kwargs) — deriving
                    # it from the healthy-stats snapshot would stamp
                    # a long prompt "decode" forever during a pass
                    # where no replica happened to be dispatchable
                    full = ((len(req.prompt) - 1)
                            // self._servers[0].block_size)
                    warmths = {i: self._servers[i].prefix_warmth(
                        req.prompt) for i in base}
                    # an already-warm decode replica beats a handoff:
                    # its admission maps the blocks copy-free, so the
                    # prefill stage would buy nothing
                    if full > 0 and max(warmths.values(),
                                        default=0) < full:
                        req.stage = "prefill"
            if req.stage == "prefill" and not pbase:
                if pre_cand:
                    # prefill replicas exist but none is dispatchable
                    # this pass (recovering): wait, don't stall decode
                    # replicas with a long prefill
                    self._count_queued(req)
                    continue
                req.stage = "decode"     # none left: decode direct
            if req.stage == "prefill":
                pool = pbase
            else:
                pool = base
            if not pool:
                # capacity wait: every candidate draining/recovering
                self._count_queued(req)
                continue
            if warmths is None or pool is not base:
                warmths = {i: self._servers[i].prefix_warmth(
                    req.prompt) for i in pool}
            views = [{"idx": i,
                      "warmth": warmths[i],
                      "free_blocks": (st["free_blocks"]
                                      - extra_blocks[i]),
                      "load": (st["live_slots"] + st["queue_depth"]
                               + extra_load[i]),
                      "spec_k": st.get("spec_k", 0),
                      "spec_acceptance": st.get(
                          "spec_acceptance_rate", 0.0)}
                     for i, st in pool.items()]
            refused = set()
            status, idx = self._place(req, views, refused)
            for i in refused:
                # a refusing replica (raced drain/shutdown) refuses
                # everyone: stop re-attempting it this pass
                pool.pop(i, None)
            if status == "placed":
                extra_load[idx] += 1
                bs = pool[idx]["block_size"]
                n_toks = len(req.prompt) + (
                    0 if req.stage == "prefill" else req.n_new)
                blocks = -(-n_toks // bs)
                if pool[idx].get("spec_k", 0) \
                        and req.stage != "prefill":
                    # a speculative replica pins the draft's table too
                    # — without the 2x the intra-pass compensation
                    # under-counts and a burst piles onto the replica.
                    # Prefill-ONLY admissions claim no draft table
                    # (generation_server skips dneed), so they stay 1x
                    blocks *= 2
                extra_blocks[idx] += blocks
                n_dispatched += 1
            elif status == "refused":
                self._count_queued(req)
        return n_dispatched

    def _place(self, req: _FleetRequest, views: List[dict],
               refused_out: Optional[set] = None):
        """Dispatch ``req`` onto the best candidate in ``views``
        (falling down the ranking when a replica refuses — raced
        drain/shutdown; refusers are reported through ``refused_out``
        so a pass can stop re-attempting them).  Returns
        ``("placed", replica_idx)``, ``("refused", None)`` when every
        candidate refused, or ``("failed", None)`` when the request
        terminally failed."""
        views = list(views)
        prefill_stage = req.stage == "prefill"
        sp_place = telemetry.get_tracer().begin(
            "request/placement", trace=req.trace_id,
            candidates=len(views), stage=req.stage or "decode")
        t_place = time.perf_counter()
        while views:
            idx, reason = choose_replica(views)
            if req._migrating:
                reason = FAILOVER
            elif prefill_stage:
                reason = PREFILL
            elif req.handoff is not None:
                reason = HANDOFF
            srv = self._servers[idx]
            remaining = (None if req.deadline is None
                         else max(req.deadline - time.monotonic(),
                                  1e-3))
            try:
                if prefill_stage:
                    # disagg stage 1: chunked prefill into the prefill
                    # replica's pool; the handoff export happens when
                    # the handle resolves (completion pass)
                    inner = srv.prefill_async(
                        req.prompt, deadline_s=remaining,
                        trace_id=req.trace_id)
                else:
                    if req.handoff is not None:
                        # disagg stage 2: land the exported prefix in
                        # THIS replica before its admission runs, so
                        # the chain walk restores it (one batched H2D)
                        # instead of re-prefilling.  A failed import
                        # only costs a cold prefill, never the request.
                        try:
                            srv.import_blocks(req.handoff)
                        except Exception:
                            log.exception(
                                "handoff import into replica %d "
                                "failed; decoding cold", idx)
                    inner = srv.submit_async(
                        req.prompt, req.n_new, eos_id=req.eos_id,
                        seed=req.seed, deadline_s=remaining,
                        sampling=req.sampling, trace_id=req.trace_id,
                        tenant=req.tenant)
            except RuntimeError:
                # raced into a draining/shutdown replica: drop it from
                # the candidate ranking and try the next one
                if refused_out is not None:
                    refused_out.add(idx)
                views = [v for v in views if v["idx"] != idx]
                continue
            except Exception as e:
                sp_place.end(outcome="failed")
                with self._lock:
                    if req in self._waiting:
                        self._waiting.remove(req)
                self._finish(req, error=e, outcome="failed")
                return "failed", None
            with self._lock:
                if req in self._waiting:
                    self._waiting.remove(req)
                req.inner = inner
                req.replica = idx
                req._migrate = False
                self._inflight.append(req)
            first = req._t_dispatch is None
            req._t_dispatch = time.perf_counter()
            _FLIGHT.record("dispatch", replica=idx, reason=reason,
                           trace=req.trace_id, tenant=req.tenant,
                           stage=req.stage or "decode")
            sp_place.end(replica=idx, reason=reason)
            _PHASE.labels(phase="placement").observe(
                req._t_dispatch - t_place)
            if first:
                wait = req._t_dispatch - req.t_submit
                _QWAIT.labels(tenant=req.tenant).observe(wait)
                _PHASE.labels(phase="admission").observe(wait)
                sp_adm = req.spans.pop("admission", None)
                if sp_adm is not None:
                    sp_adm.end(replica=idx)
            if req.deadline is not None:
                # EDF slack at dispatch: the SLO headroom the fleet
                # still has for this request — the autoscaler's
                # earliest-collapsing pressure signal
                _EDF_SLACK.labels(tenant=req.tenant).observe(
                    max(0.0, req.deadline - time.monotonic()))
            _DISPATCH.labels(replica=str(idx), reason=reason).inc()
            if req._migrating:
                req._migrating = False
                _REQS.labels(tenant=req.tenant,
                             outcome="migrated").inc()
            elif first:
                _REQS.labels(tenant=req.tenant,
                             outcome="admitted").inc()
            else:
                # the decode stage of a disagg request already counted
                # admitted at its prefill placement — one request, one
                # admitted outcome; the handoff gets its own label
                _REQS.labels(tenant=req.tenant,
                             outcome="handed_off").inc()
            if req.cancelled:
                inner.cancel()       # raced a cancel mid-placement
            return "placed", idx
        sp_place.end(outcome="refused")
        return "refused", None       # every candidate refused

    def _drop_hedge(self, req: _FleetRequest,
                    outcome: str = "cancelled") -> None:
        """Resolve a hedge race AGAINST the hedge (the primary won,
        or the request went terminal/migrating): detach the hedge
        handle, cancel it, flush its replica-side spans, and count
        the resolution — exactly one ``fleet_hedges_cancelled_total``
        per resolved race, whichever side lost."""
        with self._lock:
            hedge = req.hedge_inner
            req.hedge_inner = None
            req.hedge_replica = None
        if hedge is None:
            return
        hedge.cancel()
        hedge.close_spans(outcome)
        _HEDGE_CANCEL.inc()

    def _hedge_pass(self, now: float) -> int:
        """Tail-latency hedging (ISSUE 18): duplicate each
        near-deadline interactive decode onto a second warm replica —
        the SAME prompt/n_new/seed/sampling, so greedy decode makes
        the two placements byte-identical and first-completion-wins
        is a pure latency race.  Bounded by ``hedge_budget`` (a
        fraction of the current flight) so hedging cannot amplify the
        overload it defends against.  Returns hedges launched."""
        if self.hedge_slack_s is None:
            return 0
        with self._lock:
            flight = list(self._inflight)
            n_hedged = sum(1 for r in flight
                           if r.hedge_inner is not None)
            roles = list(self._roles)
            cand = [i for i in range(len(self._servers))
                    if i not in self._dead
                    and i not in self._draining
                    and i not in self._removed
                    and i not in self._joining
                    and roles[i] != ROLE_PREFILL]
        budget = max(1, int(self.hedge_budget * len(flight)))
        launched = 0
        stats_cache: Dict[int, dict] = {}
        for req in flight:
            if n_hedged + launched >= budget:
                break
            if (req.hedge_inner is not None or req.deadline is None
                    or req.priority > 0 or req.cancelled
                    or req._migrate or req.stage == "prefill"
                    or req.inner is None or req.inner.done()):
                continue
            remaining = req.deadline - now
            if remaining <= 0 or remaining >= self.hedge_slack_s:
                continue
            targets = []
            for i in cand:
                if i == req.replica:
                    continue
                st = stats_cache.get(i)
                if st is None:
                    try:
                        st = self._servers[i].stats()
                    except Exception:
                        continue
                    stats_cache[i] = st
                if st["healthy"] and not st["draining"]:
                    targets.append((-(st["free_blocks"]
                                      - st["queue_depth"]), i))
            if not targets:
                continue
            tgt = min(targets)[1]       # most free blocks, least queue
            srv = self._servers[tgt]
            rem = max(req.deadline - time.monotonic(), 1e-3)
            try:
                hedge = srv.submit_async(
                    req.prompt, req.n_new, eos_id=req.eos_id,
                    seed=req.seed, deadline_s=rem,
                    sampling=req.sampling, trace_id=req.trace_id,
                    tenant=req.tenant)
            except Exception:
                continue             # raced drain/shutdown: no hedge
            committed = False
            with self._lock:
                if (req in self._inflight and req.hedge_inner is None
                        and not req._migrate and not req.cancelled):
                    req.hedge_inner = hedge
                    req.hedge_replica = tgt
                    req._t_hedge = time.perf_counter()
                    committed = True
            if not committed:
                # the primary resolved (or went terminal) between the
                # snapshot and the launch: the race is void
                hedge.cancel()
                hedge.close_spans("cancelled")
                continue
            _HEDGE_LAUNCH.inc()
            _FLIGHT.record("hedge", trace=req.trace_id,
                           tenant=req.tenant, primary=req.replica,
                           replica=tgt,
                           remaining_s=round(remaining, 4))
            launched += 1
        return launched

    def _completion_pass(self, now: float) -> int:
        """Resolve finished replica-side handles: deliver results,
        propagate terminal errors, and REQUEUE migration candidates
        (dead/hard-drained replica, or a retryable server failure)
        with jittered backoff.  A hedged request resolves FIRST-WINS:
        whichever placement finishes first delivers its bytes and the
        loser is cancelled.  Returns the number resolved."""
        with self._lock:
            flight = list(self._inflight)
        n_done = 0
        for req in flight:
            inner = req.inner
            hedge = req.hedge_inner
            if (hedge is not None and hedge.done()
                    and not (inner is not None and inner.done())):
                herr = None
                try:
                    hres = hedge.result(timeout=1.0)
                except BaseException as e:
                    herr, hres = e, None
                if herr is None:
                    # the hedge WON: adopt its placement (ttft re-
                    # based on the hedge launch — the caller's wait
                    # really did end with the hedge's first token),
                    # cancel the primary, deliver
                    with self._lock:
                        if req in self._inflight:
                            self._inflight.remove(req)
                        req.inner = hedge
                        req.replica = req.hedge_replica
                        req.hedge_inner = None
                        req.hedge_replica = None
                        if req._t_hedge is not None:
                            req._t_dispatch = req._t_hedge
                    if inner is not None:
                        inner.cancel()
                        inner.close_spans("cancelled")
                    _HEDGE_WON.inc()
                    _HEDGE_CANCEL.inc()
                    _FLIGHT.record("hedge_won", trace=req.trace_id,
                                   replica=req.replica)
                    self._finish(req, result=hres)
                    n_done += 1
                    continue
                # the hedge died (its replica drained/expired it):
                # the primary races on alone — resolve the race
                # against the hedge
                with self._lock:
                    req.hedge_inner = None
                    req.hedge_replica = None
                hedge.close_spans("failed")
                _HEDGE_CANCEL.inc()
                hedge = None
            if inner is None or not inner.done():
                if req._migrate:
                    # the placement is GONE (dead replica or hard
                    # drain): do not wait for a scheduler that may
                    # never resolve the cancelled handle — a kill()
                    # fails handles via shutdown, but an organically-
                    # dead scheduler resolves nothing, ever.  Abandon
                    # the old handle and requeue (or finish) now.
                    n_done += self._abandon_placement(req, now)
                continue
            n_done += 1
            err = None
            try:
                result = inner.result(timeout=1.0)
            except BaseException as e:
                err, result = e, None
            if err is None:
                if req.stage == "prefill":
                    # disagg stage 1 finished: export the prefix off
                    # the prefill replica and requeue for the decode
                    # stage (NOT a migration — no backoff, and the
                    # cancelled/expired cases fall to the next reap)
                    self._hand_off(req)
                    continue
                # the primary won any hedge race: cancel the hedge
                self._drop_hedge(req)
                with self._lock:
                    if req in self._inflight:
                        self._inflight.remove(req)
                self._finish(req, result=result)
                continue
            # error classification
            if isinstance(err, CancelledError) and req.cancelled:
                self._remove_and_finish(req, err, "cancelled")
            elif isinstance(err, DeadlineExceededError):
                self._remove_and_finish(req, err, "expired")
            elif self._migratable(req, err, now):
                if hedge is not None:
                    # the hedge IS the migration: promote the live
                    # second placement instead of re-placing from
                    # scratch (no backoff, no lost progress) — the
                    # race resolves against the dead primary
                    with self._lock:
                        req.inner = hedge
                        req.replica = req.hedge_replica
                        req.hedge_inner = None
                        req.hedge_replica = None
                        req._migrate = False
                        if req._t_hedge is not None:
                            req._t_dispatch = req._t_hedge
                    inner.close_spans("abandoned")
                    _HEDGE_CANCEL.inc()
                    _FLIGHT.record("hedge_promote",
                                   trace=req.trace_id,
                                   replica=req.replica)
                else:
                    self._requeue(req, now)
            else:
                self._remove_and_finish(req, err, "failed")
        return n_done

    def _hand_off(self, req: _FleetRequest) -> None:
        """Disagg stage transition: the prefill replica finished, so
        export its registered prefix blocks (raw bytes + chain
        hashes) and send the request back to the wait line as a
        decode-stage request carrying the payload.  An export that
        fails (replica dying under us) degrades to an empty handoff —
        the decode replica re-prefills, byte-identically."""
        payload = None
        try:
            # short dirty-read budget: this runs ON the fleet
            # scheduler thread, so a long retry loop would stall
            # every tenant's dispatch behind one handoff — an export
            # that can't read a committed pool quickly degrades to an
            # empty payload (decode re-prefills, byte-identically).
            # On a prefill-ONLY replica the scheduler idles right
            # after the retire, so the first read is normally clean.
            payload = self._servers[req.replica].export_prefix(
                req.prompt, max_wait_s=0.25)
        except Exception:
            log.exception("prefix export off replica %s failed; the "
                          "decode stage will re-prefill", req.replica)
        _FLIGHT.record("handoff", trace=req.trace_id,
                       off_replica=req.replica,
                       blocks=len(payload or ()))
        with self._lock:
            if req in self._inflight:
                self._inflight.remove(req)
            req.prefill_replica = req.replica
            req.inner = None
            req.replica = None
            req._migrate = False
            req.stage = "decode"
            req.handoff = payload or None
            self._waiting.append(req)

    def _abandon_placement(self, req: _FleetRequest,
                           now: float) -> int:
        """A migrating request whose replica-side handle may never
        resolve: drop the handle and requeue — unless the caller no
        longer wants it, its deadline is spent, or its migration
        budget is exhausted (terminal then).  Returns 1 (the request
        always moves somewhere — progress for the pacing loop)."""
        if req.cancelled:
            self._remove_and_finish(req, CancelledError(
                "fleet request cancelled"), "cancelled")
        elif req.deadline is not None and now > req.deadline:
            self._remove_and_finish(req, DeadlineExceededError(
                "fleet request deadline elapsed while its replica "
                "was dying"), "expired")
        elif req.migrations >= self.migration_retries:
            self._remove_and_finish(req, NoHealthyReplicaError(
                "request exhausted its migration budget on dying "
                "replicas — it was never applied; safe to retry"),
                "failed")
        else:
            self._requeue(req, now)
        return 1

    def _remove_and_finish(self, req: _FleetRequest, err,
                           outcome: str) -> None:
        self._drop_hedge(req, outcome)
        inner = req.inner
        if inner is not None:
            # terminal abandon paths included: a dying replica's
            # unresolved handle still flushes its spans (idempotent
            # when the replica retired it first)
            inner.close_spans(outcome)
        with self._lock:
            if req in self._inflight:
                self._inflight.remove(req)
        self._finish(req, error=err, outcome=outcome)

    def _migratable(self, req: _FleetRequest, err, now: float) -> bool:
        """A failed in-flight request migrates when the failure was
        the REPLICA's fault (marked for migration, replica dead or
        unhealthy, or a typed retryable failure), the caller still
        wants it, its deadline still has budget, and the migration
        bound has room.  The request was never partially applied —
        the survivor re-decodes from scratch, byte-identically."""
        if req.cancelled:
            return False
        if req.deadline is not None and now > req.deadline:
            return False
        if req.migrations >= self.migration_retries:
            return False
        if req._migrate:
            return True
        with self._lock:
            replica_gone = (req.replica in self._dead
                            or req.replica in self._unhealthy_since)
        if replica_gone and isinstance(err, (RetryableServerError,
                                             RuntimeError,
                                             CancelledError)):
            return True
        # healthy replica, typed retryable failure (watchdog recovery
        # dropped the slot): same re-placement path, still bounded
        return isinstance(err, RetryableServerError)

    def _requeue(self, req: _FleetRequest, now: float) -> None:
        self._drop_hedge(req, "abandoned")
        req.migrations += 1
        _FLIGHT.record("migrate", trace=req.trace_id,
                       tenant=req.tenant, off_replica=req.replica,
                       migrations=req.migrations)
        delay = backoff_delay(req.migrations - 1,
                              self.retry_backoff_s, 1.0)
        inner = req.inner
        if inner is not None:
            # the abandoned placement's replica-side spans must flush
            # NOW: a dead replica's scheduler will never retire them
            # (idempotent no-op when the replica did retire first)
            inner.close_spans("abandoned")
        with self._lock:
            if req in self._inflight:
                self._inflight.remove(req)
            req.inner = None
            req.replica = None
            req._migrate = False
            req._migrating = True
            req._not_before = now + delay
            # re-classify on the next pass: the replica set changed
            # (a killed prefill replica's request may go direct; a
            # held handoff payload keeps its decode-stage fast path)
            req.stage = None
            self._waiting.append(req)

    def _run(self) -> None:
        stop = False
        while True:
            with self._lock:
                idle = not self._waiting and not self._inflight
            if idle and not stop:
                stop = self._ingest(self._intake.get(), stop)
            while True:                       # opportunistic drain
                try:
                    item = self._intake.get_nowait()
                except queue.Empty:
                    break
                stop = self._ingest(item, stop)
            with self._lock:
                drain_mode = self._drain_mode
            if stop and not drain_mode:
                self._fail_all(RuntimeError(
                    "ServingFleet shut down with the request in "
                    "flight"))
                _FLEET_QDEPTH.set(0)
                return
            if stop:
                with self._lock:
                    done = not self._waiting and not self._inflight
                if done and self._intake.empty():
                    _FLEET_QDEPTH.set(0)
                    return
            try:
                now = time.monotonic()
                self._sweep_health(now)
                self._reap_waiting(now)
                n_disp = self._dispatch_pass(now)
                n_hedge = self._hedge_pass(now)
                n_done = self._completion_pass(now)
                with self._lock:
                    busy = bool(self._waiting or self._inflight)
                    depth = len(self._waiting)
                _FLEET_QDEPTH.set(depth + self._intake.qsize())
                if busy and not (n_disp or n_hedge or n_done) \
                        and not stop:
                    # nothing moved: sleep ON the intake so a new
                    # submit / wake nudge cuts the latency short
                    try:
                        stop = self._ingest(
                            self._intake.get(
                                timeout=self.poll_interval_s), stop)
                    except queue.Empty:
                        pass
            except Exception:
                # the fleet scheduler must not die of one bad pass —
                # log, breathe, keep serving (replica-side failures
                # already have their own watchdog story)
                log.exception("ServingFleet scheduler pass failed")
                time.sleep(0.05)
