"""Per-tenant admission accounting: token buckets + concurrency caps.

Multi-tenant fairness is an ADMISSION property, not a scheduling
nicety: one hot tenant spraying requests at an uncapped fleet owns
every KV block within seconds and everyone else's TTFT is its queue.
The accountant meters three things per tenant, all host-side and
cheap:

* a **token bucket** over request cost (prompt + budget tokens —
  the tokens the fleet will actually process): sustained rate
  ``tokens_per_s``, capacity ``burst_tokens``.  Over-rate traffic
  WAITS for refill (it is not an error to be briefly hot); a request
  whose cost exceeds the burst outright can never pass and is
  rejected immediately (:class:`~.errors.QuotaExceededError`);
* a **concurrency cap** (``max_concurrent``): dispatched-and-
  unfinished requests — the knob that bounds how many of the fleet's
  slots/blocks one tenant can pin at once;
* a **queue cap** (``max_queued``): waiting requests beyond it are
  rejected instead of building an unbounded backlog (the bounded-
  retry rule from ``resilience.retry``, applied to queues).

The accountant is its own small lock domain — it never calls into a
replica or the router while holding its lock, so lock ordering across
the fleet stays trivial (router lock and accountant lock never nest
the other way).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

_INF = float("inf")


class TenantQuota:
    """One tenant's admission limits (immutable config; the default
    constructed with no arguments is unlimited — the single-tenant
    degenerate where the fleet behaves like a bare server pool)."""

    __slots__ = ("tokens_per_s", "burst_tokens", "max_concurrent",
                 "max_queued", "klass")

    #: admission classes the degradation ladder dispatches on:
    #: ``batch`` work is sheddable at rung 4, ``interactive`` never is
    CLASSES = ("interactive", "batch")

    def __init__(self, tokens_per_s: float = _INF,
                 burst_tokens: Optional[float] = None,
                 max_concurrent: Optional[int] = None,
                 max_queued: Optional[int] = None,
                 klass: str = "interactive"):
        self.tokens_per_s = float(tokens_per_s)
        if self.tokens_per_s < 0:
            raise ValueError("tokens_per_s must be >= 0")
        if burst_tokens is None:
            # default capacity: 4 seconds of sustained rate — enough
            # that a well-behaved tenant's bursts ride through, small
            # enough that a hot one cannot bank minutes of tokens
            burst_tokens = (self.tokens_per_s * 4.0
                            if self.tokens_per_s != _INF else _INF)
        self.burst_tokens = float(burst_tokens)
        if self.burst_tokens <= 0:
            raise ValueError("burst_tokens must be > 0")
        self.max_concurrent = (None if max_concurrent is None
                               else int(max_concurrent))
        if self.max_concurrent is not None and self.max_concurrent < 1:
            raise ValueError("max_concurrent must be >= 1")
        self.max_queued = (None if max_queued is None
                           else int(max_queued))
        if self.max_queued is not None and self.max_queued < 0:
            raise ValueError("max_queued must be >= 0")
        self.klass = str(klass)
        if self.klass not in self.CLASSES:
            raise ValueError(f"klass={klass!r} must be one of "
                             f"{self.CLASSES}")

    def __repr__(self):
        return (f"TenantQuota(tokens_per_s={self.tokens_per_s}, "
                f"burst_tokens={self.burst_tokens}, "
                f"max_concurrent={self.max_concurrent}, "
                f"max_queued={self.max_queued}, "
                f"klass={self.klass!r})")


class _Bucket:
    """One tenant's live accounting state (mutated only under the
    accountant's lock)."""

    __slots__ = ("level", "last", "concurrent", "queued")

    def __init__(self, quota: TenantQuota, now: float):
        self.level = quota.burst_tokens      # buckets start full
        self.last = now
        self.concurrent = 0
        self.queued = 0


class TenantAccountant:
    """Thread-safe per-tenant token buckets + concurrency/queue caps.

    The router calls :meth:`reserve_queued` at intake (structural
    rejects happen here, before the request ever waits),
    :meth:`try_dispatch` each scheduling pass (False = keep waiting —
    the bucket refills or a concurrent slot frees), and
    :meth:`release` when a dispatched request finishes however it
    finishes.  Unknown tenants get ``default_quota``."""

    def __init__(self, default_quota: Optional[TenantQuota] = None,
                 quotas: Optional[Dict[str, TenantQuota]] = None):
        self._lock = threading.Lock()
        self._default = default_quota or TenantQuota()
        self._quotas = dict(quotas or {})
        for t, q in self._quotas.items():
            if not isinstance(q, TenantQuota):
                raise TypeError(f"quota for tenant {t!r} must be a "
                                f"TenantQuota, got {type(q).__name__}")
        self._buckets: Dict[str, _Bucket] = {}

    def quota_for(self, tenant: str) -> TenantQuota:
        with self._lock:
            return self._quotas.get(tenant, self._default)

    def tenants_of_class(self, klass: str) -> tuple:
        """CONFIGURED tenants whose quota carries ``klass`` — the
        degradation ladder's default shed set (``"batch"``).  Only
        explicitly-quota'd tenants count: the default quota's class
        must not silently make every unknown tenant sheddable."""
        with self._lock:
            return tuple(sorted(
                t for t, q in self._quotas.items()
                if q.klass == str(klass)))

    def _bucket_locked(self, tenant: str, now: float) -> _Bucket:
        b = self._buckets.get(tenant)
        if b is None:
            b = _Bucket(self._quotas.get(tenant, self._default), now)
            self._buckets[tenant] = b
        return b

    def _refill_locked(self, tenant: str, b: _Bucket,
                       now: float) -> None:
        q = self._quotas.get(tenant, self._default)
        if q.tokens_per_s != _INF and now > b.last:
            b.level = min(q.burst_tokens,
                          b.level + (now - b.last) * q.tokens_per_s)
        b.last = now

    def reserve_queued(self, tenant: str, cost: float,
                       now: Optional[float] = None) -> Optional[str]:
        """Account one request entering the wait line.  Returns None
        on success (queued count taken) or a human-readable rejection
        reason for the structurally-unadmittable: cost above the burst
        (waiting can never help) or the tenant's queue cap is full."""
        now = time.monotonic() if now is None else now
        with self._lock:
            q = self._quotas.get(tenant, self._default)
            b = self._bucket_locked(tenant, now)
            if cost > q.burst_tokens:
                return (f"request cost {cost:g} tokens exceeds tenant "
                        f"{tenant!r} burst capacity "
                        f"{q.burst_tokens:g} — it can never pass")
            if q.max_queued is not None and b.queued >= q.max_queued:
                return (f"tenant {tenant!r} queue cap {q.max_queued} "
                        f"reached")
            b.queued += 1
            return None

    def drop_queued(self, tenant: str) -> None:
        """Undo a :meth:`reserve_queued` for a request leaving the
        wait line WITHOUT dispatching (cancel, expiry, shutdown)."""
        with self._lock:
            b = self._buckets.get(tenant)
            if b is not None and b.queued > 0:
                b.queued -= 1

    def try_dispatch(self, tenant: str, cost: float,
                     now: Optional[float] = None) -> bool:
        """Try to move one waiting request into flight: True deducts
        ``cost`` from the bucket and takes a concurrency slot; False
        means over-rate or at the concurrency cap — leave it waiting
        and try again next pass."""
        now = time.monotonic() if now is None else now
        with self._lock:
            q = self._quotas.get(tenant, self._default)
            b = self._bucket_locked(tenant, now)
            self._refill_locked(tenant, b, now)
            if (q.max_concurrent is not None
                    and b.concurrent >= q.max_concurrent):
                return False
            if b.level < cost:
                return False
            b.level -= cost
            b.concurrent += 1
            if b.queued > 0:
                b.queued -= 1
            return True

    def release(self, tenant: str) -> None:
        """A dispatched request finished (result, error, or was
        migrated INTO a terminal failure) — free its concurrency
        slot.  Token cost is NOT refunded: the work was (mostly)
        done, and refunds would let a cancel-storm tenant decode for
        free.  (:meth:`refund` exists for the one case where that
        rationale is false — charged but never dispatched anywhere.)"""
        with self._lock:
            b = self._buckets.get(tenant)
            if b is not None and b.concurrent > 0:
                b.concurrent -= 1

    def refund(self, tenant: str, cost: float) -> None:
        """Return ``cost`` tokens to the bucket for a request that
        was CHARGED but never dispatched to any replica (fleet-side
        cancel/expiry while every replica was down, no-healthy-
        replica failure): no decode happened, so the no-refund rule
        in :meth:`release` does not apply — without this, a
        rate-limited tenant facing a flapping fleet is throttled out
        of quota it never used."""
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                return
            q = self._quotas.get(tenant, self._default)
            b.level = min(q.burst_tokens, b.level + float(cost))

    def snapshot(self) -> Dict[str, dict]:
        """Per-tenant accounting view for ``ServingFleet.stats()``."""
        with self._lock:
            return {t: {"level": b.level, "concurrent": b.concurrent,
                        "queued": b.queued}
                    for t, b in self._buckets.items()}
