"""Multi-tenant serving fleet: admission routing over decode replicas.

The scale-out layer above ``parallel.GenerationServer`` (ROADMAP item
2): a thin scheduling/placement frontend (:class:`ServingFleet`) over
N homogeneous decode-server replicas — the TensorFlow-paper
frontend/worker split, with the resilience and observability the
TPU-fleet retrospective says must be designed in:

* per-tenant **quotas** (token buckets + concurrency/queue caps —
  :mod:`~.tenancy`), so one hot tenant cannot starve the fleet;
* **SLO-aware dispatch**: priority classes + earliest-deadline-first,
  with infeasible deadlines rejected at admission
  (:class:`~.errors.DeadlineInfeasibleError`) instead of burning KV
  blocks;
* **prefix-affinity placement** (:mod:`~.placement`): same-prefix
  requests route to the replica whose prefix cache is warm,
  least-loaded-by-free-blocks otherwise;
* **disaggregated prefill/decode** (ISSUE 14): per-replica ``roles``
  split the fleet — long-prompt requests stage through a prefill
  replica, whose finished prefix blocks hand off to a decode replica
  through the paged-KV block abstraction (``export_prefix`` →
  ``import_blocks``), so a compute-bound chunked prefill never stalls
  the memory-bound decode ticks; byte parity with a unified decode
  holds end to end;
* **lifecycle**: health-weighted dispatch, ``drain()`` for rolling
  restarts, and live migration — a dead or hard-drained replica's
  queued and in-flight requests re-place onto survivors and complete
  byte-identical to offline ``generate()``;
* **closed-loop autoscaling** (:mod:`~.autoscale`, ISSUE 12): an
  :class:`Autoscaler` evaluates the fleet-wide metric view
  (``telemetry.FleetRegistry``) against :class:`AutoscalePolicy` SLO
  targets and drives ``add_replica``/``remove_replica`` with
  hysteresis + cooldown, deferring/shedding batch-class tenants
  before interactive ones — and PREDICTIVELY (ISSUE 13): a
  :class:`BacklogForecaster` linear fit over the backlog series
  pre-warms a replica when the projected queue depth crosses the SLO
  horizon, before any reactive signal trips;
* **overload protection** (ISSUE 18): admission-time SLO burn
  projection (admit / degrade / reject with a server-advised
  retry-after — :class:`~.errors.AdmissionRejectedError`), a
  reversible graceful-degradation ladder
  (:class:`~.degrade.DegradeLadder`: shrink budgets → force greedy →
  spec off → shed batch), and tail-latency hedging — near-deadline
  interactive requests race a duplicate on a second warm replica,
  first completion wins, the loser is cancelled.

Telemetry rides the PR-1 registry: ``fleet_requests_total{tenant=,
outcome=}``, ``fleet_replica_dispatch_total{replica=,reason=}``,
``fleet_queue_wait_seconds{tenant=}``, ``fleet_replicas_healthy``,
``fleet_request_phase_seconds{phase=}`` (the request-trace phase
decomposition), ``fleet_edf_slack_seconds{tenant=}``, and the
``fleet_autoscale_*`` action/shed series.
"""
from deeplearning4j_tpu.serving.autoscale import (AutoscalePolicy,
                                                  Autoscaler,
                                                  BacklogForecaster,
                                                  fit_trend,
                                                  predict_breach_s)
from deeplearning4j_tpu.serving.degrade import RUNGS, DegradeLadder
from deeplearning4j_tpu.serving.errors import (AdmissionRejectedError,
                                               DeadlineInfeasibleError,
                                               FleetAdmissionError,
                                               NoHealthyReplicaError,
                                               QuotaExceededError)
from deeplearning4j_tpu.serving.placement import (AFFINITY, FAILOVER,
                                                  HANDOFF, LEAST_LOADED,
                                                  PREFILL, ROLE_DECODE,
                                                  ROLE_PREFILL,
                                                  ROLE_UNIFIED, ROLES,
                                                  choose_replica,
                                                  replica_view)
from deeplearning4j_tpu.serving.router import ServingFleet
from deeplearning4j_tpu.serving.tenancy import (TenantAccountant,
                                                TenantQuota)

__all__ = [
    "ServingFleet", "TenantQuota", "TenantAccountant",
    "Autoscaler", "AutoscalePolicy", "BacklogForecaster",
    "fit_trend", "predict_breach_s",
    "DegradeLadder", "RUNGS",
    "FleetAdmissionError", "QuotaExceededError",
    "DeadlineInfeasibleError", "NoHealthyReplicaError",
    "AdmissionRejectedError",
    "choose_replica", "replica_view",
    "AFFINITY", "LEAST_LOADED", "FAILOVER", "PREFILL", "HANDOFF",
    "ROLES", "ROLE_PREFILL", "ROLE_DECODE", "ROLE_UNIFIED",
]
