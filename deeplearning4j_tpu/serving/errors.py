"""Typed admission vocabulary for the serving fleet.

The resilience layer's rule (``resilience.errors``) applies one level
up too: a router dispatches on TYPE.  A quota rejection is the
tenant's problem (shed load, bill them, raise their quota), a
deadline-infeasibility rejection is the caller's problem (their SLO
cannot be met — retrying the identical request is pointless), and a
no-healthy-replica failure is the FLEET's problem — transient by
construction (a replica is restarting or being replaced), so it IS
``RetryableServerError`` and rides the existing submit-retry
machinery unchanged.
"""
from __future__ import annotations

from deeplearning4j_tpu.resilience.errors import RetryableServerError


class FleetAdmissionError(RuntimeError):
    """Base of the router's admission rejections.  Raised BEFORE any
    replica state is touched — a rejected request burned no KV blocks,
    no slot, and no prefill compute."""


class QuotaExceededError(FleetAdmissionError):
    """The tenant's quota can never cover this request (cost above the
    token-bucket burst) or its queue cap is already full.  Transient
    over-rate traffic does NOT raise — it queues until the bucket
    refills; this error means waiting cannot help."""


class DeadlineInfeasibleError(FleetAdmissionError):
    """The request's ``deadline_s`` cannot be met even if it were
    dispatched immediately (decode-time floor above the budget, or the
    deadline is already in the past) — rejected at admission instead
    of burning blocks on a request that must expire mid-decode."""


class NoHealthyReplicaError(RetryableServerError):
    """Every replica is dead, draining, or unhealthy.  Retryable: a
    fleet in this state is being repaired (watchdog restarts, rolling
    replace), and the request was never applied anywhere."""


class AdmissionRejectedError(FleetAdmissionError):
    """The SLO projection says admitting this tenant's request would
    deepen an error-budget overdraft that is already burning.  Unlike
    ``QuotaExceededError`` this IS worth retrying — but not blindly:
    ``retry_after_s`` is the budget-recovery slope's estimate of when
    capacity returns, and ``submit(retries=)`` honors it as the FLOOR
    of its next backoff instead of hammering the recovering fleet."""

    def __init__(self, tenant: str, retry_after_s: float,
                 projected_burn: float, reason: str = ""):
        self.tenant = str(tenant)
        self.retry_after_s = float(retry_after_s)
        self.projected_burn = float(projected_burn)
        msg = (f"tenant {self.tenant!r} rejected at admission: "
               f"projected burn {self.projected_burn:.3g}x, retry "
               f"after {self.retry_after_s:.3g}s")
        if reason:
            msg += f" ({reason})"
        super().__init__(msg)
