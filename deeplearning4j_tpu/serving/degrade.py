"""Graceful-degradation ladder for the serving fleet (ISSUE 18).

When the SLO engine projects a sustained error-budget burn, the fleet
should get CHEAPER before it gets smaller: shedding load is rung FIVE,
not the first response.  This module is the state machine between the
two — it reads the engine's admission projection (the same TSDB-backed
burn history the alert condition folds) and walks the fleet down a
ladder of progressively lossier-but-reversible economies:

====  ==============  ==================================================
rung  name            effect while the rung holds
====  ==============  ==================================================
0     normal          nothing — the ladder is invisible
1     shrink_budget   new requests' ``n_new`` capped to
                      ``n_new_factor`` of what they asked for (shorter
                      answers, same answers-per-second), and already-
                      waiting work is demoted the same way
2     force_greedy    sampling disabled (temperature 0): every decode
                      rides the cheap deterministic path — no
                      per-slot filter/categorical math in the tick,
                      and spec rounds skip the rejection-resampling
                      machinery (greedy acceptance only)
3     shrink_draft_k  the speculative draft depth capped to 1
                      (``set_draft_k_cap``): the acceptance
                      controller's k_max collapses, so each round
                      drafts ONE token — most of speculation's win at
                      a fraction of its draft compute
4     spec_off        speculative decoding suspended entirely (draft K
                      dropped to 0): no draft compute, no verify ticks
5     shed_batch      the batch tenant class is rejected at admission
                      (typed ``AdmissionRejectedError`` with a
                      retry-after hint) and its waiting work cancelled
====  ==============  ==================================================

Rungs NEST: rung 3 implies 2 implies 1.  Ascent is immediate — a burn
spike that clears threshold N lands on rung N this pass, because every
pass spent under-degraded burns budget.  Descent is damped twice over
(the no-flap property the alert engine's multi-window shape has):
burn must fall below ``hysteresis`` x the rung's own entry threshold
AND stay there for ``hold_down_s`` before ONE rung releases, then the
clock re-arms for the next.

Reversibility is structural, not aspirational: every rung acts only
on (a) requests admitted WHILE it holds (shaped at admission from the
current rung) and (b) the waiting pool at entry.  A request admitted
after the rung clears is untouched on every path, so post-recovery
outputs are byte-identical to a never-degraded run — the chaos drill
pins exactly that.

Every rung entry/exit is a counted flight-recorder event
(``flight_events_total{kind="degrade_step"}``) and the current rung is
the ``fleet_degrade_rung`` gauge, so a postmortem replays the whole
walk from the TSDB at ``/query``.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu import telemetry

log = logging.getLogger("deeplearning4j_tpu")

#: the ladder's rungs, mildest first; index == rung number
RUNGS: Tuple[str, ...] = ("normal", "shrink_budget", "force_greedy",
                          "shrink_draft_k", "spec_off", "shed_batch")

_RUNG_GAUGE = telemetry.gauge(
    "fleet_degrade_rung",
    "current degradation-ladder rung: 0 normal, 1 shrink_budget "
    "(n_new capped), 2 force_greedy (sampling off), 3 shrink_draft_k "
    "(draft depth capped to 1), 4 spec_off (draft K dropped), "
    "5 shed_batch (batch class rejected at admission)")

_FLIGHT = telemetry.get_flight_recorder()


class DegradeLadder:
    """The burn-driven degradation state machine.

    >>> ladder = DegradeLadder(fleet, engine,
    ...                        thresholds=(2.0, 6.0, 8.0, 10.0, 14.4))
    >>> fleet.attach_degrade(ladder)     # admission shaping
    >>> ladder.start()                   # or: autoscaler drives it

    ``thresholds`` are the burn levels (units of the SLO budget-spend
    rate, like the alert windows') at which rungs 1..5 engage;
    ``burn`` is injectable into :meth:`evaluate` for tests, otherwise
    the worst covered projection across the engine's specs.  The
    ``batch_tenants`` shed set defaults to the fleet accountant's
    ``klass="batch"`` tenants."""

    def __init__(self, fleet=None, engine=None, *,
                 thresholds: Tuple[float, ...] = (2.0, 6.0, 8.0, 10.0,
                                                  14.4),
                 hysteresis: float = 0.7,
                 hold_down_s: float = 2.0,
                 n_new_factor: float = 0.25,
                 min_n_new: int = 1,
                 batch_tenants: Optional[Tuple[str, ...]] = None,
                 shed_retry_after_s: float = 1.0,
                 interval_s: float = 0.5):
        self.fleet = fleet
        self.engine = engine
        self.thresholds = tuple(float(t) for t in thresholds)
        if len(self.thresholds) != len(RUNGS) - 1:
            raise ValueError(
                f"need {len(RUNGS) - 1} thresholds (one per rung "
                f"above normal), got {len(self.thresholds)}")
        if any(b <= a for a, b in zip(self.thresholds,
                                      self.thresholds[1:])):
            raise ValueError("thresholds must strictly increase "
                             f"rung by rung: {self.thresholds}")
        if self.thresholds[0] <= 0:
            raise ValueError("thresholds must be > 0")
        self.hysteresis = float(hysteresis)
        if not 0.0 < self.hysteresis <= 1.0:
            raise ValueError(f"hysteresis={hysteresis} must be in "
                             "(0, 1] — a release point ABOVE the "
                             "entry threshold flaps by construction")
        self.hold_down_s = float(hold_down_s)
        if self.hold_down_s < 0:
            raise ValueError("hold_down_s must be >= 0")
        self.n_new_factor = float(n_new_factor)
        if not 0.0 < self.n_new_factor <= 1.0:
            raise ValueError("n_new_factor must be in (0, 1]")
        self.min_n_new = max(1, int(min_n_new))
        self._batch_tenants = (None if batch_tenants is None
                               else tuple(str(t) for t in batch_tenants))
        self.shed_retry_after_s = float(shed_retry_after_s)
        self.interval_s = float(interval_s)
        if self.interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self._lock = threading.Lock()
        self._rung = 0
        self._below_since: Optional[float] = None
        self._last_burn = 0.0
        self._transitions: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        _RUNG_GAUGE.set(0.0)

    # -- configuration reads -------------------------------------------
    def shed_tenants(self) -> Tuple[str, ...]:
        """The tenant set rung 5 sheds: the configured list, else the
        fleet accountant's batch-class tenants, else nothing (a fleet
        with no batch class has nothing safe to shed)."""
        if self._batch_tenants is not None:
            return self._batch_tenants
        acct = getattr(self.fleet, "_acct", None)
        if acct is None:
            return ()
        return tuple(acct.tenants_of_class("batch"))

    # -- state reads ---------------------------------------------------
    def rung(self) -> int:
        with self._lock:
            return self._rung

    def state(self) -> dict:
        """Snapshot for tests and postmortems: rung, rung name, last
        burn driven through, and the entry/exit transition counts."""
        with self._lock:
            return {"rung": self._rung, "name": RUNGS[self._rung],
                    "burn": self._last_burn,
                    "transitions": dict(self._transitions)}

    # -- the policy each rung implies ----------------------------------
    def policy(self, rung: Optional[int] = None) -> dict:
        """The fleet-facing knob settings for ``rung`` (default: the
        current rung) — what :meth:`ServingFleet.apply_degrade`
        actuates.  Rungs nest: each includes everything below it."""
        if rung is None:
            with self._lock:
                rung = self._rung
        rung = int(rung)
        return {
            "max_n_new_factor": (self.n_new_factor if rung >= 1
                                 else None),
            "min_n_new": self.min_n_new,
            "force_greedy": rung >= 2,
            "draft_k_cap": 1 if rung >= 3 else None,
            "spec": rung < 4,
            "shed_tenants": (self.shed_tenants() if rung >= 5
                             else ()),
        }

    # -- admission shaping ---------------------------------------------
    def shape_admission(self, tenant: str, n_new: int,
                        sampling: Optional[dict]
                        ) -> Tuple[int, Optional[dict], str]:
        """Shape ONE request at admission from the current rung:
        returns ``(n_new, sampling, verdict)`` with verdict one of
        ``admit`` / ``degraded`` / ``reject``.  Reject (rung 5, batch
        tenant) costs the pool nothing — the router raises before any
        reserve.  Requests admitted at rung 0 pass through untouched,
        which is the reversibility contract.  Rungs 3 and 4 act on
        the REPLICAS (draft depth cap / spec off via
        ``apply_degrade``), not on individual requests — nothing to
        shape here."""
        with self._lock:
            rung = self._rung
        if rung <= 0:
            return int(n_new), sampling, "admit"
        if rung >= 5 and str(tenant) in self.shed_tenants():
            return int(n_new), sampling, "reject"
        verdict = "admit"
        n_new = int(n_new)
        if rung >= 1:
            capped = max(self.min_n_new,
                         int(n_new * self.n_new_factor))
            if capped < n_new:
                n_new = capped
                verdict = "degraded"
        if rung >= 2:
            temp = (sampling or {}).get("temperature", None)
            if temp is None or float(temp) > 0.0:
                # greedy-only sampling dict: top_k/top_p with
                # temperature 0 is a typed error in the decode server
                sampling = {"temperature": 0.0}
                verdict = "degraded"
        return n_new, sampling, verdict

    # -- the walk ------------------------------------------------------
    def _read_burn(self, now: float) -> float:
        """The drive signal: worst covered projected burn across the
        engine's specs (a young/uncovered history drives 0 — the
        ladder can no more flap on a first blip than admission can
        reject on one)."""
        if self.engine is None:
            return 0.0
        try:
            rows = self.engine.projection(now=now)
        except Exception:
            log.exception("degrade ladder: projection read failed")
            return 0.0
        covered = [r["projected_burn"] for r in rows if r["covered"]]
        return max(covered) if covered else 0.0

    def evaluate(self, now: Optional[float] = None,
                 burn: Optional[float] = None) -> int:
        """One ladder pass; returns the rung after the pass.  ``now``
        and ``burn`` are injectable for tests — the production loop
        reads ``time.monotonic`` and the engine projection."""
        now = time.monotonic() if now is None else float(now)
        burn = self._read_burn(now) if burn is None else float(burn)
        target = sum(1 for t in self.thresholds if burn >= t)
        steps: List[Tuple[str, int]] = []
        with self._lock:
            self._last_burn = burn
            cur = self._rung
            if target > cur:
                # immediate ascent: every pass spent under-degraded
                # burns budget, so the spike lands on its rung NOW
                for r in range(cur + 1, target + 1):
                    steps.append(("enter", r))
                self._rung = target
                self._below_since = None
            elif cur > 0:
                # damped descent: below hysteresis x the CURRENT
                # rung's entry threshold, held hold_down_s, releases
                # ONE rung — then the clock re-arms
                release = self.thresholds[cur - 1] * self.hysteresis
                if burn < release:
                    if self._below_since is None:
                        self._below_since = now
                    elif now - self._below_since >= self.hold_down_s:
                        steps.append(("exit", cur))
                        self._rung = cur - 1
                        self._below_since = now
                else:
                    self._below_since = None
            rung = self._rung
            for direction, r in steps:
                key = f"{direction}:{RUNGS[r]}"
                self._transitions[key] = \
                    self._transitions.get(key, 0) + 1
        # actuation OUTSIDE the ladder lock: apply_degrade takes the
        # fleet lock and demotes replica queues — never nest ours
        # around theirs
        _RUNG_GAUGE.set(float(rung))
        for direction, r in steps:
            _FLIGHT.record("degrade_step", rung=int(r),
                           name=RUNGS[r], direction=direction,
                           burn=float(burn))
            log.info("degrade ladder: %s rung %d (%s) at burn %.3g",
                     direction, r, RUNGS[r], burn)
        if steps and self.fleet is not None:
            try:
                self.fleet.apply_degrade(**self.policy(rung))
            except Exception:
                log.exception("degrade ladder: apply_degrade failed")
        return rung

    # -- standalone loop ----------------------------------------------
    def _loop(self, stop: threading.Event) -> None:
        while not stop.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception:
                # one bad pass must not silence the overload defense
                log.exception("degrade ladder evaluation failed")

    def start(self) -> "DegradeLadder":
        # fresh stop event: re-armable after a close() (a set() event
        # would end the new loop on its first wait); the thread
        # closes over ITS OWN event
        stop = threading.Event()
        thread = threading.Thread(target=self._loop, args=(stop,),
                                  name="dl4j-tpu-degrade-ladder",
                                  daemon=True)
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self          # already running
            self._stop = stop
            self._thread = thread
        thread.start()
        return self

    def close(self) -> None:
        with self._lock:
            stop = self._stop
            thread = self._thread
            self._thread = None
        stop.set()
        if thread is not None:
            thread.join(timeout=max(5.0, 2 * self.interval_s))

    def __enter__(self) -> "DegradeLadder":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
