"""MNIST dataset iterator.

Parity with ``org.deeplearning4j.datasets.iterator.impl.MnistDataSetIterator``
(batch, train/test split, auto-download+cache, binarization option).

This environment has no network egress, so when the IDX files are absent
from the cache directory (``$DL4J_TPU_MNIST_DIR`` or ``~/.deeplearning4j_tpu/
mnist``), a DETERMINISTIC SYNTHETIC digit set is generated instead: class-
conditional stroke templates rendered at 28x28 with per-example jitter and
noise.  It is statistically MNIST-shaped (10 classes, [0,255] grayscale,
60k/10k split) and hard enough that a linear model gets ~90% while the
reference MLP config reaches >97% — preserving the convergence-test
semantics of the real dataset.  Drop real IDX files in the cache dir to use
actual MNIST.
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator

_CACHE_ENV = "DL4J_TPU_MNIST_DIR"
_DEFAULT_CACHE = os.path.expanduser("~/.deeplearning4j_tpu/mnist")

_IDX_FILES = {
    True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">HBB", f.read(4))
        _, dtype_code, ndim = magic
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(dims)


def _load_real(train: bool) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    cache = os.environ.get(_CACHE_ENV, _DEFAULT_CACHE)
    img_name, lbl_name = _IDX_FILES[train]
    for suffix in ("", ".gz"):
        ip = os.path.join(cache, img_name + suffix)
        lp = os.path.join(cache, lbl_name + suffix)
        if os.path.exists(ip) and os.path.exists(lp):
            return _read_idx(ip), _read_idx(lp)
    return None


def _digit_templates(rng: np.random.Generator) -> np.ndarray:
    """10 fixed 28x28 'digit' stroke templates from a seeded RNG: random
    smooth blobs per class, distinct enough to be separable, overlapping
    enough to need a nonlinear model for >95%."""
    templates = np.zeros((10, 28, 28), np.float32)
    yy, xx = np.mgrid[0:28, 0:28]
    for c in range(10):
        n_strokes = 3 + c % 3
        img = np.zeros((28, 28), np.float32)
        for _ in range(n_strokes):
            # random quadratic stroke: p(t) = a + b t + c t^2 in pixel space
            p0 = rng.uniform(4, 24, 2)
            p1 = rng.uniform(4, 24, 2)
            p2 = rng.uniform(4, 24, 2)
            t = np.linspace(0, 1, 64)[:, None]
            pts = ((1 - t) ** 2) * p0 + 2 * t * (1 - t) * p1 + (t**2) * p2
            for py, px in pts:
                d2 = (yy - py) ** 2 + (xx - px) ** 2
                img += np.exp(-d2 / 3.0)
        templates[c] = np.clip(img / img.max(), 0, 1)
    return templates


def synthetic_mnist(n: int, train: bool, seed: int = 123
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic synthetic digit arrays: (images uint8 [n,28,28],
    labels int [n]).  Train and test draw from the same distribution with
    disjoint RNG streams."""
    rng_t = np.random.default_rng(seed)  # templates shared train/test
    templates = _digit_templates(rng_t)
    rng = np.random.default_rng(seed + (1 if train else 2))
    labels = rng.integers(0, 10, size=n)
    images = np.zeros((n, 28, 28), np.float32)
    shifts = rng.integers(-1, 2, size=(n, 2))
    noise = rng.normal(0, 0.15, size=(n, 28, 28)).astype(np.float32)
    scales = rng.uniform(0.8, 1.0, size=n).astype(np.float32)
    for i in range(n):
        img = np.roll(templates[labels[i]], tuple(shifts[i]), axis=(0, 1))
        images[i] = img * scales[i]
    images = np.clip(images + noise, 0, 1)
    return (images * 255).astype(np.uint8), labels.astype(np.int32)


class MnistDataSetIterator(ArrayDataSetIterator):
    """DL4J-style MNIST iterator: features flat [batch, 784] float scaled to
    [0,1] (DL4J's MnistDataFetcher does the /255 itself), one-hot labels
    [batch, 10]."""

    def __init__(self, batch_size: int, train: bool = True,
                 seed: int = 123, binarize: bool = False,
                 shuffle: bool = True, n_examples: Optional[int] = None):
        real = _load_real(train)
        if real is not None:
            images, labels = real
        else:
            n = n_examples or (60000 if train else 10000)
            images, labels = synthetic_mnist(n, train, seed)
        if n_examples is not None:
            images, labels = images[:n_examples], labels[:n_examples]
        feats = images.reshape(images.shape[0], 784).astype(np.float32) / 255.0
        if binarize:
            feats = (feats > 0.5).astype(np.float32)
        onehot = np.zeros((labels.shape[0], 10), np.float32)
        onehot[np.arange(labels.shape[0]), labels] = 1.0
        super().__init__(feats, onehot, batch_size, shuffle=shuffle and train,
                         seed=seed)
        self.is_synthetic = real is None


class EmnistDataSetIterator(MnistDataSetIterator):
    """Placeholder parity for ``EmnistDataSetIterator`` — same synthetic
    backing until real EMNIST files are provided in the cache dir."""
