"""BertIterator (``org.deeplearning4j.iterator.BertIterator``
[UNVERIFIED]) — sentence (pairs) -> (ids, mask, segment[, labels])
MultiDataSets through the WordPiece tokenizer, for both supervised
sequence classification and unsupervised MLM pretraining.

Feed order matches the imported frozen-BERT placeholders
(``i``/``m``/``t``), so
``BertIterator -> import_frozen_pb(...).fit(...)`` is the full
BASELINE-config-4 pipeline end to end.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import MultiDataSet
from deeplearning4j_tpu.nlp.wordpiece import BertWordPieceTokenizerFactory


class BertIterator:
    """Tasks: ``"seq_classification"`` (labels are int classes) and
    ``"unsupervised"`` (BERT MLM: 15% of positions selected; of those
    80% -> [MASK], 10% -> random id, 10% unchanged; label mapping is
    (masked_target_ids, selection_mask))."""

    def __init__(self, tokenizer: BertWordPieceTokenizerFactory,
                 sentences: Sequence, batch_size: int, max_len: int,
                 task: str = "seq_classification",
                 mask_prob: float = 0.15, seed: int = 0):
        if task not in ("seq_classification", "unsupervised"):
            raise ValueError(f"unknown task {task!r}")
        self.tok = tokenizer
        self.sentences = list(sentences)
        self.batch = int(batch_size)
        self.max_len = int(max_len)
        self.task = task
        self.mask_prob = float(mask_prob)
        self._rng = np.random.default_rng(seed)
        self._mask_id = tokenizer.vocab.get("[MASK]")
        if task == "unsupervised" and self._mask_id is None:
            raise ValueError("MLM task needs [MASK] in the vocab")

    def _encode_batch(self, texts: List) -> Tuple[np.ndarray, ...]:
        ids, mask, tt = [], [], []
        for t in texts:
            pair = None
            if isinstance(t, (tuple, list)):
                t, pair = t[0], t[1]
            i, m, s = self.tok.encode(t, pair=pair, max_len=self.max_len)
            ids.append(i)
            mask.append(m)
            tt.append(s)
        return (np.asarray(ids, np.int32), np.asarray(mask, np.int32),
                np.asarray(tt, np.int32))

    def __iter__(self):
        for lo in range(0, len(self.sentences), self.batch):
            chunk = self.sentences[lo:lo + self.batch]
            if self.task == "seq_classification":
                texts = [c[0] for c in chunk]
                labels = np.asarray([c[1] for c in chunk], np.int32)
                ids, mask, tt = self._encode_batch(texts)
                yield MultiDataSet([ids, mask, tt], [labels])
            else:
                ids, mask, tt = self._encode_batch(list(chunk))
                tgt = ids.copy()
                special = np.isin(
                    ids, [self.tok.vocab["[CLS]"],
                          self.tok.vocab["[SEP]"],
                          self.tok.vocab["[PAD]"]])
                candidates = (mask == 1) & ~special
                sel = (self._rng.random(ids.shape) < self.mask_prob) \
                    & candidates
                # canonical BERT data gen guarantees >=1 prediction per
                # example: a zero-selection row would NaN any consumer
                # normalizing by sum(sel)
                for r in np.nonzero(~sel.any(axis=1)
                                    & candidates.any(axis=1))[0]:
                    sel[r, self._rng.choice(
                        np.nonzero(candidates[r])[0])] = True
                r = self._rng.random(ids.shape)
                ids = np.where(sel & (r < 0.8), self._mask_id, ids)
                rand_ids = self._rng.integers(
                    0, len(self.tok.vocab), ids.shape).astype(np.int32)
                ids = np.where(sel & (r >= 0.8) & (r < 0.9), rand_ids,
                               ids)
                yield MultiDataSet([ids, mask, tt],
                                   [tgt, sel.astype(np.int32)])

    def reset(self):
        pass
