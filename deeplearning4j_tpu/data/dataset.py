"""DataSet / MultiDataSet containers.

Parity with ``org.nd4j.linalg.dataset.DataSet`` (features, labels,
featuresMask, labelsMask + split/shuffle/batch utilities) and
``MultiDataSet`` (lists of each).  Host-side numpy; conversion to device
arrays happens at the jit boundary.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class DataSet:
    features: np.ndarray
    labels: np.ndarray
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        out = []
        n = self.num_examples()
        for i in range(0, n, batch_size):
            sl = slice(i, min(i + batch_size, n))
            out.append(DataSet(
                self.features[sl], self.labels[sl],
                None if self.features_mask is None else self.features_mask[sl],
                None if self.labels_mask is None else self.labels_mask[sl],
            ))
        return out

    def shuffle(self, seed: Optional[int] = None) -> "DataSet":
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.num_examples())
        return DataSet(
            self.features[idx], self.labels[idx],
            None if self.features_mask is None else self.features_mask[idx],
            None if self.labels_mask is None else self.labels_mask[idx],
        )

    def split_test_and_train(self, n_train: int):
        """DL4J ``splitTestAndTrain``: (train, test) SplitTestAndTrain."""
        train = DataSet(
            self.features[:n_train], self.labels[:n_train],
            None if self.features_mask is None else self.features_mask[:n_train],
            None if self.labels_mask is None else self.labels_mask[:n_train])
        test = DataSet(
            self.features[n_train:], self.labels[n_train:],
            None if self.features_mask is None else self.features_mask[n_train:],
            None if self.labels_mask is None else self.labels_mask[n_train:])
        return train, test

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        def cat(parts):
            if any(p is None for p in parts):
                return None
            return np.concatenate(parts, axis=0)
        return DataSet(
            cat([d.features for d in datasets]),
            cat([d.labels for d in datasets]),
            cat([d.features_mask for d in datasets]),
            cat([d.labels_mask for d in datasets]),
        )


@dataclasses.dataclass
class MultiDataSet:
    """Multi-input/multi-output sample batch (``org.nd4j.linalg.dataset.MultiDataSet``)."""

    features: List[np.ndarray]
    labels: List[np.ndarray]
    features_masks: Optional[List[Optional[np.ndarray]]] = None
    labels_masks: Optional[List[Optional[np.ndarray]]] = None

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])


def tbptt_segments(ds, length: int):
    """Split a sequence batch along time into truncated-BPTT segments
    (DL4J ``MultiLayerNetwork.doTruncatedBPTT`` /
    ``ComputationGraph.doTruncatedBPTT``).  [b, t, f] arrays are sliced on
    the time axis; 2-D masks slice too; per-example arrays pass through.
    Batches with no time dimension come back unchanged."""
    def t_len(arrays):
        for a in arrays:
            if a is not None and np.ndim(a) == 3:
                return a.shape[1]
        return None

    def tslice(a, sl, is_mask=False):
        if a is None:
            return None
        if np.ndim(a) == 3 or (is_mask and np.ndim(a) == 2):
            return a[:, sl]
        return a

    if isinstance(ds, MultiDataSet):
        t = t_len(list(ds.features) + list(ds.labels))
        if t is None:
            return [ds]
        return [MultiDataSet(
            [tslice(a, sl) for a in ds.features],
            [tslice(a, sl) for a in ds.labels],
            None if ds.features_masks is None else
            [tslice(a, sl, True) for a in ds.features_masks],
            None if ds.labels_masks is None else
            [tslice(a, sl, True) for a in ds.labels_masks])
            for sl in (slice(s, min(s + length, t))
                       for s in range(0, t, length))]
    t = t_len([ds.features, ds.labels])
    if t is None:
        return [ds]
    return [DataSet(
        tslice(ds.features, sl), tslice(ds.labels, sl),
        tslice(ds.features_mask, sl, True),
        tslice(ds.labels_mask, sl, True))
        for sl in (slice(s, min(s + length, t))
                   for s in range(0, t, length))]
