"""DataSetIterator protocol + async prefetch.

Parity with ``org.nd4j.linalg.dataset.api.iterator.DataSetIterator`` and
``org.deeplearning4j.datasets.iterator.AsyncDataSetIterator`` (the
background prefetch thread DL4J wraps every fit() iterator in).  On TPU the
prefetch thread overlaps host ETL with device compute; the device-side
double buffering is XLA's async dispatch.
"""
from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet


class DataSetIterator:
    """Base contract: iterable over DataSet minibatches, resettable."""

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def batch_size(self) -> Optional[int]:
        return None

    def total_outcomes(self) -> Optional[int]:
        return None

    # DL4J's pre-processor hook (DataNormalization attaches here)
    pre_processor = None

    def _maybe_preprocess(self, ds: DataSet) -> DataSet:
        if self.pre_processor is not None:
            ds = self.pre_processor.transform(ds)
        return ds


class ListDataSetIterator(DataSetIterator):
    """Iterate over a pre-batched list (``ListDataSetIterator``)."""

    def __init__(self, data: Sequence[DataSet], batch_size: Optional[int] = None):
        if batch_size is not None:
            merged = DataSet.merge(list(data))
            data = merged.batch_by(batch_size)
        self._batches: List[DataSet] = list(data)
        self._bs = batch_size or (self._batches[0].num_examples()
                                  if self._batches else None)

    def __iter__(self):
        for b in self._batches:
            yield self._maybe_preprocess(b)

    def batch_size(self):
        return self._bs

    def total_outcomes(self):
        if self._batches:
            return int(self._batches[0].labels.shape[-1])
        return None


class ExistingDataSetIterator(DataSetIterator):
    """Wrap any python iterable of DataSets (``ExistingDataSetIterator``)."""

    def __init__(self, iterable_factory):
        """`iterable_factory`: zero-arg callable returning a fresh iterable
        (so reset() works), or a list."""
        if isinstance(iterable_factory, (list, tuple)):
            data = list(iterable_factory)
            self._factory = lambda: iter(data)
        else:
            self._factory = iterable_factory

    def __iter__(self):
        for b in self._factory():
            yield self._maybe_preprocess(b)


class ArrayDataSetIterator(DataSetIterator):
    """Batch a (features, labels) array pair with optional shuffling —
    the workhorse equivalent of DL4J's in-memory iterators."""

    def __init__(self, features: np.ndarray, labels: np.ndarray,
                 batch_size: int, shuffle: bool = False, seed: int = 0,
                 drop_last: bool = False):
        self.features = features
        self.labels = labels
        self._bs = int(batch_size)
        self.shuffle = shuffle
        self.seed = seed
        self._epoch = 0
        self.drop_last = drop_last

    def __iter__(self):
        n = self.features.shape[0]
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            rng.shuffle(idx)
        self._epoch += 1
        end = n - (n % self._bs) if self.drop_last else n
        for i in range(0, end, self._bs):
            sl = idx[i:i + self._bs]
            yield self._maybe_preprocess(
                DataSet(self.features[sl], self.labels[sl]))

    def reset(self):
        pass  # epoch counter advances shuffling; order resets naturally

    def batch_size(self):
        return self._bs

    def total_outcomes(self):
        return int(self.labels.shape[-1])


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch (``AsyncDataSetIterator``): a worker
    thread pulls from the wrapped iterator into a bounded queue, so host
    ETL/normalization overlaps device execution of the previous step."""

    _SENTINEL = object()

    def __init__(self, wrapped: DataSetIterator, queue_size: int = 4):
        self.wrapped = wrapped
        self.queue_size = max(1, int(queue_size))

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.queue_size)
        err: List[BaseException] = []
        cancelled = threading.Event()

        def worker():
            try:
                for item in self.wrapped:
                    # Bounded put with cancellation poll so an abandoned
                    # consumer (exception mid-epoch) never strands this
                    # thread blocked on a full queue.
                    while not cancelled.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if cancelled.is_set():
                        return
            except BaseException as e:  # propagate into consumer
                err.append(e)
            # no sentinel: the consumer watches thread liveness instead,
            # so a full queue at shutdown can never deadlock either side.

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                try:
                    item = q.get(timeout=0.1)
                except queue.Empty:
                    if not t.is_alive() and q.empty():
                        break
                    continue
                yield item
        finally:
            # Runs on normal exhaustion AND on generator close/abandon.
            cancelled.set()
            t.join(timeout=5.0)
        if err:
            raise err[0]

    def reset(self):
        self.wrapped.reset()

    def batch_size(self):
        return self.wrapped.batch_size()

    def total_outcomes(self):
        return self.wrapped.total_outcomes()
