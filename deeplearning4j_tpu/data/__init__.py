"""Data pipeline: DataSet containers, iterators, normalizers, built-in datasets.

TPU-native twin of the ND4J dataset API + DataVec ETL (reference:
``org.nd4j.linalg.dataset.{DataSet,MultiDataSet}``,
``org.nd4j.linalg.dataset.api.iterator.DataSetIterator``,
``org.deeplearning4j.datasets.iterator.*``, ``datavec/*``).  Host-side data
stays numpy; device transfer happens once per batch at the jit boundary
(sharded device_put when a mesh is active).
"""

from deeplearning4j_tpu.data.bert_iterator import BertIterator
from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterator import (
    AsyncDataSetIterator,
    DataSetIterator,
    ExistingDataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.data.builtin import (
    Cifar10DataSetIterator,
    IrisDataSetIterator,
)
from deeplearning4j_tpu.data.normalization import (
    ImagePreProcessingScaler,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
)

__all__ = [
    "BertIterator", "DataSet", "MultiDataSet", "DataSetIterator", "ListDataSetIterator",
    "ExistingDataSetIterator", "AsyncDataSetIterator",
    "IrisDataSetIterator", "Cifar10DataSetIterator",
    "NormalizerStandardize", "NormalizerMinMaxScaler",
    "ImagePreProcessingScaler",
]
