"""Tiny hand-written sentiment corpus — the egress-free stand-in for
SST-2 in BASELINE config 4's fine-tune quality proof (VERDICT r4 item
3: "no run anywhere shows held-out accuracy improving on a real
labeled text task").

318 hand-authored English review sentences (159 positive / 159
negative, ``corpora/tiny_sentiment.tsv``) spanning film, food,
product, travel and service registers.  Train and held-out sentences
are DISJOINT but share a sentiment lexicon, so a model that learns the
lexical task (rather than memorizing training rows) generalizes —
exactly the property the quality artifact needs to demonstrate.

Parity role: the data side of the reference's BERT fine-tune examples
(``deeplearning4j-examples`` BertIterator + SST-2 style CSVs
[UNVERIFIED]); the corpus itself replaces the undownloadable dataset.
"""
from __future__ import annotations

import os
from typing import Dict, List, Tuple

from deeplearning4j_tpu.nlp.wordpiece import (BertWordPieceTokenizerFactory,
                                              _basic_tokens)

_TSV = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "corpora", "tiny_sentiment.tsv")

SPECIALS = ("[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]")


def load_tiny_sentiment() -> List[Tuple[str, int]]:
    """All (sentence, label) pairs in file order (balanced 159/159)."""
    out = []
    with open(_TSV, encoding="utf-8") as f:
        for line in f:
            line = line.rstrip("\n")
            if not line:
                continue
            label, text = line.split("\t", 1)
            out.append((text, int(label)))
    return out


def train_test_split(k: int = 4) -> Tuple[List[Tuple[str, int]],
                                          List[Tuple[str, int]]]:
    """Deterministic PAIR-AWARE split (k=4 -> 238 train / 80 test,
    label-balanced).

    The corpus is written as parallel pairs: positive sentence i and
    negative sentence 159+i share their scaffolding ("the film was
    ...delight" / "the film was ...slog").  Both members of a pair must
    land on the same side of the split: with a naive interleaved split
    a scaffold word ("film") appears in TRAIN with exactly one label —
    a perfectly predictive memorization feature — while its held-out
    twin carries the OPPOSITE label, so a scaffold-keying model scores
    systematically BELOW chance (observed: 0.35-0.39 held-out with
    train loss -> 0).  Splitting by pair puts each scaffold in train
    with both labels (useless for memorization) or only in test
    (unseen), leaving the corpus-wide sentiment lexicon as the only
    signal that generalizes — which is exactly the property the
    config-4 quality artifact must demonstrate."""
    data = load_tiny_sentiment()
    half = len(data) // 2
    pos, neg = data[:half], data[half:]
    train: List[Tuple[str, int]] = []
    test: List[Tuple[str, int]] = []
    for i in range(half):
        dst = test if i % k == 0 else train
        dst.append(pos[i])
        dst.append(neg[i])
    return train, test


def build_vocab() -> Dict[str, int]:
    """WordPiece vocab covering the corpus: specials + every basic
    token (the corpus is lowercase English, so whole words suffice —
    encode() never falls back to [UNK])."""
    vocab: Dict[str, int] = {s: i for i, s in enumerate(SPECIALS)}
    for text, _ in load_tiny_sentiment():
        for tok in _basic_tokens(text, lower=True, strip_accents=True):
            if tok not in vocab:
                vocab[tok] = len(vocab)
    return vocab


def make_tokenizer() -> BertWordPieceTokenizerFactory:
    return BertWordPieceTokenizerFactory(build_vocab())
