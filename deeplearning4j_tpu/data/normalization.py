"""Data normalizers with fit/transform semantics.

Parity with ``org.nd4j.linalg.dataset.api.preprocessor.{NormalizerStandardize,
NormalizerMinMaxScaler,ImagePreProcessingScaler}`` — fit statistics on a
training iterator, then attach as the iterator's pre-processor so every
batch is normalized on the host prefetch thread.
"""
from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet


class DataNormalization:
    def fit(self, iterator) -> "DataNormalization":
        raise NotImplementedError

    def transform(self, ds: DataSet) -> DataSet:
        raise NotImplementedError

    # serialization for checkpoints (NormalizerSerializer analogue)
    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, d: dict) -> None:
        pass


class NormalizerStandardize(DataNormalization):
    """Zero-mean unit-variance per feature column."""

    def __init__(self):
        self.mean = None
        self.std = None

    def fit(self, iterator):
        # Streaming two-pass-free fit via Welford-style accumulation.
        n, s, s2 = 0, None, None
        for ds in iterator:
            f = ds.features.reshape(ds.features.shape[0], -1).astype(np.float64)
            if s is None:
                s = f.sum(0)
                s2 = (f * f).sum(0)
            else:
                s += f.sum(0)
                s2 += (f * f).sum(0)
            n += f.shape[0]
        iterator.reset()
        self.mean = (s / n).astype(np.float32)
        var = np.maximum(s2 / n - (s / n) ** 2, 1e-12)
        self.std = np.sqrt(var).astype(np.float32)
        return self

    def transform(self, ds: DataSet) -> DataSet:
        shape = ds.features.shape
        f = ds.features.reshape(shape[0], -1)
        f = (f - self.mean) / self.std
        return DataSet(f.reshape(shape).astype(np.float32), ds.labels,
                       ds.features_mask, ds.labels_mask)

    def state_dict(self):
        return {"mean": self.mean, "std": self.std}

    def load_state_dict(self, d):
        self.mean, self.std = d["mean"], d["std"]


class NormalizerMinMaxScaler(DataNormalization):
    """Scale features into [min, max] (default [0, 1])."""

    def __init__(self, min_val: float = 0.0, max_val: float = 1.0):
        self.target_min = min_val
        self.target_max = max_val
        self.data_min = None
        self.data_max = None

    def fit(self, iterator):
        lo, hi = None, None
        for ds in iterator:
            f = ds.features.reshape(ds.features.shape[0], -1)
            bmin, bmax = f.min(0), f.max(0)
            lo = bmin if lo is None else np.minimum(lo, bmin)
            hi = bmax if hi is None else np.maximum(hi, bmax)
        iterator.reset()
        self.data_min, self.data_max = lo.astype(np.float32), hi.astype(np.float32)
        return self

    def transform(self, ds: DataSet) -> DataSet:
        shape = ds.features.shape
        f = ds.features.reshape(shape[0], -1)
        rng = np.maximum(self.data_max - self.data_min, 1e-12)
        f = (f - self.data_min) / rng
        f = f * (self.target_max - self.target_min) + self.target_min
        return DataSet(f.reshape(shape).astype(np.float32), ds.labels,
                       ds.features_mask, ds.labels_mask)

    def state_dict(self):
        return {"data_min": self.data_min, "data_max": self.data_max,
                "target_min": self.target_min, "target_max": self.target_max}

    def load_state_dict(self, d):
        self.data_min, self.data_max = d["data_min"], d["data_max"]
        self.target_min, self.target_max = d["target_min"], d["target_max"]


class ImagePreProcessingScaler(DataNormalization):
    """Pixel scaling [0,255] -> [a,b] (``ImagePreProcessingScaler``);
    needs no fit."""

    def __init__(self, min_val: float = 0.0, max_val: float = 1.0):
        self.min_val = min_val
        self.max_val = max_val

    def fit(self, iterator):
        return self

    def transform(self, ds: DataSet) -> DataSet:
        f = ds.features.astype(np.float32) / 255.0
        f = f * (self.max_val - self.min_val) + self.min_val
        return DataSet(f, ds.labels, ds.features_mask, ds.labels_mask)

    def state_dict(self):
        return {"min_val": self.min_val, "max_val": self.max_val}

    def load_state_dict(self, d):
        self.min_val, self.max_val = d["min_val"], d["max_val"]
