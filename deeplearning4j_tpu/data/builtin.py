"""Built-in small datasets (``IrisDataSetIterator``,
``CifarDataSetIterator`` — ``org.deeplearning4j.datasets.iterator.impl``).

Iris ships the REAL 150-example Fisher dataset in-repo
(``resources/iris.csv`` — public-domain data; DL4J bundles it the same
way).  CIFAR-10 has no egress here, so ``Cifar10DataSetIterator``
loads real batches from ``DL4J_TPU_CIFAR_DIR`` when the standard
``data_batch_*.bin``/``test_batch.bin`` files exist and otherwise
falls back to a DETERMINISTIC synthetic set (class-conditional color
blobs, ``is_synthetic=True``) — the same explicit-caveat pattern as
``data/mnist.py``.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator

_RES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "resources")


def load_iris_arrays():
    """(features [150, 4] f32, one-hot labels [150, 3] f32)."""
    rows = np.loadtxt(os.path.join(_RES, "iris.csv"), delimiter=",")
    feats = rows[:, :4].astype(np.float32)
    labels = rows[:, 4].astype(np.int32)
    onehot = np.eye(3, dtype=np.float32)[labels]
    return feats, onehot


class IrisDataSetIterator(ArrayDataSetIterator):
    """The classic 150-example Fisher iris set
    (``IrisDataSetIterator(batch, numExamples)``)."""

    def __init__(self, batch_size: int = 150,
                 n_examples: Optional[int] = None, shuffle: bool = True,
                 seed: int = 123):
        feats, onehot = load_iris_arrays()
        if shuffle:
            # deterministic pre-shuffle so truncation keeps all classes
            # (the file is class-ordered)
            order = np.random.default_rng(seed).permutation(len(feats))
            feats, onehot = feats[order], onehot[order]
        if n_examples is not None:
            feats, onehot = feats[:n_examples], onehot[:n_examples]
        super().__init__(feats, onehot, batch_size, shuffle=shuffle,
                         seed=seed)


def _synthetic_cifar(n: int, train: bool, seed: int):
    """Class-conditional 32x32 RGB blobs: mean color + textured shape
    per class, noise-jittered — separable but not trivial."""
    rng = np.random.default_rng(seed + (0 if train else 1))
    labels = rng.integers(0, 10, n).astype(np.int32)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32) / 31.0
    imgs = np.empty((n, 32, 32, 3), np.float32)
    base = np.random.default_rng(7)          # fixed class palettes
    palette = base.random((10, 3)).astype(np.float32)
    freq = base.integers(1, 5, size=(10, 2))
    for i in range(n):
        c = labels[i]
        tex = 0.5 + 0.5 * np.sin(
            freq[c, 0] * np.pi * yy + freq[c, 1] * np.pi * xx
            + rng.random() * 2 * np.pi)
        img = palette[c][None, None, :] * tex[..., None]
        imgs[i] = np.clip(img + rng.normal(0, 0.08, (32, 32, 3)), 0, 1)
    return (imgs * 255).astype(np.uint8), labels


def _load_real_cifar(train: bool):
    d = os.environ.get("DL4J_TPU_CIFAR_DIR")
    if not d or not os.path.isdir(d):
        return None
    names = [f"data_batch_{i}.bin" for i in range(1, 6)] if train \
        else ["test_batch.bin"]
    imgs, labels = [], []
    for name in names:
        p = os.path.join(d, name)
        if not os.path.exists(p):
            return None
        raw = np.fromfile(p, np.uint8).reshape(-1, 3073)
        labels.append(raw[:, 0].astype(np.int32))
        imgs.append(raw[:, 1:].reshape(-1, 3, 32, 32)
                    .transpose(0, 2, 3, 1))  # CHW binary -> NHWC
    return np.concatenate(imgs), np.concatenate(labels)


class Cifar10DataSetIterator(ArrayDataSetIterator):
    """CIFAR-10 iterator (``Cifar10DataSetIterator``): NHWC [b,32,32,3]
    float in [0,1], one-hot labels [b,10].  Real binary batches load
    from ``DL4J_TPU_CIFAR_DIR``; otherwise a deterministic synthetic
    stand-in (``is_synthetic``)."""

    def __init__(self, batch_size: int, train: bool = True,
                 n_examples: Optional[int] = None, seed: int = 123,
                 shuffle: bool = True):
        real = _load_real_cifar(train)
        if real is not None:
            images, labels = real
        else:
            n = n_examples or (50000 if train else 10000)
            images, labels = _synthetic_cifar(n, train, seed)
        if n_examples is not None:
            images, labels = images[:n_examples], labels[:n_examples]
        feats = images.astype(np.float32) / 255.0
        onehot = np.eye(10, dtype=np.float32)[labels]
        super().__init__(feats, onehot, batch_size,
                         shuffle=shuffle and train, seed=seed)
        self.is_synthetic = real is None
