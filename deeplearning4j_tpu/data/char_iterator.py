"""Character iterator for char-RNN language modelling.

Parity with the dl4j-examples ``CharacterIterator`` used by
``LSTMCharModellingExample`` (the GravesLSTM char-RNN baseline config in
BASELINE.json): one-hot [b, t, vocab] features, labels = next character,
random example offsets per epoch.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterator import DataSetIterator


class CharacterIterator(DataSetIterator):
    def __init__(self, text: str, seq_length: int = 64, batch: int = 32,
                 valid_chars: Optional[Sequence[str]] = None,
                 seed: int = 12345):
        if valid_chars is None:
            valid_chars = sorted(set(text))
        self.chars: List[str] = list(valid_chars)
        self.char_to_idx = {c: i for i, c in enumerate(self.chars)}
        self.data = np.asarray(
            [self.char_to_idx[c] for c in text if c in self.char_to_idx],
            np.int32)
        if len(self.data) <= seq_length + 1:
            raise ValueError("Text shorter than one sequence")
        self.seq_length = seq_length
        self.batch = batch
        self._rng = np.random.default_rng(seed)
        self._seed = seed

    @property
    def vocab_size(self) -> int:
        return len(self.chars)

    def total_outcomes(self):
        return self.vocab_size

    def batch_size(self):
        return self.batch

    def __iter__(self):
        n_examples = (len(self.data) - 1) // self.seq_length
        starts = self._rng.permutation(n_examples) * self.seq_length
        eye = np.eye(self.vocab_size, dtype=np.float32)
        for i in range(0, len(starts) - self.batch + 1, self.batch):
            xs, ys = [], []
            for s in starts[i:i + self.batch]:
                window = self.data[s:s + self.seq_length + 1]
                xs.append(eye[window[:-1]])
                ys.append(eye[window[1:]])
            yield DataSet(np.stack(xs), np.stack(ys))

    def reset(self):
        # Keep the RNG rolling: each epoch draws a FRESH permutation of
        # example offsets (dl4j-examples CharacterIterator reshuffles on
        # reset; re-seeding here would replay epoch 1's order forever).
        pass

    def encode(self, s: str) -> np.ndarray:
        eye = np.eye(self.vocab_size, dtype=np.float32)
        return eye[[self.char_to_idx[c] for c in s]][None]

    def decode(self, indices) -> str:
        return "".join(self.chars[int(i)] for i in np.asarray(indices))


def sample_characters(model, iterator: CharacterIterator, init: str,
                      n_chars: int = 200, temperature: float = 1.0,
                      seed: int = 0) -> str:
    """Generate text with ``rnn_time_step`` (the dl4j-examples
    ``sampleCharactersFromNetwork`` loop: prime with `init`, then feed each
    sampled char back one step at a time)."""
    rng = np.random.default_rng(seed)
    model.rnn_clear_previous_state()
    probs = np.asarray(model.rnn_time_step(iterator.encode(init)))[0, -1]
    out = list(init)
    eye = np.eye(iterator.vocab_size, dtype=np.float32)
    for _ in range(n_chars):
        logits = np.log(np.maximum(probs, 1e-12)) / temperature
        p = np.exp(logits - logits.max())
        p /= p.sum()
        idx = int(rng.choice(iterator.vocab_size, p=p))
        out.append(iterator.chars[idx])
        probs = np.asarray(model.rnn_time_step(eye[idx][None]))[0]
    model.rnn_clear_previous_state()
    return "".join(out)
