"""``concurrency_lint`` — static lock-discipline analysis.

For every class in a module the pass infers which instance attributes
are LOCK-GUARDED, then flags accesses to those attributes that happen
outside the lock on any code path another thread can run.  This is a
static race detector for the host-side schedulers
(``generation_server.py``, ``inference.py``, ``telemetry/registry.py``)
— the bug class PR 3 fixed by hand (scheduler state mutated outside
the watchdog's lock) is exactly what it catches.

Inference, per class:

* **lock attributes**: ``self.X = threading.Lock()/RLock()/Condition()``
  assignments, plus any attribute whose name contains ``lock``;
* **guarded attributes**: targets of ``self.Y = ...`` stores (plain,
  augmented, and element stores ``self.Y[i] = ...`` / ``del
  self.Y[i]``) that appear lexically inside a ``with self.<lock>:``
  block, or anywhere inside a method whose name ends in ``_locked``
  (the "caller holds the lock" convention);
* **checked entry points**: methods named as ``threading.Thread(
  target=self.X)`` targets, plus — when the class starts threads or
  owns a lock (either is an advertisement of concurrent use) — every
  public method; plus everything transitively reachable from those via
  ``self.meth()`` calls.  Base classes defined in the same module are
  folded in so ``Counter.inc -> _Family._default`` resolves.

``__init__`` (and ``__enter__``) are exempt: construction happens
before the object is shared.  Methods ending in ``_locked`` are exempt
as access sites (their contract is "caller holds the lock") but calls
to them from outside a ``with self.<lock>:`` block are themselves
flagged.

Aliasing through locals IS resolved (ISSUE 10): ``s = self`` (and
chains, ``t = s``) makes ``with s._lock:`` a lock region and
``s.attr`` a self-attribute access for every rule above — hiding an
unguarded write behind a one-letter alias no longer blinds the pass.
A later rebind of the alias to something else is NOT tracked (the
name counts as ``self`` for the whole method); that pattern reads as
a bug in its own right.

Known blind spots (ROADMAP): lock objects not stored on ``self``
(module-level locks, locks passed in — partially covered by CONC205's
lock provenance), and cross-module subclassing.

Rules
-----
CONC201 (error)   write to a lock-guarded attribute outside the lock
                  in a thread-reachable method.
CONC202 (warning) read of a lock-guarded attribute outside the lock in
                  a thread-reachable method.
CONC203 (error)   ``*_locked`` method called outside a ``with
                  self.<lock>:`` block.
CONC204 (warning) lock-free class shares mutable state: the class
                  starts a thread, has no lock at all, and an
                  attribute is written outside ``__init__`` and also
                  accessed from another checked method.
CONC205 (error)   module-level mutable state (a module dict/list, or a
                  ``global``-rebound name) written WITHOUT a provable
                  lock from a function another thread can reach —
                  thread reachability is computed over the whole
                  package call graph (Thread targets anywhere,
                  including cross-module ``target=mod.fn``, plus
                  public methods of lock/thread-owning classes), so
                  the write site and the thread spawn may live in
                  different modules.  Emitted by :func:`lint_package`.
CONC206 (error on store / warning on load) cross-module guarded-attr
                  access: an object of a lock-owning class (typed via
                  a class annotation like ``server:
                  "GenerationServer"``, a constructor assignment, or a
                  typed ``self.<attr>``) has one of its LOCK-GUARDED
                  attributes accessed in a NON-owning module outside a
                  ``with obj.<lock>:`` block.  Emitted by
                  :func:`lint_package`.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from deeplearning4j_tpu.analysis.astutil import (FuncDef, add_parents,
                                                 attr_accesses, dotted,
                                                 subscript_store_bases)
from deeplearning4j_tpu.analysis.findings import Finding

_EXEMPT_METHODS = {"__init__", "__new__", "__enter__", "__post_init__",
                   "__del__"}
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}


def _is_lock_ctor(expr: ast.AST) -> bool:
    if not isinstance(expr, ast.Call):
        return False
    parts = dotted(expr.func)
    return parts is not None and parts[-1] in _LOCK_CTORS


def _self_aliases(method: ast.AST) -> Set[str]:
    """Local names bound to ``self`` inside ``method`` — ``s = self``
    and chains (``t = s``) — to a fixed point.  Rebinding an alias to
    something else later is not tracked: the name counts as ``self``
    for the whole method (conservative for guarded-inference, and the
    pattern itself reads as a bug)."""
    aliases: Set[str] = {"self"}
    changed = True
    while changed:
        changed = False
        for n in ast.walk(method):
            if not (isinstance(n, ast.Assign)
                    and isinstance(n.value, ast.Name)
                    and n.value.id in aliases):
                continue
            for t in n.targets:
                if isinstance(t, ast.Name) and t.id not in aliases:
                    aliases.add(t.id)
                    changed = True
    return aliases


def _attr_accesses_aliased(node: ast.AST, aliases: Set[str]):
    """``attr_accesses`` over every self-alias base."""
    for base in aliases:
        yield from attr_accesses(node, base)


def _subscript_stores_aliased(node: ast.AST, aliases: Set[str]):
    for base in aliases:
        yield from subscript_store_bases(node, base)


class _ClassInfo:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.name = node.name
        self.bases: List[str] = [p[-1] for p in
                                 (dotted(b) for b in node.bases) if p]
        self.methods: Dict[str, ast.AST] = {
            n.name: n for n in node.body if isinstance(n, FuncDef)}
        self.lock_attrs: Set[str] = set()
        self.guarded: Set[str] = set()
        self.thread_targets: Set[str] = set()
        self.starts_threads = False
        self.stores_by_method: Dict[str, Set[str]] = {}
        self.loads_by_method: Dict[str, Set[str]] = {}
        self.calls_by_method: Dict[str, Set[str]] = {}


class _ModuleLint:
    def __init__(self, tree: ast.Module, path: str):
        self.tree = tree
        self.path = path
        self.parents = add_parents(tree)
        self.findings: List[Finding] = []
        self.classes: Dict[str, _ClassInfo] = {}

    def run(self) -> List[Finding]:
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = self._scan_class(node)
        for ci in self.classes.values():
            self._merge_bases(ci)
        for ci in self.classes.values():
            self._lint_class(ci)
        return self.findings

    # -- per-class fact gathering --------------------------------------
    def _scan_class(self, node: ast.ClassDef) -> _ClassInfo:
        ci = _ClassInfo(node)
        # lock attributes
        for n in ast.walk(node):
            if isinstance(n, ast.Assign) and _is_lock_ctor(n.value):
                for t in n.targets:
                    parts = dotted(t)
                    if parts and parts[0] == "self" and len(parts) == 2:
                        ci.lock_attrs.add(parts[1])
        for _, name, _ in attr_accesses(node):
            if "lock" in name.lower():
                ci.lock_attrs.add(name)
        # alias-aware lock-name pre-pass: a lock only ever touched as
        # ``s._lock`` must still register before guarded inference runs
        for m in ci.methods.values():
            aliases = _self_aliases(m)
            if aliases != {"self"}:
                for _, name, _ in _attr_accesses_aliased(m, aliases):
                    if "lock" in name.lower():
                        ci.lock_attrs.add(name)
        # thread targets
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                parts = dotted(n.func)
                if parts and parts[-1] == "Thread":
                    ci.starts_threads = True
                    for kw in n.keywords:
                        if kw.arg == "target":
                            tp = dotted(kw.value)
                            if tp and tp[0] == "self" and len(tp) == 2:
                                ci.thread_targets.add(tp[1])
        # guarded attributes + per-method access maps (alias-aware:
        # ``s = self`` makes ``s.attr`` a self access and ``with
        # s._lock:`` a lock region)
        for mname, m in ci.methods.items():
            aliases = _self_aliases(m)
            in_lock = self._locked_regions(m, ci.lock_attrs, aliases)
            whole_locked = mname.endswith("_locked")
            stores, loads = set(), set()
            for attr_node, name, kind in _attr_accesses_aliased(
                    m, aliases):
                if name in ci.lock_attrs:
                    continue
                if kind == "store":
                    stores.add(name)
                    if whole_locked or attr_node in in_lock:
                        ci.guarded.add(name)
                else:
                    loads.add(name)
            for attr_node, name in _subscript_stores_aliased(m, aliases):
                if name in ci.lock_attrs:
                    continue
                stores.add(name)
                if whole_locked or attr_node in in_lock:
                    ci.guarded.add(name)
            ci.stores_by_method[mname] = stores
            ci.loads_by_method[mname] = loads
            ci.calls_by_method[mname] = {
                p[1] for p in (dotted(c.func) for c in ast.walk(m)
                               if isinstance(c, ast.Call))
                if p and p[0] == "self" and len(p) == 2}
        return ci

    def _locked_regions(self, method: ast.AST, lock_attrs: Set[str],
                        aliases: Optional[Set[str]] = None
                        ) -> Set[ast.AST]:
        """All nodes lexically inside a ``with self.<lock>:`` block —
        ``self`` meaning any local alias of it when ``aliases`` is
        given (``s = self; with s._lock:``)."""
        bases = aliases if aliases is not None else {"self"}
        inside: Set[ast.AST] = set()
        for n in ast.walk(method):
            if not isinstance(n, ast.With):
                continue
            if not any(
                    (lambda p: p and p[0] in bases and len(p) == 2
                     and p[1] in lock_attrs)(dotted(item.context_expr))
                    for item in n.items):
                continue
            for stmt in n.body:
                for sub in ast.walk(stmt):
                    inside.add(sub)
        return inside

    def _merge_bases(self, ci: _ClassInfo, depth: int = 0) -> None:
        """Fold same-module base classes' facts into the subclass so
        ``Counter.inc -> _Family._default`` style chains resolve."""
        if depth > 4:
            return
        for bname in ci.bases:
            base = self.classes.get(bname)
            if base is None:
                continue
            self._merge_bases(base, depth + 1)
            ci.lock_attrs |= base.lock_attrs
            ci.guarded |= base.guarded
            ci.thread_targets |= base.thread_targets
            ci.starts_threads |= base.starts_threads
            for mname, m in base.methods.items():
                if mname not in ci.methods:
                    ci.methods[mname] = m
                    ci.stores_by_method[mname] = \
                        base.stores_by_method.get(mname, set())
                    ci.loads_by_method[mname] = \
                        base.loads_by_method.get(mname, set())
                    ci.calls_by_method[mname] = \
                        base.calls_by_method.get(mname, set())

    # -- rule evaluation -----------------------------------------------
    def _reachable_methods(self, ci: _ClassInfo) -> Set[str]:
        entries = set(ci.thread_targets)
        if ci.starts_threads or ci.lock_attrs:
            entries |= {m for m in ci.methods if not m.startswith("_")}
        seen: Set[str] = set()
        frontier = [m for m in entries if m in ci.methods]
        while frontier:
            m = frontier.pop()
            if m in seen:
                continue
            seen.add(m)
            for callee in ci.calls_by_method.get(m, ()):
                if callee in ci.methods and callee not in seen:
                    frontier.append(callee)
        return seen

    def _lint_class(self, ci: _ClassInfo) -> None:
        reachable = self._reachable_methods(ci)
        if ci.lock_attrs and ci.guarded:
            self._lint_guarded(ci, reachable)
        if ci.lock_attrs:
            self._lint_locked_suffix_calls(ci)
        if not ci.lock_attrs and ci.starts_threads:
            self._lint_lockfree_shared(ci, reachable)

    def _lint_guarded(self, ci: _ClassInfo, reachable: Set[str]) -> None:
        for mname in sorted(reachable):
            if mname in _EXEMPT_METHODS or mname.endswith("_locked"):
                continue
            m = ci.methods.get(mname)
            if m is None:
                continue
            aliases = _self_aliases(m)
            in_lock = self._locked_regions(m, ci.lock_attrs, aliases)
            qn = f"{ci.name}.{mname}"
            reported: Set[Tuple[str, str, int]] = set()

            def check(attr_node: ast.AST, name: str, kind: str) -> None:
                if name not in ci.guarded or attr_node in in_lock:
                    return
                key = (name, kind, attr_node.lineno)
                if key in reported:
                    return
                reported.add(key)
                if kind == "store":
                    self.findings.append(Finding(
                        "CONC201", "error", self.path,
                        attr_node.lineno, qn,
                        f"write to lock-guarded attribute "
                        f"'self.{name}' outside the lock",
                        f"wrap in 'with self.{sorted(ci.lock_attrs)[0]}:'"
                    ))
                else:
                    self.findings.append(Finding(
                        "CONC202", "warning", self.path,
                        attr_node.lineno, qn,
                        f"read of lock-guarded attribute "
                        f"'self.{name}' outside the lock",
                        "read under the lock, or document why the "
                        "race is benign and baseline this finding"))

            sub_store_nodes = {id(a) for a, _ in
                               _subscript_stores_aliased(m, aliases)}
            for attr_node, name, kind in _attr_accesses_aliased(
                    m, aliases):
                if id(attr_node) in sub_store_nodes:
                    kind = "store"
                check(attr_node, name, kind)

    def _lint_locked_suffix_calls(self, ci: _ClassInfo) -> None:
        for mname, m in ci.methods.items():
            if mname.endswith("_locked"):
                continue     # _locked calling _locked: caller's caller
            aliases = _self_aliases(m)
            in_lock = self._locked_regions(m, ci.lock_attrs, aliases)
            for c in ast.walk(m):
                if not isinstance(c, ast.Call):
                    continue
                parts = dotted(c.func)
                if not (parts and parts[0] in aliases
                        and len(parts) == 2
                        and parts[1].endswith("_locked")):
                    continue
                if c not in in_lock:
                    self.findings.append(Finding(
                        "CONC203", "error", self.path, c.lineno,
                        f"{ci.name}.{mname}",
                        f"'self.{parts[1]}()' called outside a 'with "
                        f"self.<lock>:' block — the _locked suffix "
                        "declares the caller must hold the lock",
                        "move the call inside the locked region"))

    def _lint_lockfree_shared(self, ci: _ClassInfo,
                              reachable: Set[str]) -> None:
        checked = {m for m in reachable
                   if m not in _EXEMPT_METHODS}
        for attr in sorted({
                a for m in checked
                for a in ci.stores_by_method.get(m, ())}):
            writers = {m for m in checked
                       if attr in ci.stores_by_method.get(m, ())}
            readers = {m for m in checked
                       if attr in ci.loads_by_method.get(m, ())}
            if writers and (readers | writers) - writers or \
                    len(writers) > 1:
                first = ci.methods[sorted(writers)[0]]
                self.findings.append(Finding(
                    "CONC204", "warning", self.path, first.lineno,
                    f"{ci.name}.{sorted(writers)[0]}",
                    f"attribute 'self.{attr}' is written here and "
                    f"accessed from {sorted((readers | writers) - {sorted(writers)[0]}) or '[same method]'} "
                    "with no lock in a thread-spawning class",
                    "guard with a threading.Lock, or use a "
                    "threading.Event for flags"))


def lint_tree(tree: ast.Module, path: str) -> List[Finding]:
    return _ModuleLint(tree, path).run()


def lint_source(source: str, path: str = "<string>") -> List[Finding]:
    return lint_tree(ast.parse(source), path)


# ---------------------------------------------------------------------------
# cross-module pass (CONC205 / CONC206) over the package index
# ---------------------------------------------------------------------------

def lint_package(index) -> List[Finding]:
    """Lock discipline the per-class pass cannot see: module-level
    state raced by threads spawned in OTHER modules, and lock-owning
    objects whose guarded attributes are poked from outside their
    defining module."""
    findings: List[Finding] = []
    seeds = index.thread_seeds()
    parent = index.closure(seeds)

    # -- CONC205: unguarded module-state writes on thread-reachable
    #    paths ---------------------------------------------------------
    for fid in sorted(parent):
        fn = index.functions[fid]
        if not fn["module_writes"]:
            continue
        mod = index.func_module[fid]
        s = index.modules[mod]
        path = s["path"]
        qn = fid.split("::", 1)[1]
        mname = qn.rsplit(".", 1)[-1]
        if mname in _EXEMPT_METHODS or mname.endswith("_locked"):
            # same convention the per-class pass honors: a _locked
            # suffix declares the CALLER holds the lock
            continue
        reported = set()
        for line, name, guarded in fn["module_writes"]:
            if guarded:
                continue
            kind = s["module_state"].get(name, {}).get("kind", "other")
            if kind == "lock":
                continue
            key = (name, line)
            if key in reported:
                continue
            reported.add(key)
            chain = index.chain(parent, fid)
            findings.append(Finding(
                "CONC205", "error", path, line, qn,
                f"module-level state '{name}' written without a lock "
                f"in thread-reachable '{qn}' (reached via {chain})",
                f"guard the write with a module-level threading.Lock "
                f"(e.g. 'with _LOCK:'), or make '{name}' thread-local"))

    # -- CONC206: guarded attrs of a foreign lock-owning class --------
    for fid, fn in sorted(index.functions.items()):
        if not fn["foreign"]:
            continue
        mod = index.func_module[fid]
        path = index.modules[mod]["path"]
        qn = fid.split("::", 1)[1]
        reported = set()
        for line, type_parts, attr, kind, locked in fn["foreign"]:
            if locked:
                continue
            hit = index.resolve_class(mod, type_parts)
            if hit is None or hit[0] == mod:
                continue          # local class: CONC201/202 territory
            facts = index.class_facts(*hit)
            if attr not in facts["guarded"] or not facts["lock_attrs"]:
                continue
            key = (attr, kind, line)
            if key in reported:
                continue
            reported.add(key)
            owner = f"{hit[1]} ({index.modules[hit[0]]['path']})"
            lock = sorted(facts["lock_attrs"])[0]
            if kind == "store":
                findings.append(Finding(
                    "CONC206", "error", path, line, qn,
                    f"write to '{attr}' — an attribute of {owner} "
                    f"guarded by its '{lock}' — outside the lock, in "
                    "a module that does not own it",
                    f"wrap the access in 'with <obj>.{lock}:'"))
            else:
                findings.append(Finding(
                    "CONC206", "warning", path, line, qn,
                    f"read of '{attr}' — an attribute of {owner} "
                    f"guarded by its '{lock}' — outside the lock, in "
                    "a module that does not own it",
                    f"read under 'with <obj>.{lock}:', or document "
                    "why the race is benign and baseline this"))
    return findings
