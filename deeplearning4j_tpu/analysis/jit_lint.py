"""``jit_lint`` — trace-safety static analysis.

Identifies the module's TRACE CONTEXTS — functions that execute under a
jax tracer — and flags host-side impurity inside them.  A traced
function runs ONCE per compilation, not once per call: a ``time.time()``
inside it bakes the trace-time clock into the compiled program, a
``print`` fires only on recompiles, a ``self.x = ...`` mutates host
state at trace time, and a Python ``if`` on a traced value either
crashes (ConcretizationTypeError) or silently specializes.

Trace contexts are found purely syntactically (no imports, no
execution):

* functions decorated with ``jit``/``pjit`` (including
  ``@partial(jax.jit, ...)``);
* functions passed to ``jax.jit(fn, ...)`` / ``pjit`` / ``shard_map`` /
  ``lax.scan`` / ``lax.while_loop`` / ``lax.cond`` / ``lax.fori_loop``
  / ``vmap`` / ``pmap`` / ``grad`` call sites (the repo's dominant
  idiom: a nested ``def tick(...)`` returned as ``jax.jit(tick,
  donate_argnums=...)``);
* transitively, functions CALLED from a trace context in the same
  module — bare names resolve through the enclosing scopes,
  ``obj.meth(...)`` resolves to same-module methods by name.

Known blind spots (ROADMAP): tracer flow across module boundaries, and
functions reaching jit only through data (callback tables).

Rules
-----
JIT101 (error)   host-impure call: ``time.*`` / ``random.*`` /
                 ``np.random.*`` / ``print`` / ``input`` / ``open`` /
                 ``datetime.*`` inside a trace context (``jax.random``
                 is fine — it is traced PRNG, not host PRNG).
JIT102 (warning) host-state mutation: ``global`` declarations or
                 ``self.<attr>`` stores inside a trace context.
JIT103 (warning) tracer-dependent Python branch: ``if``/``while`` whose
                 test reads a traced (non-static) parameter of the
                 trace context.  Shape-derived tests (``len``,
                 ``.shape``/``.ndim``/``.dtype``), ``is None`` checks
                 and ``isinstance`` are static and skipped.
JIT104 (error)   non-hashable static argument: a call site of a jitted
                 function passes a list/dict/set (literal or
                 constructor) at a ``static_argnums`` position.
JIT105 (warning) donated-buffer reuse: an argument at a
                 ``donate_argnums`` position of a jitted call is read
                 again after the call without an intervening rebind —
                 the buffer may already be invalidated in place.
JIT106 (error / warning) cross-module trace impurity: a host-impure
                 call (error) or host-state mutation (warning) in a
                 function reached FROM a trace context ACROSS a module
                 boundary — the blind spot the per-module pass
                 documents.  Emitted by :func:`lint_package` over the
                 :mod:`~deeplearning4j_tpu.analysis.package_index`
                 call graph; the finding lands on the impure function's
                 own module with the reaching chain in the message.

Annotations: parameters annotated ``Static`` / ``Traced``
(:mod:`~deeplearning4j_tpu.analysis.annotations`) override JIT103's
name heuristics — ``Static`` suppresses the rule for that parameter
(like ``static_argnums``), ``Traced`` forces it even through reads the
heuristics would excuse (attribute access, membership).  Unannotated
parameters keep the heuristic behavior.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.analysis.astutil import (FuncDef, FuncIndex,
                                                 add_parents, dotted)
from deeplearning4j_tpu.analysis.findings import Finding

# wrapper name -> positions of the traced-function argument(s)
_TRACED_ARG_POS: Dict[str, Tuple[int, ...]] = {
    "jit": (0,), "pjit": (0,), "shard_map": (0,), "scan": (0,),
    "while_loop": (0, 1), "cond": (1, 2), "fori_loop": (2,),
    "vmap": (0,), "pmap": (0,), "grad": (0,), "value_and_grad": (0,),
    "checkpoint": (0,), "remat": (0,), "custom_jvp": (0,),
    "custom_vjp": (0,), "eval_shape": (0,),
}
# dotted roots under which the wrapper names are trusted; a bare name
# (``from jax import jit``) is accepted for the unambiguous ones
_TRACE_ROOTS = {"jax", "lax", "pjit"}
_BARE_OK = {"jit", "pjit", "shard_map", "vmap", "pmap", "grad",
            "value_and_grad"}

_HOST_CALL_ROOTS = {"time", "random", "datetime"}
_HOST_BUILTINS = {"print", "input", "open"}
_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size"}
# calls whose results are static under tracing (shape/type/structure
# queries); a param appearing only inside one is not a tracer read
_STATIC_CALLS = {"len", "isinstance", "getattr", "hasattr", "range",
                 "type", "int", "bool", "float", "str", "tuple",
                 "ndim", "shape", "rank", "tree_structure"}
# methods through which an attribute access DOES read traced data —
# any other `x.attr` in a test is treated as static config
_TRACER_REDUCERS = {"any", "all", "item", "sum", "max", "min", "mean",
                    "prod"}


def host_impure_detail(call: ast.Call) -> Optional[str]:
    """The dotted name when ``call`` is a host-impure operation
    (``time.*`` / ``random.*`` / ``np.random.*`` / ``print`` / ...)
    — shared between the per-module JIT101 check and the
    cross-module JIT106 fact extraction (package_index)."""
    parts = dotted(call.func)
    if parts is None:
        return None
    impure = (
        (parts[0] in _HOST_CALL_ROOTS and len(parts) > 1)
        or (len(parts) == 1 and parts[0] in _HOST_BUILTINS)
        or (len(parts) >= 2 and parts[0] in ("np", "numpy")
            and parts[1] == "random"))
    return ".".join(parts) if impure else None


def _is_trace_wrapper(parts: Tuple[str, ...]) -> Optional[str]:
    """The wrapper name when ``parts`` spells a tracing transform."""
    last = parts[-1]
    if last not in _TRACED_ARG_POS:
        return None
    if len(parts) == 1:
        return last if last in _BARE_OK else None
    return last if parts[0] in _TRACE_ROOTS else None


def _static_names_from_call(call: ast.Call, fn: ast.AST) -> Set[str]:
    """Parameter NAMES of ``fn`` made static by a jit call's
    static_argnums/static_argnames keywords."""
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args] \
        if isinstance(fn, FuncDef) else []
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    out.add(n.value)
        elif kw.arg == "static_argnums":
            for i in _int_elems(kw.value):
                if 0 <= i < len(params):
                    out.add(params[i])
    return out


def _int_elems(node: ast.AST) -> List[int]:
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, int) \
                and not isinstance(n.value, bool):
            out.append(n.value)
    return out


class _ModuleLint:
    def __init__(self, tree: ast.Module, path: str):
        self.tree = tree
        self.path = path
        self.parents = add_parents(tree)
        self.index = FuncIndex(tree, self.parents)
        self.findings: List[Finding] = []
        # traced def -> static param names (union over entry sites)
        self.traced: Dict[ast.AST, Set[str]] = {}
        # dotted target name -> (static positions, donate positions)
        self.jitted_objects: Dict[Tuple[str, ...],
                                  Tuple[Set[int], Set[int]]] = {}

    # -- entry discovery ----------------------------------------------
    def collect_entries(self) -> None:
        for fn in self.index.defs:
            for deco in fn.decorator_list:
                call = deco if isinstance(deco, ast.Call) else None
                target = call.func if call is not None else deco
                parts = dotted(target)
                if parts and _is_trace_wrapper(parts):
                    self._mark(fn, _static_names_from_call(call, fn)
                               if call else set())
                elif call is not None and parts is None:
                    pass
                elif call is not None and parts and \
                        parts[-1] == "partial":
                    # @partial(jax.jit, static_argnums=...)
                    inner = dotted(call.args[0]) if call.args else None
                    if inner and _is_trace_wrapper(inner):
                        self._mark(fn, _static_names_from_call(call, fn))
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            parts = dotted(node.func)
            wrapper = _is_trace_wrapper(parts) if parts else None
            if wrapper is None:
                continue
            for pos in _TRACED_ARG_POS[wrapper]:
                if pos >= len(node.args):
                    continue
                for target in self._resolve_funcs(node.args[pos], node):
                    self._mark(target,
                               _static_names_from_call(node, target))
            if wrapper in ("jit", "pjit"):
                self._register_jitted_object(node)

    def _resolve_funcs(self, expr: ast.AST, at: ast.AST) -> List[ast.AST]:
        if isinstance(expr, ast.Lambda):
            return []          # lambdas: too small to host impurity
        parts = dotted(expr)
        if parts is None:
            return []
        if len(parts) == 1:
            hit = self.index.resolve_name(parts[0], at)
            return [hit] if hit is not None else []
        return self.index.resolve_attr_method(parts[-1], at)

    def _mark(self, fn: ast.AST, static_names: Set[str]) -> None:
        if fn in self.traced:
            self.traced[fn] |= static_names
            return
        self.traced[fn] = set(static_names)
        # transitive: calls + nested defs inside this trace context
        for node in ast.walk(fn):
            if isinstance(node, FuncDef) and node is not fn:
                self._mark(node, set())
            if isinstance(node, ast.Call):
                for target in self._resolve_funcs(node.func, node):
                    if target not in self.traced:
                        self._mark(target, set())

    def _register_jitted_object(self, call: ast.Call) -> None:
        """Track ``X = jax.jit(fn, static_argnums=…, donate_argnums=…)``
        so call sites of ``X`` can be checked (JIT104/JIT105).  Also
        handles the chained ``a = b = jit(...)`` and the immediate
        ``jit(fn, ...)(args)`` forms."""
        static: Set[int] = set()
        donate: Set[int] = set()
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                static.update(_int_elems(kw.value))
            elif kw.arg == "donate_argnums":
                donate.update(_int_elems(kw.value))
        if not static and not donate:
            return
        parent = self.parents.get(call)
        targets: List[ast.AST] = []
        if isinstance(parent, ast.Assign):
            targets = list(parent.targets)
        elif isinstance(parent, (ast.AnnAssign, ast.AugAssign)):
            targets = [parent.target]
        elif isinstance(parent, ast.Call) and parent.func is call:
            # immediate invocation: check this very call site
            self._check_jitted_call(parent, static, donate)
            return
        for t in targets:
            parts = dotted(t)
            if parts:
                self.jitted_objects[parts] = (static, donate)

    # -- rule evaluation ----------------------------------------------
    def run(self) -> List[Finding]:
        self.collect_entries()
        for fn, static_names in self.traced.items():
            self._lint_traced_body(fn, static_names)
        self._lint_jitted_call_sites()
        return self.findings

    def _emit(self, rule: str, severity: str, node: ast.AST,
              symbol: str, message: str, hint: str = "") -> None:
        self.findings.append(Finding(
            rule=rule, severity=severity, path=self.path,
            line=getattr(node, "lineno", 0), symbol=symbol,
            message=message, fix_hint=hint))

    def _body_nodes(self, fn: ast.AST):
        """Walk ``fn`` excluding nested function bodies (each traced
        nested def is linted as its own context)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            n = stack.pop()
            yield n
            if not isinstance(n, FuncDef + (ast.Lambda,)):
                stack.extend(ast.iter_child_nodes(n))

    def _lint_traced_body(self, fn: ast.AST, static_names: Set[str]):
        from deeplearning4j_tpu.analysis.annotations import (
            param_annotations)
        qn = self.index.qualname[fn]
        params = {a.arg for a in
                  fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs}
        # annotation convention beats the heuristics: Static params
        # drop out entirely, Traced params are checked even through
        # reads the heuristics would excuse
        static_ann, traced_ann, _ = param_annotations(fn)
        params -= static_names | static_ann | {"self", "cls"}
        forced = traced_ann & params
        for node in self._body_nodes(fn):
            if isinstance(node, ast.Call):
                self._check_host_call(node, qn)
            elif isinstance(node, ast.Global):
                self._emit(
                    "JIT102", "warning", node, qn,
                    f"'global {', '.join(node.names)}' inside "
                    f"jit-traced '{fn.name}' mutates host state at "
                    "trace time, not per call",
                    "return the value and thread it through the caller")
            elif isinstance(node, (ast.Assign, ast.AugAssign,
                                   ast.AnnAssign)):
                self._check_self_mutation(node, fn, qn)
            elif isinstance(node, (ast.If, ast.While)):
                self._check_tracer_branch(node, params, forced, fn, qn)

    def _check_host_call(self, call: ast.Call, qn: str) -> None:
        name = host_impure_detail(call)
        if name is None:
            return
        self._emit(
            "JIT101", "error", call, qn,
            f"host-impure call '{name}' inside a jit-traced function — "
            "it executes once at trace time and its result is baked "
            "into the compiled program",
            "hoist it out of the traced function (pass the value in), "
            "or use jax.random / jax.debug.print")

    def _check_self_mutation(self, node: ast.AST, fn: ast.AST,
                             qn: str) -> None:
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            for tt in ast.walk(t):
                base = tt
                while isinstance(base, ast.Subscript):
                    base = base.value
                if isinstance(base, ast.Attribute) and \
                        isinstance(base.value, ast.Name) and \
                        base.value.id == "self":
                    self._emit(
                        "JIT102", "warning", node, qn,
                        f"store to self.{base.attr} inside jit-traced "
                        f"'{fn.name}' happens at trace time (once per "
                        "compilation), not per call",
                        "return the new value instead of mutating, or "
                        "hoist the caching out of the traced function")

    def _check_tracer_branch(self, node: ast.AST, params: Set[str],
                             forced: Set[str], fn: ast.AST,
                             qn: str) -> None:
        if not params:
            return
        raise_only = isinstance(node, ast.If) and all(
            isinstance(s, ast.Raise) for s in node.body)
        # raise-only guards are exempt for HEURISTIC params (raising at
        # trace time is the point of a validation guard) — but not for
        # declared-Traced ones: `if x.flag: raise` on a tracer still
        # fails with TracerBoolConversionError before it can raise
        hot = set() if raise_only else _dynamic_names(node.test)
        # a declared-Traced param fires on ANY read in the test, even
        # through forms the heuristics treat as static (attr reads,
        # membership): the author said it is a tracer
        raw = {n.id for n in ast.walk(node.test)
               if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}
        bad = sorted((hot & params) | (raw & forced))
        if not bad:
            return
        kind = "if" if isinstance(node, ast.If) else "while"
        self._emit(
            "JIT103", "warning", node, qn,
            f"Python '{kind}' on traced parameter(s) "
            f"{', '.join(bad)} inside '{fn.name}' — branching on a "
            "tracer fails (or silently specializes when the value is "
            "concrete at trace time)",
            "use jnp.where/lax.cond/lax.while_loop, or mark the "
            "parameter static_argnums")

    # -- call sites of jitted objects (JIT104/JIT105) ------------------
    def _lint_jitted_call_sites(self) -> None:
        if not self.jitted_objects:
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            parts = dotted(node.func)
            if parts is None:
                continue
            spec = self.jitted_objects.get(parts)
            if spec is None and len(parts) > 1:
                # "self._tick" registered, called as "self._tick" — but
                # also match a bare local alias of the last component
                spec = self.jitted_objects.get(parts[-1:])
            if spec is None:
                continue
            static, donate = spec
            self._check_jitted_call(node, static, donate)

    def _check_jitted_call(self, call: ast.Call, static: Set[int],
                           donate: Set[int]) -> None:
        fn = self.index.enclosing_function(call)
        qn = self.index.qualname.get(fn, "<module>") if fn else "<module>"
        for pos in static:
            if pos < len(call.args):
                arg = call.args[pos]
                bad = isinstance(arg, (ast.List, ast.Dict, ast.Set,
                                       ast.ListComp, ast.DictComp,
                                       ast.SetComp))
                if not bad and isinstance(arg, ast.Call):
                    ap = dotted(arg.func)
                    bad = ap is not None and ap[-1] in ("list", "dict",
                                                        "set")
                if bad:
                    self._emit(
                        "JIT104", "error", arg, qn,
                        f"non-hashable value at static_argnums position "
                        f"{pos} — jit static arguments are dict keys "
                        "and must be hashable",
                        "pass a tuple / frozenset, or drop the "
                        "argument from static_argnums")
        if donate and fn is not None:
            self._check_donation_reuse(call, donate, fn, qn)

    def _check_donation_reuse(self, call: ast.Call, donate: Set[int],
                              fn: ast.AST, qn: str) -> None:
        donated: Dict[Tuple[str, ...], ast.AST] = {}
        for pos in donate:
            if pos < len(call.args):
                parts = dotted(call.args[pos])
                if parts is not None:
                    donated[parts] = call.args[pos]
        if not donated:
            return
        # linear post-order approximation: any LOAD of the donated
        # dotted path strictly after the call line, before a STORE to
        # the same path, is a use-after-donate
        accesses: List[Tuple[int, int, Tuple[str, ...], str]] = []
        for n in self._body_nodes(fn):
            if isinstance(n, (ast.Attribute, ast.Name)):
                parts = dotted(n)
                if parts in donated:
                    kind = "store" if isinstance(
                        n.ctx, (ast.Store, ast.Del)) else "load"
                    accesses.append((n.lineno, n.col_offset, parts, kind))
        accesses.sort()
        end = (call.end_lineno or call.lineno, call.end_col_offset or 0)
        # the statement the call sits in rebinds its own assignment
        # targets (`buf = f(buf, x)` is the canonical donation idiom)
        rebound: Set[Tuple[str, ...]] = set()
        parent = self.parents.get(call)
        while parent is not None and not isinstance(parent, ast.stmt):
            parent = self.parents.get(parent)
        if isinstance(parent, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = parent.targets if isinstance(parent, ast.Assign) \
                else [parent.target]
            for t in targets:
                for tt in ast.walk(t):
                    parts = dotted(tt)
                    if parts:
                        rebound.add(parts)
        for lineno, col, parts, kind in accesses:
            if (lineno, col) <= end:
                continue
            if kind == "store":
                rebound.add(parts)
            elif parts not in rebound:
                rebound.add(parts)   # report once per path
                self._emit(
                    "JIT105", "warning",
                    donated[parts], qn,
                    f"'{'.'.join(parts)}' is donated to a jitted call "
                    f"(line {call.lineno}) and read again afterwards — "
                    "the buffer may already be invalidated in place",
                    "rebind the name to the call's output before any "
                    "further use (enable DL4J_TPU_SANITIZE=donation "
                    "to confirm at runtime)")


def lint_tree(tree: ast.Module, path: str) -> List[Finding]:
    return _ModuleLint(tree, path).run()


def lint_source(source: str, path: str = "<string>") -> List[Finding]:
    return lint_tree(ast.parse(source), path)


# ---------------------------------------------------------------------------
# cross-module pass (JIT106) over the package index
# ---------------------------------------------------------------------------

def lint_package(index) -> List[Finding]:
    """Walk every trace context through its CROSS-MODULE callees.

    Seeds are the functions each module's local pass already proves
    traced (entries + same-module transitive closure); the package
    call graph then carries trace-ness through imports, typed
    attributes (``self._gen = TransformerGenerator(...)``), aliases
    and single-hop higher-order returns.  A function that becomes
    traced ONLY via such a cross-module edge gets JIT106 for each
    host-impure call (error) / host-state mutation (warning) in its
    body — the per-module JIT101/102 equivalents it was invisible to.
    Functions the local pass already covers are skipped (no double
    report)."""
    findings: List[Finding] = []
    locally_traced = set(index.traced_local_fids())
    parent = index.closure(locally_traced)
    for fid in sorted(parent):
        if fid in locally_traced:
            continue
        fn = index.functions[fid]
        mod = index.func_module[fid]
        path = index.modules[mod]["path"]
        # only report when the reaching chain really crossed a module
        # boundary (a same-module function reached through another
        # module and back still qualifies — its module differs from
        # SOME ancestor on the chain)
        cur, crossed = parent.get(fid), False
        while cur is not None:
            if index.func_module[cur] != mod:
                crossed = True
                break
            cur = parent.get(cur)
        if not crossed:
            continue
        qn = fid.split("::", 1)[1]
        chain = index.chain(parent, fid)
        for line, kind, detail in fn["impure"]:
            if kind == "host_call":
                findings.append(Finding(
                    "JIT106", "error", path, line, qn,
                    f"host-impure call '{detail}' in a function "
                    f"reached from a trace context across a module "
                    f"boundary ({chain}) — it runs once at trace "
                    "time, not per call",
                    "hoist the host work out of the traced call "
                    "graph, or pass the value in"))
            else:
                what = (f"host-state mutation ('{detail}')"
                        if kind == "global" else f"store to {detail}")
                findings.append(Finding(
                    "JIT106", "warning", path, line, qn,
                    f"{what} in a function reached from "
                    f"a trace context across a module boundary "
                    f"({chain}) — it happens at trace time (once per "
                    "compilation), not per call",
                    "return the new value instead of mutating"))
    return findings


def _dynamic_names(test: ast.AST) -> Set[str]:
    """Names in a branch test that would read a TRACED value — i.e.
    excluding shape-derived reads, identity/type checks, attribute
    reads of config objects, membership tests, and string-equality
    dispatch, all of which are static under tracing."""
    out: Set[str] = set()
    skip: Set[ast.AST] = set()
    str_dispatched: Set[str] = set()
    for n in ast.walk(test):
        if isinstance(n, ast.Call):
            parts = dotted(n.func)
            if parts and (parts[-1] in _STATIC_CALLS):
                for sub in ast.walk(n):
                    skip.add(sub)
        elif isinstance(n, ast.Attribute):
            # `cfg.flag` is config plumbing, not a tracer read; only
            # reducer methods (`x.any()`, …) read traced data
            if n.attr in _SHAPE_ATTRS or n.attr not in _TRACER_REDUCERS:
                for sub in ast.walk(n):
                    skip.add(sub)
        elif isinstance(n, ast.Compare):
            if any(isinstance(c, (ast.Is, ast.IsNot)) for c in n.ops):
                for sub in ast.walk(n):
                    skip.add(sub)
            # `kind == "clip"` string dispatch: tracers are never
            # strings, so the compared name is static everywhere
            sides = [n.left] + list(n.comparators)
            if any(isinstance(s, ast.Constant) and
                   isinstance(s.value, str) for s in sides):
                for s in sides:
                    if isinstance(s, ast.Name):
                        str_dispatched.add(s.id)
            # `x in needed`: the container is a static host set
            for op, comp in zip(n.ops, n.comparators):
                if isinstance(op, (ast.In, ast.NotIn)):
                    for sub in ast.walk(comp):
                        skip.add(sub)
    for n in ast.walk(test):
        if n in skip:
            continue
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            out.add(n.id)
    return out - str_dispatched
