"""Traced/Static parameter annotations — the lint's type language.

PR 4's JIT103 guessed a parameter's trace-time nature from NAME
heuristics (``cfg.attr`` reads look static, ``x.any()`` looks traced,
``is None`` is static, ...).  Heuristics degrade as the hot paths grow
— the paged decode scan branches on knobs the heuristics cannot
classify — so this module gives authors a way to SAY it, jaxtyping
style, and gives the linter ground truth:

>>> from deeplearning4j_tpu.analysis.annotations import Static, Traced
>>> def step(x: Traced, tick_batch: Static, cfg=None):
...     if tick_batch > 4:        # fine: declared static
...         ...
...     if x.flag:                # JIT103: declared traced — the
...         ...                   # attr-read heuristic is overridden

Semantics (consumed by ``jit_lint``; the old heuristics remain the
fallback for unannotated parameters):

* ``Static`` — the parameter is a Python-level constant at trace time
  (a config knob, a shape, a mode string).  Branching on it is
  specialization, not a tracer leak: JIT103 never fires on it.
* ``Traced`` — the parameter is (or contains) traced array data.
  JIT103 fires on ANY Python branch that reads it, even through forms
  the heuristics would excuse (attribute reads, membership tests).

Both markers subscript (``Static[int]``, ``Traced["f32[b n]"]``) and
compose with ``typing.Annotated``/string annotations — at runtime they
are inert objects, so annotating costs nothing and imports nothing
beyond this tiny module.  A class-typed parameter annotation (e.g.
``server: "GenerationServer"``) is equally load-bearing: the
cross-module concurrency pass (CONC206) resolves it through the
package index to that class's lock/guarded-attribute facts.
"""
from __future__ import annotations

#: Names the linter recognizes in parameter annotations.  Matching is
#: syntactic (``Static``, ``annotations.Static``, ``Static[...]``, or
#: the same inside a string annotation) — the linted module does not
#: need to import anything for the annotation to be honored, though
#: importing these keeps the annotation a real object for tooling.
STATIC_NAMES = frozenset({"Static"})
TRACED_NAMES = frozenset({"Traced"})


class _Marker:
    """Inert, subscriptable annotation marker."""

    __slots__ = ("_name",)

    def __init__(self, name: str):
        self._name = name

    def __repr__(self):
        return f"deeplearning4j_tpu.analysis.annotations.{self._name}"

    def __getitem__(self, item):
        # Static[int] / Traced["f32[b n]"]: the payload is documentation
        # for the reader; the linter keys on the marker name alone.
        return self

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"{self._name} is an annotation marker, not a constructor; "
            f"write 'param: {self._name}' (or '{self._name}[...]') in "
            "the signature")


Static = _Marker("Static")
Traced = _Marker("Traced")


def classify_annotation(ann_node) -> str:
    """Classify a parameter-annotation AST node: ``"static"``,
    ``"traced"``, a class-name string (potential CONC206 type
    reference, e.g. ``"GenerationServer"``), or ``""`` (no verdict).

    Recognized shapes: ``Static`` / ``Traced`` as a bare name, dotted
    attribute tail, subscripted (``Static[int]``), or spelled inside a
    string annotation; any other bare/dotted/string name whose last
    component looks like a class name (CapWord) is returned as that
    name for type resolution."""
    import ast

    node = ann_node
    # string annotation: "Static", "Traced", "GenerationServer", and
    # forward references like "Optional[GenerationServer]" (take the
    # innermost CapWord)
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        text = node.value.strip()
        try:
            node = ast.parse(text, mode="eval").body
        except SyntaxError:
            return ""
    while isinstance(node, ast.Subscript):
        base = node.value
        name = _tail_name(base)
        if name in STATIC_NAMES:
            return "static"
        if name in TRACED_NAMES:
            return "traced"
        # Optional[X] / Annotated[X, ...]: classify the first slice elt
        # (recursing — it may itself be a string forward reference)
        sl = node.slice
        if isinstance(sl, ast.Tuple) and sl.elts:
            sl = sl.elts[0]
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            return classify_annotation(sl)
        node = sl
    name = _tail_name(node)
    if name in STATIC_NAMES:
        return "static"
    if name in TRACED_NAMES:
        return "traced"
    if name and name[:1].isupper() and name.isidentifier():
        return name
    return ""


def _tail_name(node) -> str:
    import ast
    if isinstance(node, ast.Attribute):   # a.b.Static -> "Static"
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def param_annotations(fn_node):
    """``(static_names, traced_names, type_refs)`` for a function-def
    AST node: parameter names annotated ``Static`` / ``Traced``, and a
    ``{param: ClassName}`` map for class-typed parameters."""
    static, traced, types = set(), set(), {}
    args = fn_node.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs +
              ([args.vararg] if args.vararg else []) +
              ([args.kwarg] if args.kwarg else [])):
        if a.annotation is None:
            continue
        verdict = classify_annotation(a.annotation)
        if verdict == "static":
            static.add(a.arg)
        elif verdict == "traced":
            traced.add(a.arg)
        elif verdict:
            types[a.arg] = verdict
    return static, traced, types
