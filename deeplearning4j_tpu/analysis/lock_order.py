"""Whole-package deadlock lint: the lock-acquisition graph.

PR 17/18 made the serving stack genuinely multi-threaded (fleet
scheduler, degrade-ladder clock, hedge racer, TSDB recorder, beacon,
watchdogs) — the point where per-region lock rules stop being enough.
``concurrency_lint`` proves each *region* is consistent; nothing so
far proves the regions compose: that no two threads ever acquire the
same two locks in opposite orders, that nothing blocks indefinitely
while holding a lock, and that a callback drained from a handler
table does not re-enter a lock its invoking thread already holds.
Those are exactly the properties ThreadSanitizer's lock-order
-inversion detection and Eraser's lockset discipline check at runtime
— this pass checks them statically, over
:class:`~deeplearning4j_tpu.analysis.package_index.PackageIndex`'s
whole-package call graph, so the CI gate proves the topology
deadlock-free before any thread is ever started.

Rules
-----

* **CONC301** (error) — cycle in the lock-order graph: lock A is held
  while B is acquired on one path and B is held while A is acquired
  on another.  Two threads interleaving those paths deadlock.  The
  finding carries one witness per edge of the cycle.
* **CONC302** (warning) — a blocking call (``Thread.join`` /
  ``Queue.get`` / ``Future.result`` / ``Event.wait`` without timeout,
  ``time.sleep`` at or above 50 ms, socket/HTTP I/O, subprocess
  waits) executes while a lock is held — directly, or transitively
  through any chain of calls the package index can resolve.  Every
  other thread needing that lock stalls for the full blocking time.
* **CONC303** (error) — a callback stored into a container (handler
  table, sink list, actuator registry) is invoked by a thread holding
  a lock the callback itself acquires.  The registration site hides
  the acquisition from lexical review — the container data-flow makes
  it part of the lock graph anyway.

Lock identity is canonical across modules: ``self._lock`` folds to
the base-most class in the MRO that constructs the attribute
(``module::Class.attr``), module-level locks to ``module::NAME``
through import aliases.  Edges whose lock cannot be canonicalized are
dropped rather than guessed (an ambiguous ``other._lock`` must not
fabricate a deadlock report).

Thread roots are ``threading.Thread(target=...)`` spawns, public
methods of lock-owning/thread-starting classes, and — when the caller
indexes ``scripts/`` as aux seed modules — every function a script's
module-level code reaches, closing the "bare entry points called only
from scripts" blind spot carried since PR 8.  Aux modules only seed
and route reachability; findings are never reported in them.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.analysis.findings import Finding

#: acqstar/blockstar chains longer than this are rendered elided
_CHAIN_LIMIT = 4


def _short_lock(canon: str) -> str:
    """``pkg.serving.router::ServingFleet._lock`` ->
    ``router::ServingFleet._lock`` (messages are line-free AND
    package-prefix-free so baseline keys survive moves of the tree)."""
    mod, _, rest = canon.partition("::")
    return f"{mod.rsplit('.', 1)[-1]}::{rest}"


class _Pass:
    def __init__(self, index):
        self.index = index
        self.fids = sorted(index.functions)
        #: fid -> sorted unique resolved callees (with call lines)
        self.calls: Dict[str, List[Tuple[int, List, str]]] = {}
        #: fid -> canonical lock implicitly held on entry (the
        #: ``*_locked`` suffix convention: caller holds the class lock)
        self.implicit: Dict[str, Optional[str]] = {}
        #: lock-order graph: a -> b -> witness dict
        self.edges: Dict[str, Dict[str, Dict]] = {}
        self.findings: List[Finding] = []
        self._canon_cache: Dict[Tuple, Optional[str]] = {}
        self._lock_attr_owners = self._collect_lock_attr_owners()

    # -- lock identity --------------------------------------------------
    def _collect_lock_attr_owners(self) -> Dict[str, List[Tuple[str, str]]]:
        """lock attribute name -> classes that construct it (for the
        unique-attribute fallback on untyped foreign bases)."""
        out: Dict[str, List[Tuple[str, str]]] = {}
        for mod in sorted(self.index.modules):
            classes = self.index.modules[mod].get("classes", {})
            for cname in sorted(classes):
                for attr in classes[cname].get("lock_attrs", ()):
                    out.setdefault(attr, []).append((mod, cname))
        return out

    def _canon_attr(self, mod: str, cls: str, attr: str) -> str:
        """Fold ``Class.attr`` to the base-most MRO class constructing
        it, so a subclass and its base name the SAME lock node."""
        owner = (mod, cls)
        for m, c in self.index.class_mro(mod, cls):
            ci = self.index.modules.get(m, {}).get("classes", {}).get(c)
            if ci and attr in ci.get("lock_attrs", ()):
                owner = (m, c)          # MRO is subclass-first: keep last
        return f"{owner[0]}::{owner[1]}.{attr}"

    def canon_lock(self, mod: str, cls: Optional[str],
                   parts: Sequence[str],
                   base_type: Optional[Sequence[str]] = None
                   ) -> Optional[str]:
        key = (mod, cls, tuple(parts),
               tuple(base_type) if base_type else None)
        if key in self._canon_cache:
            return self._canon_cache[key]
        self._canon_cache[key] = out = self._canon_lock(
            mod, cls, list(parts), base_type)
        return out

    def _canon_lock(self, mod, cls, parts, base_type):
        if not parts:
            return None
        attr = parts[-1]
        if parts[0] in ("self", "cls") and len(parts) == 2 and cls:
            return self._canon_attr(mod, cls, attr)
        s = self.index.modules.get(mod, {})
        if len(parts) == 1:
            if parts[0] in s.get("module_locks", ()) or \
                    parts[0] in s.get("module_state", {}):
                return f"{mod}::{parts[0]}"
            hop = self.index.resolve_import(mod, parts[0])
            if hop is not None and hop[1] is not None and \
                    hop[0] in self.index.modules:
                tmod, tname = hop
                ts = self.index.modules[tmod]
                if tname in ts.get("module_locks", ()) or \
                        tname in ts.get("module_state", {}):
                    return f"{tmod}::{tname}"
            # a function-local lock: per-call-frame, orders with
            # nothing across threads by identity we can prove — skip
            return None
        if base_type is not None:
            hit = self.index.resolve_class(mod, list(base_type))
            if hit is not None:
                return self._canon_attr(hit[0], hit[1], attr)
        owners = self._lock_attr_owners.get(attr, [])
        if len(owners) == 1:
            return self._canon_attr(owners[0][0], owners[0][1], attr)
        return None

    def canon_container(self, mod: str, cls: Optional[str],
                        parts: Sequence[str]) -> Optional[str]:
        """Callback-container identity; same shape as locks but folded
        over guarded/typed attribute declarations."""
        parts = list(parts)
        if parts and parts[0] in ("self", "cls") and \
                len(parts) == 2 and cls:
            attr = parts[1]
            owner = (mod, cls)
            for m, c in self.index.class_mro(mod, cls):
                ci = self.index.modules.get(m, {}) \
                    .get("classes", {}).get(c)
                if ci and (attr in ci.get("guarded", ()) or
                           attr in ci.get("attr_types", {})):
                    owner = (m, c)
            return f"{owner[0]}::{owner[1]}.{attr}"
        if len(parts) == 1 and \
                parts[0] in self.index.modules.get(mod, {}) \
                .get("module_state", {}):
            return f"{mod}::{parts[0]}"
        return None

    # -- per-function fact preparation ---------------------------------
    def _fn_ctx(self, fid: str) -> Tuple[str, Optional[str], Dict]:
        mod = self.index.func_module[fid]
        fn = self.index.functions[fid]
        return mod, fn.get("cls"), fn

    def _implicit_lock(self, fid: str) -> Optional[str]:
        mod, cls, fn = self._fn_ctx(fid)
        qn = fid.split("::", 1)[1]
        if not qn.rsplit(".", 1)[-1].endswith("_locked") or not cls:
            return None
        facts = self.index.class_facts(mod, cls)
        locks = sorted(facts["lock_attrs"])
        if len(locks) == 1:
            return self._canon_attr(mod, cls, locks[0])
        return None

    def _held_canons(self, fid: str, raw_held: Sequence[Sequence[str]]
                     ) -> List[str]:
        mod, cls, _ = self._fn_ctx(fid)
        out: List[str] = []
        for parts in raw_held:
            c = self.canon_lock(mod, cls, parts)
            if c is not None and c not in out:
                out.append(c)
        imp = self.implicit.get(fid)
        if imp is not None and imp not in out:
            out.append(imp)
        return out

    def _resolved_calls(self, fid: str) -> List[Tuple[int, List, str]]:
        """[(line, held_canons, callee_fid)] — deterministic order."""
        if fid in self.calls:
            return self.calls[fid]
        mod, cls, fn = self._fn_ctx(fid)
        out: List[Tuple[int, List, str]] = []
        for call in fn.get("calls", ()):
            held = self._held_canons(fid, call.get("locks", ()))
            for callee in sorted(set(self.index.resolve_call(fid, call))):
                out.append((call.get("line", 0), held, callee))
        for qn in fn.get("nested", ()):
            nfid = f"{mod}::{qn}"
            if nfid in self.index.functions:
                out.append((fn.get("line", 0), [], nfid))
        self.calls[fid] = out
        return out

    def _fn_name(self, fid: str) -> str:
        mod, qn = fid.split("::", 1)
        return f"{mod.rsplit('.', 1)[-1]}.{qn}"

    # -- fixpoints ------------------------------------------------------
    def _acqstar(self) -> Dict[str, Dict[str, Tuple[int, str]]]:
        """fid -> lock -> (depth, via-chain) for every lock the
        function acquires itself or through any resolvable callee.
        Deterministic: merges prefer smaller (depth, chain)."""
        acq: Dict[str, Dict[str, Tuple[int, str]]] = {}
        for fid in self.fids:
            mod, cls, fn = self._fn_ctx(fid)
            direct: Dict[str, Tuple[int, str]] = {}
            for line, parts, base_t, _held in fn.get("acquires", ()):
                c = self.canon_lock(mod, cls, parts, base_t)
                if c is not None:
                    direct.setdefault(c, (0, ""))
            acq[fid] = direct
        changed = True
        while changed:
            changed = False
            for fid in self.fids:
                cur = acq[fid]
                for _line, _held, callee in self._resolved_calls(fid):
                    for lock, (d, via) in acq.get(callee, {}).items():
                        cand = (d + 1,
                                self._fn_name(callee) +
                                (" -> " + via if via else ""))
                        if cand[0] > _CHAIN_LIMIT * 4:
                            continue
                        old = cur.get(lock)
                        if old is None or cand < old:
                            cur[lock] = cand
                            changed = True
        return acq

    def _blockstar(self) -> Dict[str, Tuple[int, str, str]]:
        """fid -> nearest (depth, detail, via-chain) blocking call the
        function reaches, itself included."""
        blk: Dict[str, Tuple[int, str, str]] = {}
        for fid in self.fids:
            _mod, _cls, fn = self._fn_ctx(fid)
            best: Optional[Tuple[int, str, str]] = None
            for _line, detail, _parts, _held in fn.get("blocking", ()):
                cand = (0, detail, "")
                if best is None or cand < best:
                    best = cand
            if best is not None:
                blk[fid] = best
        changed = True
        while changed:
            changed = False
            for fid in self.fids:
                for _line, _held, callee in self._resolved_calls(fid):
                    hit = blk.get(callee)
                    if hit is None:
                        continue
                    d, detail, via = hit
                    cand = (d + 1, detail,
                            self._fn_name(callee) +
                            (" -> " + via if via else ""))
                    if cand[0] > _CHAIN_LIMIT * 4:
                        continue
                    old = blk.get(fid)
                    if old is None or cand < old:
                        blk[fid] = cand
                        changed = True
        return blk

    # -- graph ----------------------------------------------------------
    def _add_edge(self, a: str, b: str, fid: str, line: int,
                  via: str) -> None:
        if a == b:
            return                      # reentrant re-acquire (RLock)
        slot = self.edges.setdefault(a, {})
        if b not in slot:
            slot[b] = {"fid": fid, "line": line, "via": via}

    def run(self) -> List[Finding]:
        for fid in self.fids:
            self.implicit[fid] = self._implicit_lock(fid)
        acq = self._acqstar()
        blk = self._blockstar()

        registrations = self._registrations()
        reach = set(self.index.closure(
            list(self.index.thread_seeds()) +
            list(self.index.entry_seeds())))

        seen302: Set[Tuple[str, str, str]] = set()
        for fid in self.fids:
            mod, cls, fn = self._fn_ctx(fid)
            aux = self.index.is_aux(mod)
            path = self.index.modules[mod]["path"]
            qn = fid.split("::", 1)[1]

            # direct with-nesting edges + edges through calls
            for line, parts, base_t, raw_held in fn.get("acquires", ()):
                inner = self.canon_lock(mod, cls, parts, base_t)
                if inner is None:
                    continue
                for outer in self._held_canons(fid, raw_held):
                    self._add_edge(outer, inner, fid, line, "")
            for line, held, callee in self._resolved_calls(fid):
                if not held:
                    continue
                for lock, (_d, via) in acq.get(callee, {}).items():
                    chain = self._fn_name(callee) + \
                        (" -> " + via if via else "")
                    for outer in held:
                        self._add_edge(outer, lock, fid, line, chain)
                # CONC302: blocking reached through the call
                hit = blk.get(callee)
                if hit is not None and not aux:
                    _d, detail, via = hit
                    chain = self._fn_name(callee) + \
                        (" -> " + via if via else "")
                    for outer in held:
                        key = (fid, outer, detail)
                        if key in seen302:
                            continue
                        seen302.add(key)
                        self.findings.append(Finding(
                            rule="CONC302", severity="warning",
                            path=path, line=line, symbol=qn,
                            message=(f"call while holding "
                                     f"'{_short_lock(outer)}' reaches "
                                     f"blocking {detail} via {chain}"),
                            fix_hint="bound the blocking call with a "
                                     "timeout or move it outside the "
                                     "lock region"))

            # CONC302: lexically-direct blocking under a lock
            for line, detail, parts, raw_held in fn.get("blocking", ()):
                held = self._held_canons(fid, raw_held)
                if not held or aux:
                    continue
                base = self.canon_lock(mod, cls, parts[:-1]) \
                    if len(parts) > 1 else None
                for outer in held:
                    if base is not None and base == outer:
                        # cond.wait() RELEASES the lock it waits on —
                        # the canonical condition-variable pattern
                        continue
                    key = (fid, outer, detail)
                    if key in seen302:
                        continue
                    seen302.add(key)
                    self.findings.append(Finding(
                        rule="CONC302", severity="warning",
                        path=path, line=line, symbol=qn,
                        message=(f"blocking {detail} while holding "
                                 f"'{_short_lock(outer)}'"),
                        fix_hint="bound the call with a timeout or "
                                 "move it outside the lock region"))

            # callbacks drained here: their acquisitions join the
            # graph, and a held lock they re-acquire is CONC303
            for line, cparts, raw_held in fn.get("cb_invokes", ()):
                cont = self.canon_container(mod, cls, cparts)
                if cont is None:
                    continue
                held = self._held_canons(fid, raw_held)
                for reg_fid, cb_fid, reg_held in \
                        registrations.get(cont, ()):
                    cb_locks: Dict[str, str] = {}
                    for lock, (_d, via) in acq.get(cb_fid, {}).items():
                        cb_locks[lock] = via
                    for lock, via in sorted(cb_locks.items()):
                        chain = self._fn_name(cb_fid) + \
                            (" -> " + via if via else "")
                        for outer in held:
                            self._add_edge(outer, lock, fid, line,
                                           chain)
                    if aux or fid not in reach:
                        continue
                    clash = sorted(set(held) & set(cb_locks))
                    if not clash or set(held) == set(reg_held):
                        continue
                    lock = clash[0]
                    self.findings.append(Finding(
                        rule="CONC303", severity="error",
                        path=path, line=line, symbol=qn,
                        message=(f"callback "
                                 f"'{self._fn_name(cb_fid)}' from "
                                 f"'{_short_lock(cont)}' acquires "
                                 f"'{_short_lock(lock)}' already held "
                                 f"at this invocation (registered in "
                                 f"{self._fn_name(reg_fid)} holding "
                                 + (", ".join(_short_lock(h) for h in
                                              reg_held)
                                    if reg_held else "no locks") + ")"),
                        fix_hint="snapshot the table and invoke the "
                                 "callbacks after releasing the lock, "
                                 "or make the callback lock-free"))

        self._cycle_findings()
        return self.findings

    def _registrations(self) -> Dict[str, List[Tuple[str, str, List]]]:
        """container canon -> [(registering fid, callback fid,
        registration-held canons)]."""
        out: Dict[str, List[Tuple[str, str, List]]] = {}
        for fid in self.fids:
            mod, cls, fn = self._fn_ctx(fid)
            for _line, cparts, fparts, raw_held, via, base_t in \
                    fn.get("cb_stores", ()):
                cont = self.canon_container(mod, cls, cparts)
                if cont is None and via:
                    cont = self._forwarded_container(mod, cls, via,
                                                     base_t)
                if cont is None:
                    continue
                cands = self.index.resolve_in_module(
                    mod, fparts, cls=cls)
                held = self._held_canons(fid, raw_held)
                for cb in sorted(set(cands)):
                    out.setdefault(cont, []).append((fid, cb, held))
        return out

    def _forwarded_container(self, mod: str, cls: Optional[str],
                             via: Sequence[str],
                             base_t: Optional[Sequence[str]]
                             ) -> Optional[str]:
        """``bus.subscribe(cb)``: the table lives inside the callee —
        find the cb_store in ``Bus.subscribe`` whose stored value is a
        bare unresolvable name (the forwarded parameter) and
        canonicalize THAT container."""
        callees: List[str] = []
        if base_t is not None:
            hit = self.index.resolve_class(mod, list(base_t))
            if hit is not None:
                m = self.index.resolve_method(hit[0], hit[1], via[-1])
                if m is not None:
                    callees.append(m)
        if not callees:
            callees = sorted(set(
                self.index.resolve_in_module(mod, list(via), cls=cls)))
        for callee in callees:
            cmod, ccls, cfn = self._fn_ctx(callee)
            for _l, c2, f2, _h, _v, _b in cfn.get("cb_stores", ()):
                if len(f2) == 1 and not self.index.resolve_in_module(
                        cmod, f2, cls=ccls):
                    cont = self.canon_container(cmod, ccls, c2)
                    if cont is not None:
                        return cont
        return None

    # -- cycles ---------------------------------------------------------
    def _cycle_findings(self) -> None:
        for scc in self._sccs():
            cycle = self._cycle_path(scc)
            if cycle is None:
                continue
            steps: List[str] = []
            anchor: Optional[Tuple[str, int]] = None
            for a, b in zip(cycle, cycle[1:]):
                w = self.edges[a][b]
                mod = self.index.func_module[w["fid"]]
                if anchor is None and not self.index.is_aux(mod):
                    anchor = (w["fid"], w["line"])
                steps.append(
                    f"'{_short_lock(a)}' held in "
                    f"{self._fn_name(w['fid'])} while acquiring "
                    f"'{_short_lock(b)}'"
                    + (f" via {w['via']}" if w["via"] else ""))
            if anchor is None:
                continue            # cycle witnessed only in aux code
            fid, line = anchor
            mod = self.index.func_module[fid]
            self.findings.append(Finding(
                rule="CONC301", severity="error",
                path=self.index.modules[mod]["path"], line=line,
                symbol=fid.split("::", 1)[1],
                message=("lock-order cycle " +
                         " -> ".join(_short_lock(n) for n in cycle) +
                         ": " + "; ".join(steps)),
                fix_hint="pick one global acquisition order for these "
                         "locks (or collapse them into one)"))

    def _sccs(self) -> List[List[str]]:
        """Tarjan SCCs of the lock graph, size >= 2 only, iterative,
        deterministic (sorted roots/neighbors)."""
        index_of: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        out: List[List[str]] = []
        counter = [0]
        nodes = sorted(set(self.edges) |
                       {b for bs in self.edges.values() for b in bs})

        def neighbors(n):
            return sorted(self.edges.get(n, {}))

        for root in nodes:
            if root in index_of:
                continue
            work: List[Tuple[str, int]] = [(root, 0)]
            while work:
                node, pi = work[-1]
                if pi == 0:
                    index_of[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    on_stack.add(node)
                recurse = False
                ns = neighbors(node)
                for j in range(pi, len(ns)):
                    w = ns[j]
                    if w not in index_of:
                        work[-1] = (node, j + 1)
                        work.append((w, 0))
                        recurse = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index_of[w])
                if recurse:
                    continue
                work.pop()
                if low[node] == index_of[node]:
                    scc: List[str] = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) >= 2:
                        out.append(sorted(scc))
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
        return sorted(out)

    def _cycle_path(self, scc: List[str]) -> Optional[List[str]]:
        """Shortest cycle through the lexicographically first node of
        the SCC — ``[a, ..., a]`` including the closing hop."""
        start = scc[0]
        members = set(scc)
        prev: Dict[str, Optional[str]] = {start: None}
        frontier = [start]
        while frontier:
            nxt: List[str] = []
            for n in frontier:
                for m in sorted(self.edges.get(n, {})):
                    if m == start:
                        path = [n]
                        cur = prev[n]
                        while cur is not None:
                            path.append(cur)
                            cur = prev[cur]
                        path.reverse()
                        return path + [start]
                    if m in members and m not in prev:
                        prev[m] = n
                        nxt.append(m)
            frontier = nxt
        return None


def lint_package(index) -> List[Finding]:
    """CONC301/302/303 over a built package index (plus optional aux
    seed modules merged by the caller)."""
    return _Pass(index).run()


def lock_graph(index) -> Dict[str, Dict[str, Dict]]:
    """The raw lock-order graph (``a -> b -> witness``) — for the
    chaos probe's acyclicity assertion over the live configuration."""
    p = _Pass(index)
    p.run()
    return p.edges
