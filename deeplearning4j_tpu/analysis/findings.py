"""Shared findings model for the static-analysis passes.

Every pass (``jit_lint``, ``concurrency_lint``, ``graph_lint``) emits
:class:`Finding` records — one defect each, carrying a stable rule id,
a severity, a location, and a fix hint — so the CLI, the CI gate, and
the baseline workflow treat all three uniformly.

Baseline design: a finding's identity deliberately EXCLUDES the line
number.  Keys are ``rule::path::symbol::message`` — an unrelated edit
that shifts a flagged function down 40 lines must not invalidate the
checked-in baseline, while touching the flagged code itself (message
or enclosing symbol changes) correctly surfaces the finding as new.
Duplicate keys are tracked by COUNT: a second unguarded read of the
same attribute in the same method is a new finding even though its key
already exists.
"""
from __future__ import annotations

import dataclasses
import json
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: Severity order, most severe first.  ``error`` findings are the CI
#: gate's hard bar (fix, don't baseline, unless justified); ``warning``
#: is a real smell worth a baseline justification; ``info`` is advice.
SEVERITIES = ("error", "warning", "info")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One static-analysis defect."""

    rule: str            # stable id, e.g. "JIT101"
    severity: str        # "error" | "warning" | "info"
    path: str            # repo-relative file (or "<graph:NAME>")
    line: int            # 1-based; 0 when not line-anchored (graph IR)
    symbol: str          # enclosing qualified symbol ("Class.method")
    message: str         # line-free statement of the defect
    fix_hint: str = ""   # how to make it go away

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"severity {self.severity!r} not in {SEVERITIES}")

    @property
    def key(self) -> str:
        """Line-insensitive identity used by the baseline."""
        return f"{self.rule}::{self.path}::{self.symbol}::{self.message}"

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict) -> "Finding":
        return cls(**{f.name: d[f.name]
                      for f in dataclasses.fields(cls) if f.name in d})

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        hint = f"  [fix: {self.fix_hint}]" if self.fix_hint else ""
        return (f"{self.severity.upper():7s} {self.rule} {loc} "
                f"({self.symbol}) {self.message}{hint}")


def sort_findings(findings: Iterable[Finding]) -> List[Finding]:
    return sorted(findings,
                  key=lambda f: (_SEV_RANK[f.severity], f.path, f.line,
                                 f.rule, f.message))


def max_severity(findings: Iterable[Finding]) -> Optional[str]:
    ranks = [_SEV_RANK[f.severity] for f in findings]
    return SEVERITIES[min(ranks)] if ranks else None


# ---------------------------------------------------------------------------
# Baseline: checked-in set of accepted pre-existing findings
# ---------------------------------------------------------------------------

class Baseline:
    """The checked-in findings debt ledger (``ANALYSIS_BASELINE.json``).

    Each entry is a finding key, an occurrence count, and a one-line
    human justification for why it is accepted rather than fixed.  The
    gate (:mod:`scripts.lint_gate`) fails only on findings NOT covered
    here — new code meets the bar immediately, old debt is explicit."""

    def __init__(self, entries: Optional[Dict[str, Dict]] = None):
        # key -> {"count": int, "justification": str}
        self.entries: Dict[str, Dict] = dict(entries or {})

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path) as fh:
            doc = json.load(fh)
        entries = {}
        for e in doc.get("entries", []):
            entries[e["key"]] = {
                "count": int(e.get("count", 1)),
                "justification": e.get("justification", ""),
            }
        return cls(entries)

    def save(self, path: str) -> None:
        doc = {
            "version": 1,
            "tool": "deeplearning4j_tpu.analysis",
            "note": ("accepted pre-existing findings; keys are "
                     "line-insensitive (rule::path::symbol::message). "
                     "Regenerate with scripts/lint_gate.py "
                     "--update-baseline, then fill in justifications."),
            "entries": [
                {"key": k, "count": v["count"],
                 "justification": v["justification"]}
                for k, v in sorted(self.entries.items())
            ],
        }
        with open(path, "w") as fh:
            json.dump(doc, fh, indent=2)
            fh.write("\n")

    def diff(self, findings: Sequence[Finding]
             ) -> Tuple[List[Finding], List[Finding], List[str]]:
        """Split ``findings`` against the baseline.

        Returns ``(new, baselined, stale_keys)``: findings beyond each
        key's baselined count are new; keys in the baseline that the
        run no longer produces at all are stale (fixed debt — prune
        them with ``--update-baseline``)."""
        seen = Counter(f.key for f in findings)
        budget = {k: v["count"] for k, v in self.entries.items()}
        new: List[Finding] = []
        baselined: List[Finding] = []
        used: Counter = Counter()
        for f in sort_findings(findings):
            if used[f.key] < budget.get(f.key, 0):
                used[f.key] += 1
                baselined.append(f)
            else:
                new.append(f)
        stale = [k for k in self.entries if seen.get(k, 0) == 0]
        return new, baselined, sorted(stale)

    def updated_with(self, findings: Sequence[Finding]) -> "Baseline":
        """A baseline covering exactly ``findings``, preserving the
        justifications of keys that survive."""
        counts = Counter(f.key for f in findings)
        entries = {}
        for k, n in counts.items():
            old = self.entries.get(k, {})
            entries[k] = {"count": n,
                          "justification": old.get("justification", "")}
        return Baseline(entries)
