"""Runtime sanitizer — the dynamic companion to the static passes.

``DL4J_TPU_SANITIZE=nan,donation`` (or ``all``) turns on opt-in
runtime confirmation of the two bug classes the static passes flag:

* **nan** — ``jax.debug_nans``-style finite checks at the host
  boundaries the lint reasons about: the fit loop checks every step's
  loss, and the decode tick checks the active slots' held logits — the
  exact surface PR 2's NaN-poisoned-slot bug corrupted.  One device
  sync per step/tick while enabled; a debug mode, like the solver's
  ``DL4J_TPU_CHECK_NUMERICS``.
* **donation** — a use-after-donate guard: buffers passed at
  ``donate_argnums`` positions are registered as dead, and touching
  one again (before rebinding to the call's fresh output) raises
  :class:`SanitizerError` with the donation site — the dynamic mirror
  of jit_lint's JIT105.

With no modes active every hook is one frozenset-membership check, so
the call sites stay compiled into production paths (the same honesty
property as the fault injector: the check traverses exactly the code a
real run would).

Telemetry: every trip increments
``sanitizer_trips_total{mode=nan|donation}``.
"""
from __future__ import annotations

import os
import threading
import weakref
from typing import Dict, Optional, Tuple

import numpy as np

from deeplearning4j_tpu import telemetry

MODES = ("nan", "donation")

_TRIPS = telemetry.counter(
    "sanitizer_trips_total",
    "runtime sanitizer violations detected (raise sites), by mode",
    labelnames=("mode",))


class SanitizerError(RuntimeError):
    """A runtime sanitizer check failed (non-finite value or
    use-after-donate)."""


def _parse(text: Optional[str]) -> frozenset:
    if not text:
        return frozenset()
    parts = {p.strip().lower() for p in text.split(",") if p.strip()}
    if "all" in parts:
        return frozenset(MODES)
    unknown = parts - set(MODES)
    if unknown:
        raise ValueError(
            f"DL4J_TPU_SANITIZE: unknown mode(s) {sorted(unknown)} "
            f"(choose from {MODES} or 'all')")
    return frozenset(parts)


def _parse_lenient(text: Optional[str]) -> frozenset:
    """Import-time parse: a typo in the env var must not make the
    whole package unimportable — warn and ignore the bad mode.
    ``refresh()`` (the explicit API) stays strict."""
    try:
        return _parse(text)
    except ValueError as e:
        import logging
        logging.getLogger("deeplearning4j_tpu").warning("%s", e)
        return frozenset(p.strip().lower() for p in (text or "").split(",")
                         if p.strip().lower() in MODES)


_active: frozenset = _parse_lenient(os.environ.get("DL4J_TPU_SANITIZE"))


def refresh() -> frozenset:
    """Re-read ``DL4J_TPU_SANITIZE`` (tests toggle the env mid-process;
    production reads it once at import)."""
    global _active
    _active = _parse(os.environ.get("DL4J_TPU_SANITIZE"))
    return _active


def active(mode: str) -> bool:
    return mode in _active


def enabled() -> frozenset:
    return _active


# ---------------------------------------------------------------------------
# nan mode
# ---------------------------------------------------------------------------

def check_finite(site: str, value, detail: str = "") -> None:
    """Raise :class:`SanitizerError` when any element of ``value``
    (array-like, or a scalar) is non-finite.  Call only when
    ``active('nan')`` — the caller gates, so the off path costs one
    set lookup, not an array pull."""
    arr = np.asarray(value)
    if np.issubdtype(arr.dtype, np.floating) and \
            not np.isfinite(arr).all():
        _TRIPS.labels(mode="nan").inc()
        n_bad = int((~np.isfinite(arr)).sum())
        raise SanitizerError(
            f"[sanitize:nan] non-finite value at {site}: {n_bad}/"
            f"{arr.size} elements{' — ' + detail if detail else ''}")


def check_finite_rows(site: str, value, row_mask,
                      detail: str = "") -> None:
    """Finite check restricted to rows where ``row_mask`` is True —
    the decode tick's shape: inactive slots legitimately hold stale
    garbage, only ACTIVE slots' state must stay finite."""
    arr = np.asarray(value)
    mask = np.asarray(row_mask, bool)
    if not mask.any() or not np.issubdtype(arr.dtype, np.floating):
        return
    bad_rows = [int(i) for i in np.nonzero(mask)[0]
                if not np.isfinite(arr[i]).all()]
    if bad_rows:
        _TRIPS.labels(mode="nan").inc()
        raise SanitizerError(
            f"[sanitize:nan] non-finite values at {site} in active "
            f"row(s) {bad_rows}"
            f"{' — ' + detail if detail else ''}")


# ---------------------------------------------------------------------------
# donation mode
# ---------------------------------------------------------------------------

class _DonationLedger:
    """Tracks buffers whose storage was donated to a jitted call.
    Entries hold weakrefs — a garbage-collected buffer cannot be
    misused, so its entry evaporates."""

    def __init__(self):
        self._lock = threading.Lock()
        self._dead: Dict[int, Tuple[weakref.ref, str]] = {}

    def _sweep_locked(self) -> None:
        gone = [k for k, (r, _) in self._dead.items() if r() is None]
        for k in gone:
            del self._dead[k]

    def mark(self, site: str, *buffers) -> None:
        """Record every array leaf of ``buffers`` as donated at
        ``site``.  A later :meth:`check` on the same object raises."""
        import jax
        with self._lock:
            self._sweep_locked()
            for b in buffers:
                for leaf in jax.tree_util.tree_leaves(b):
                    try:
                        r = weakref.ref(leaf)
                    except TypeError:
                        continue
                    self._dead[id(leaf)] = (r, site)

    def clear(self, *buffers) -> None:
        """Un-mark (a failed dispatch may leave buffers valid)."""
        import jax
        with self._lock:
            for b in buffers:
                for leaf in jax.tree_util.tree_leaves(b):
                    self._dead.pop(id(leaf), None)

    def check(self, use_site: str, *buffers) -> None:
        """Raise when any array leaf of ``buffers`` was donated."""
        import jax
        with self._lock:
            self._sweep_locked()
            for b in buffers:
                for leaf in jax.tree_util.tree_leaves(b):
                    hit = self._dead.get(id(leaf))
                    if hit is not None and hit[0]() is leaf:
                        _TRIPS.labels(mode="donation").inc()
                        raise SanitizerError(
                            f"[sanitize:donation] buffer used at "
                            f"{use_site} was donated at {hit[1]} — "
                            "its storage may already be overwritten")

    def reset(self) -> None:
        with self._lock:
            self._dead.clear()


#: process-wide ledger (one donation namespace per process, like the
#: metrics registry)
ledger = _DonationLedger()


def mark_donated(site: str, *buffers) -> None:
    if active("donation"):
        ledger.mark(site, *buffers)


def check_not_donated(use_site: str, *buffers) -> None:
    if active("donation"):
        ledger.check(use_site, *buffers)


def clear_donated(*buffers) -> None:
    if active("donation"):
        ledger.clear(*buffers)
