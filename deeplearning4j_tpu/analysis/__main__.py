import sys

from deeplearning4j_tpu.analysis.cli import main

sys.exit(main())
