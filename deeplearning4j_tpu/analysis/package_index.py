"""Package-wide program index: symbol table + call graph + fact cache.

PR 4's passes are MODULE-LOCAL: ``jit_lint`` stops a trace context at
the file boundary and ``concurrency_lint`` only sees locks stored on
``self`` — which makes exactly the code most likely to retrace or race
invisible (trace contexts in ``parallel/`` calling helpers in
``models/`` and ``kernels/``, fault-injection state in ``resilience/``
mutated from the decode scheduler's threads).  This module builds the
whole-package view both passes need, the way the Julia→TPU compiler
(PAPERS: arxiv 1810.09868) proves offloadability over whole call
graphs rather than per function:

* **module summaries** — per file, a serializable digest of the facts
  the cross-module rules consume: imports (aliases resolved to package
  modules), function defs with their calls / host-impure operations /
  ``Static``/``Traced``/class-typed parameter annotations
  (:mod:`~deeplearning4j_tpu.analysis.annotations`), class defs with
  lock provenance (``self`` locks, locks passed into ``__init__``,
  module-level locks), thread targets, and module-level state writes;
* **symbol table** — import-resolution across the package: a dotted
  reference in module A resolves to the def in module B it names,
  including ``from x import y`` chains, module aliases, class
  inheritance folded across modules (MRO), constructor-typed
  attributes (``self._gen = TransformerGenerator(...)``), local
  aliases (``gen = self._gen``), and single-hop higher-order returns
  (``pick = self._sampler(s)`` then ``pick(x)`` reaches the functions
  ``_sampler`` returns);
* **call graph** — edges over resolved calls, used two ways:
  trace-context closure (``jit_lint.lint_package`` walks entries
  through cross-module callees → JIT106) and thread-reachability
  closure (``concurrency_lint.lint_package`` seeds from every thread
  target / public method of a lock-owning class → CONC205/206);
* **on-disk cache** — per-file summaries AND per-file local findings
  keyed by (mtime, size), so the CI gate re-parses only what changed;
  cross-module findings are recomputed from summaries every run (pure
  dict work, milliseconds).

Nothing is imported or executed from the indexed tree — pure AST
walking, like the per-module passes.
"""
from __future__ import annotations

import ast
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.analysis import annotations as _ann
from deeplearning4j_tpu.analysis.astutil import (FuncDef, FuncIndex,
                                                 add_parents, dotted)

#: bump when the summary schema changes — stale caches self-invalidate
CACHE_VERSION = 2

_MUTABLE_CTORS = {"dict", "list", "set", "OrderedDict", "defaultdict",
                  "deque", "Counter"}
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
#: names too generic for the unique-method fallback resolution
_FALLBACK_MIN_LEN = 4
#: builtin container/sync/file method names the unique-method fallback
#: must NEVER resolve: ``in_specs.append(x)`` is a plain list append,
#: not a call into the one package class that happens to define
#: ``append`` (the PR-18 false JIT106 edges into TimeSeriesStore came
#: exactly from this).  Losing a true edge here only shrinks closures
#: (fewer findings, never new ones), so the list errs broad.
_FALLBACK_DENY = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update",
    "clear", "remove", "discard", "pop", "popleft", "popitem",
    "setdefault", "sort", "reverse", "copy", "count", "index",
    "items", "keys", "values", "get", "put", "join", "split",
    "strip", "format", "encode", "decode", "read", "write", "close",
    "flush", "acquire", "release", "wait", "notify", "notify_all",
    "result", "cancel", "is_alive", "is_set", "send", "recv",
})

#: callback-registration method names: ``table.append(fn)`` /
#: ``sinks.add(fn)`` / ``bus.register(fn)`` store a callable into a
#: container another thread may later drain (CONC303 facts)
_CB_REGISTER = {"append", "add", "insert", "register", "subscribe",
                "attach", "setdefault", "on", "connect"}

#: how long a constant ``time.sleep`` must be before it counts as a
#: blocking call (scheduler breathers under 50 ms are noise)
_SLEEP_THRESHOLD_S = 0.05
_SUBPROCESS_FNS = {"run", "check_output", "check_call", "call"}
#: module roots whose EVERY call blocks on the network; urllib/http
#: are deliberately absent (urllib.parse is pure string work) — their
#: blocking entry points are caught by method name instead
_NET_ROOTS = {"socket", "requests"}
_NET_METHS = {"recv", "recvfrom", "accept", "urlopen", "getresponse",
              "sendall"}


def module_name(relpath: str) -> str:
    """``deeplearning4j_tpu/parallel/trainer.py`` ->
    ``deeplearning4j_tpu.parallel.trainer`` (``__init__`` drops)."""
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace(os.sep, ".").replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _lockish(parts: Optional[Tuple[str, ...]]) -> bool:
    """A dotted expr that names a lock by convention (``_LOCK``,
    ``self._lock``, ``srv._pool_lock`` ...)."""
    return bool(parts) and "lock" in parts[-1].lower()


def _is_ctor_of(call: ast.Call, names: Set[str]) -> bool:
    parts = dotted(call.func)
    return parts is not None and parts[-1] in names


def _is_lock_parts(parts: Optional[Tuple[str, ...]],
                   module_locks: Set[str]) -> bool:
    """A lock either by NAME convention or by module-level constructor
    provenance (``_MUTEX = threading.Lock()``)."""
    return _lockish(parts) or (
        parts is not None and len(parts) == 1
        and parts[0] in module_locks)


def blocking_call_detail(call: ast.Call) -> Optional[str]:
    """Why this call can block indefinitely (or long enough to matter
    under a lock), or None.  Purely syntactic — the lock-order pass
    (CONC302) decides whether a lock is actually held around it."""
    parts = dotted(call.func)
    if parts is None:
        return None
    name = parts[-1]
    nargs = len(call.args)
    kw = {k.arg for k in call.keywords}
    timed = "timeout" in kw
    if name == "sleep" and (len(parts) == 1 or parts[-2] == "time"):
        if nargs == 1 and isinstance(call.args[0], ast.Constant) and \
                isinstance(call.args[0].value, (int, float)) and \
                call.args[0].value < _SLEEP_THRESHOLD_S:
            return None
        return "time.sleep(...)"
    if name == "join" and nargs == 0 and not timed:
        # "".join(xs) / os.path.join(a, b) always take arguments —
        # the zero-arg form is a thread/process join
        return "join() without timeout"
    if name == "get" and nargs == 0 and not kw:
        # dict.get() requires a key: the bare form is a queue get
        return "get() without timeout"
    if name in ("result", "wait") and nargs == 0 and not timed:
        return f"{name}() without timeout"
    if name == "communicate" and not timed:
        return "communicate() without timeout"
    if parts[0] == "subprocess" and name in _SUBPROCESS_FNS and \
            not timed:
        return f"subprocess.{name}(...)"
    if parts[0] in _NET_ROOTS or name in _NET_METHS:
        return f"{'.'.join(parts)}(...) network I/O"
    return None


# ---------------------------------------------------------------------------
# per-module summary extraction
# ---------------------------------------------------------------------------

class _Extractor:
    """One module -> serializable summary dict (see module docstring)."""

    def __init__(self, tree: ast.Module, relpath: str, modname: str):
        self.tree = tree
        self.relpath = relpath
        self.modname = modname
        # an __init__.py IS its package: relative imports anchor at
        # modname itself, not at its parent like a plain module's do
        self.is_package = os.path.basename(relpath) == "__init__.py"
        self.parents = add_parents(tree)
        self.findex = FuncIndex(tree, self.parents)

    def run(self) -> Dict:
        imports = self._imports()
        classes = self._classes()
        module_state, module_locks = self._module_state()
        functions: Dict[str, Dict] = {}
        for fn in self.findex.defs:
            qn = self.findex.qualname[fn]
            env = self._inherited_env(fn, classes)
            functions[qn] = self._function(fn, qn, env, classes,
                                           module_state, module_locks)
        traced_local = self._traced_local()
        return {
            "module": self.modname,
            "path": self.relpath,
            "imports": imports,
            "classes": classes,
            "functions": functions,
            "module_state": module_state,
            "module_locks": sorted(module_locks),
            "thread_target_fns": self._module_thread_targets(),
            "entry_calls": self._entry_calls(),
            "traced_local": traced_local,
        }

    # -- imports -------------------------------------------------------
    def _imports(self) -> Dict[str, List]:
        """alias -> [module, attr-or-None].  ``import a.b.c`` binds
        ``a`` (resolution walks the chain); relative imports resolve
        against this module's package."""
        out: Dict[str, List] = {}
        if self.is_package:
            pkg = self.modname
        else:
            pkg = self.modname.rsplit(".", 1)[0] \
                if "." in self.modname else self.modname
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        out[alias.asname] = [alias.name, None]
                    else:
                        out[alias.name.split(".")[0]] = \
                            [alias.name.split(".")[0], None]
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    up = pkg.split(".")
                    up = up[: len(up) - (node.level - 1)]
                    base = ".".join(up + ([base] if base else []))
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    out[alias.asname or alias.name] = [base, alias.name]
        return out

    # -- classes -------------------------------------------------------
    def _classes(self) -> Dict[str, Dict]:
        from deeplearning4j_tpu.analysis import concurrency_lint as _cl
        scanner = _cl._ModuleLint(self.tree, self.relpath)
        out: Dict[str, Dict] = {}
        for node in self.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            ci = scanner._scan_class(node)
            out[node.name] = {
                "line": node.lineno,
                "bases": [list(p) for p in
                          (dotted(b) for b in node.bases) if p],
                "methods": sorted(ci.methods),
                "lock_attrs": sorted(ci.lock_attrs),
                "guarded": sorted(ci.guarded),
                "thread_targets": sorted(ci.thread_targets),
                "starts_threads": ci.starts_threads,
                "attr_types": self._attr_types(node),
            }
        return out

    def _attr_types(self, cls: ast.ClassDef) -> Dict[str, List[str]]:
        """``self.X = Cls(...)`` and ``self.X = <typed param>`` give
        the attribute a class type the resolver can use."""
        out: Dict[str, List[str]] = {}
        for m in cls.body:
            if not isinstance(m, FuncDef):
                continue
            _, _, ptypes = _ann.param_annotations(m)
            for n in ast.walk(m):
                if not isinstance(n, ast.Assign) or len(n.targets) != 1:
                    continue
                t = dotted(n.targets[0])
                if not (t and t[0] == "self" and len(t) == 2):
                    continue
                if isinstance(n.value, ast.Call):
                    cp = dotted(n.value.func)
                    if cp and cp[-1][:1].isupper():
                        out[t[1]] = list(cp)
                elif isinstance(n.value, ast.Name) and \
                        n.value.id in ptypes:
                    out[t[1]] = [ptypes[n.value.id]]
        return out

    # -- module-level state --------------------------------------------
    def _module_state(self) -> Tuple[Dict[str, Dict], Set[str]]:
        state: Dict[str, Dict] = {}
        locks: Set[str] = set()
        for node in self.tree.body:
            targets: List[ast.AST] = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                kind = "other"
                if isinstance(value, ast.Call) and \
                        _is_ctor_of(value, _LOCK_CTORS):
                    kind = "lock"
                    locks.add(t.id)
                elif isinstance(value, (ast.Dict, ast.List, ast.Set,
                                        ast.ListComp, ast.DictComp,
                                        ast.SetComp)) or \
                        (isinstance(value, ast.Call) and
                         _is_ctor_of(value, _MUTABLE_CTORS)):
                    kind = "mutable"
                state[t.id] = {"line": t.lineno, "kind": kind}
        return state, locks

    def _module_thread_targets(self) -> List[List[str]]:
        """``threading.Thread(target=X)`` where X is NOT ``self.meth``
        — a module function or an imported one (cross-module thread
        target, invisible to the per-class pass)."""
        out: List[List[str]] = []
        for n in ast.walk(self.tree):
            if not (isinstance(n, ast.Call) and
                    (p := dotted(n.func)) and p[-1] == "Thread"):
                continue
            for kw in n.keywords:
                if kw.arg != "target":
                    continue
                tp = dotted(kw.value)
                if tp and tp[0] not in ("self", "cls"):
                    out.append(list(tp))
        return out

    def _entry_calls(self) -> List[List[str]]:
        """Module-level calls (including under ``if __name__ ==
        "__main__":``) — what running the file as a script executes
        with no thread/class context.  Seeds the lock-order pass's
        thread-reachability for ``scripts/`` entry points."""
        out: List[List[str]] = []
        queue: List[ast.AST] = list(self.tree.body)
        i = 0
        while i < len(queue):
            n = queue[i]
            i += 1
            if isinstance(n, FuncDef + (ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(n, ast.Call):
                p = dotted(n.func)
                if p:
                    out.append(list(p))
            queue.extend(ast.iter_child_nodes(n))
        return out

    # -- trace entries (local pass's view) -----------------------------
    def _traced_local(self) -> Dict[str, List[str]]:
        from deeplearning4j_tpu.analysis import jit_lint as _jl
        lint = _jl._ModuleLint(self.tree, self.relpath)
        lint.collect_entries()
        return {lint.index.qualname[fn]: sorted(static)
                for fn, static in lint.traced.items()}

    # -- per-function facts --------------------------------------------
    def _inherited_env(self, fn: ast.AST, classes: Dict) -> Dict:
        """Type/alias environment inherited from enclosing functions
        (closures see the outer scope's ``gen = self._gen``)."""
        chain: List[ast.AST] = []
        cur = self.parents.get(fn)
        while cur is not None:
            if isinstance(cur, FuncDef):
                chain.append(cur)
            cur = self.parents.get(cur)
        env: Dict = {"types": {}, "via": {}}
        for outer in reversed(chain):
            oenv = self._local_env(outer, classes)
            env["types"].update(oenv["types"])
            env["via"].update(oenv["via"])
        return env

    def _owner_attr_types(self, fn: ast.AST, classes: Dict) -> Dict:
        cls = self.findex.owner_class.get(fn)
        if cls is None:
            # nested functions: the enclosing method's class
            cur = self.parents.get(fn)
            while cur is not None and cls is None:
                if isinstance(cur, FuncDef):
                    cls = self.findex.owner_class.get(cur)
                cur = self.parents.get(cur)
        if cls is None:
            return {}
        return classes.get(cls.name, {}).get("attr_types", {})

    def _local_env(self, fn: ast.AST, classes: Dict) -> Dict:
        """types: var -> class-ref parts; via: var -> callee parts
        whose RETURNED functions the var aliases."""
        types: Dict[str, List[str]] = {}
        via: Dict[str, List[str]] = {}
        _, _, ptypes = _ann.param_annotations(fn)
        for p, cname in ptypes.items():
            types[p] = [cname]
        attr_types = self._owner_attr_types(fn, classes)
        for n in self._body(fn):
            if not isinstance(n, ast.Assign) or len(n.targets) != 1:
                continue
            if not isinstance(n.targets[0], ast.Name):
                continue
            name = n.targets[0].id
            v = n.value
            if isinstance(v, ast.Call):
                cp = dotted(v.func)
                if cp and cp[-1][:1].isupper():
                    types[name] = list(cp)        # v = Cls(...)
                elif cp:
                    via[name] = list(cp)          # v = f(...): returns
            else:
                vp = dotted(v)
                if vp and vp[0] == "self" and len(vp) == 2 and \
                        vp[1] in attr_types:
                    types[name] = attr_types[vp[1]]   # v = self._gen
        return {"types": types, "via": via}

    def _body(self, fn: ast.AST):
        """fn's own statements, excluding nested function bodies."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            n = stack.pop()
            yield n
            if not isinstance(n, FuncDef + (ast.Lambda,)):
                stack.extend(ast.iter_child_nodes(n))

    def _locked_nodes(self, fn: ast.AST,
                      module_locks: Set[str] = frozenset()
                      ) -> Dict[int, List[Tuple]]:
        """id(node) -> [lock parts] for nodes inside ``with <lock>:``
        blocks — a lock either by NAME convention (``_LOCK``,
        ``self._lock``, ``server._pool_lock``) or by module-level
        CONSTRUCTOR provenance (``_MUTEX = threading.Lock()`` counts
        even though nothing in the name says so)."""
        out: Dict[int, List[Tuple]] = {}
        for n in self._body(fn):
            if not isinstance(n, ast.With):
                continue
            lock_parts = [dotted(i.context_expr) for i in n.items
                          if _is_lock_parts(dotted(i.context_expr),
                                            module_locks)]
            if not lock_parts:
                continue
            for stmt in n.body:
                for sub in ast.walk(stmt):
                    out.setdefault(id(sub), []).extend(lock_parts)
        return out

    def _function(self, fn: ast.AST, qn: str, inherited: Dict,
                  classes: Dict, module_state: Dict,
                  module_locks: Set[str]) -> Dict:
        from deeplearning4j_tpu.analysis import jit_lint as _jl
        env = self._local_env(fn, classes)
        types = dict(inherited["types"]); types.update(env["types"])
        via = dict(inherited["via"]); via.update(env["via"])
        attr_types = self._owner_attr_types(fn, classes)
        locked = self._locked_nodes(fn, module_locks)
        owner = self.findex.owner_class.get(fn)

        calls: List[Dict] = []
        impure: List[List] = []
        module_writes: List[List] = []
        foreign: List[List] = []
        globals_declared: Set[str] = set()
        local_stores: Set[str] = set()
        returns_fns: List[str] = []
        acquires: List[List] = []
        blocking: List[List] = []
        cb_stores: List[List] = []
        cb_invokes: List[List] = []

        def held_at(node: ast.AST) -> List[List[str]]:
            """Deduped lock parts lexically held around ``node``."""
            out: List[List[str]] = []
            for lp in locked.get(id(node), ()):
                l = list(lp)
                if l not in out:
                    out.append(l)
            return out

        def type_of_base(node: ast.AST) -> Optional[List[str]]:
            p = dotted(node)
            if p is None:
                return None
            if len(p) == 1 and p[0] in types:
                return types[p[0]]
            if len(p) == 2 and p[0] == "self" and p[1] in attr_types:
                return attr_types[p[1]]
            return None

        def base_locked(node: ast.AST, base: ast.AST) -> bool:
            """access ``base.attr`` inside ``with base.<lock>:``?"""
            bp = dotted(base)
            for lp in locked.get(id(node), ()):
                if lp and tuple(lp[:-1]) == tuple(bp or ()):
                    return True
            return False

        # pass 0: global declarations first (they change how stores in
        # the later passes classify)
        for n in self._body(fn):
            if isinstance(n, ast.Global):
                globals_declared.update(n.names)
                impure.append([n.lineno, "global",
                               "global " + ", ".join(n.names)])

        # container-drain aliases: ``for cb in self._sinks:`` /
        # ``cb = self._tbl[k]`` / ``cb = self._tbl.get(k)`` bind a name
        # whose CALL is an invocation through the container (CONC303)
        drained: Dict[str, List[str]] = {}
        for n in self._body(fn):
            if isinstance(n, ast.For):
                it = n.iter
                if isinstance(it, ast.Call) and \
                        (ip := dotted(it.func)) and \
                        ip[-1] in ("list", "tuple", "sorted") and it.args:
                    it = it.args[0]
                if isinstance(it, ast.Call) and \
                        isinstance(it.func, ast.Attribute) and \
                        it.func.attr in ("values", "items") and \
                        not it.args:
                    it = it.func.value
                cont = dotted(it)
                if not cont:
                    continue
                tgt = n.target
                if isinstance(tgt, ast.Tuple) and len(tgt.elts) == 2:
                    tgt = tgt.elts[1]       # for key, cb in tbl.items()
                if isinstance(tgt, ast.Name):
                    drained[tgt.id] = list(cont)
            elif isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name):
                v = n.value
                cont = None
                if isinstance(v, ast.Subscript):
                    cont = dotted(v.value)
                elif isinstance(v, ast.Call) and \
                        isinstance(v.func, ast.Attribute) and \
                        v.func.attr == "get":
                    cont = dotted(v.func.value)
                if cont:
                    drained[n.targets[0].id] = list(cont)

        for n in self._body(fn):
            if isinstance(n, FuncDef):
                pass
            elif isinstance(n, ast.With):
                # lock-acquisition site: which lock, under which
                # already-held locks (nested with-regions give the
                # direct lock-order edges)
                w_held = held_at(n)
                for item in n.items:
                    lp = dotted(item.context_expr)
                    if not _is_lock_parts(lp, module_locks):
                        continue
                    bt = None
                    if len(lp) >= 2 and \
                            isinstance(item.context_expr, ast.Attribute):
                        bt = type_of_base(item.context_expr.value)
                    acquires.append([n.lineno, list(lp), bt, w_held])
            elif isinstance(n, ast.Call):
                detail = _jl.host_impure_detail(n)
                if detail:
                    impure.append([n.lineno, "host_call", detail])
                held = held_at(n)
                if isinstance(n.func, ast.Subscript) and \
                        (sp := dotted(n.func.value)):
                    cb_invokes.append([n.lineno, list(sp), held])
                elif isinstance(n.func, ast.Name) and \
                        n.func.id in drained:
                    cb_invokes.append([n.lineno,
                                       drained[n.func.id], held])
                cp = dotted(n.func)
                if cp is not None:
                    entry: Dict = {"line": n.lineno}
                    base_t = None
                    if len(cp) >= 2:
                        base_t = type_of_base(n.func.value) \
                            if isinstance(n.func, ast.Attribute) else None
                    if base_t is not None:
                        entry["type"] = base_t
                        entry["meth"] = cp[-1]
                    elif len(cp) == 1 and cp[0] in via:
                        entry["via"] = via[cp[0]]
                    else:
                        entry["parts"] = list(cp)
                    if held:
                        entry["locks"] = held
                    calls.append(entry)
                    bdetail = blocking_call_detail(n)
                    if bdetail is not None:
                        blocking.append([n.lineno, bdetail,
                                         list(cp), held])
                    if len(cp) >= 2 and cp[-1] in _CB_REGISTER:
                        # the full call + receiver type ride along so
                        # the lock-order pass can follow ONE forwarding
                        # hop (bus.subscribe(cb) appends its param to
                        # the real table inside Bus.subscribe)
                        for arg in n.args:
                            fp = dotted(arg)
                            if fp:
                                cb_stores.append([n.lineno,
                                                  list(cp[:-1]),
                                                  list(fp), held,
                                                  list(cp), base_t])
            elif isinstance(n, ast.Return) and n.value is not None:
                vals = [n.value]
                if isinstance(n.value, ast.IfExp):
                    vals = [n.value.body, n.value.orelse]
                for v in vals:
                    if isinstance(v, ast.Name):
                        hit = self.findex.resolve_name(v.id, n)
                        if hit is not None:
                            returns_fns.append(self.findex.qualname[hit])

        # stores: self mutations, module-state writes
        def _store_targets(n):
            if isinstance(n, ast.Assign):
                return list(n.targets)
            if isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                return [n.target]
            if isinstance(n, ast.Delete):
                return list(n.targets)
            return []

        # pass 1: which names are plain local binds (shadowing) —
        # parameters shadow module state exactly like assignments do
        a = fn.args
        for p in (a.posonlyargs + a.args + a.kwonlyargs +
                  ([a.vararg] if a.vararg else []) +
                  ([a.kwarg] if a.kwarg else [])):
            local_stores.add(p.arg)
        for n in self._body(fn):
            for t in _store_targets(n):
                for tt in ast.walk(t):
                    if isinstance(tt, ast.Name) and \
                            isinstance(tt.ctx, (ast.Store, ast.Del)) \
                            and tt.id not in globals_declared:
                        local_stores.add(tt.id)
        # pass 2: module-state writes + self-mutations
        self_store_bases: Set[int] = set()
        for n in self._body(fn):
            guard = bool(locked.get(id(n)))
            for t in _store_targets(n):
                for tt in ast.walk(t):
                    if isinstance(tt, ast.Name) and \
                            isinstance(tt.ctx, (ast.Store, ast.Del)) \
                            and tt.id in globals_declared:
                        module_writes.append([tt.lineno, tt.id, guard])
                    if isinstance(tt, ast.Subscript) and \
                            isinstance(tt.value, ast.Name):
                        name = tt.value.id
                        if name in globals_declared or (
                                name in module_state and
                                name not in local_stores):
                            module_writes.append([tt.lineno, name,
                                                  guard])
                    # self.<attr> (incl. element stores) = trace-time
                    # host mutation when reached from a trace context.
                    # The walk visits both `self.buf[0]` and the inner
                    # `self.buf` — dedupe on the Attribute node itself
                    # so one statement yields one fact.
                    base = tt
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if isinstance(base, ast.Attribute) and \
                            isinstance(base.value, ast.Name) and \
                            base.value.id == "self" and \
                            id(base) not in self_store_bases:
                        self_store_bases.add(id(base))
                        impure.append([base.lineno, "self_store",
                                       f"self.{base.attr}"])

        # foreign typed-object attribute accesses (CONC206 facts)
        for n in self._body(fn):
            if not isinstance(n, ast.Attribute):
                continue
            base_t = type_of_base(n.value)
            if base_t is None:
                continue
            if _lockish((n.attr,)):
                continue                 # the lock itself
            parent = self.parents.get(n)
            if isinstance(parent, ast.Call) and parent.func is n:
                continue                 # method call: API use, not state
            kind = "store" if isinstance(n.ctx, (ast.Store, ast.Del)) \
                else "load"
            if kind == "load":
                # element store through the attribute
                pp = self.parents.get(n)
                if isinstance(pp, ast.Subscript) and \
                        isinstance(pp.ctx, (ast.Store, ast.Del)):
                    kind = "store"
            foreign.append([n.lineno, base_t, n.attr, kind,
                            base_locked(n, n.value)])

        # handler-table registration through subscript assignment:
        # ``self._handlers[kind] = self._on_kind``
        for n in self._body(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Subscript):
                cont = dotted(n.targets[0].value)
                fp = dotted(n.value)
                if cont and fp:
                    cb_stores.append([n.lineno, list(cont), list(fp),
                                      held_at(n), None, None])

        static_ann, traced_ann, ptypes = _ann.param_annotations(fn)
        return {
            "line": fn.lineno,
            "cls": owner.name if owner is not None else None,
            "nested": [self.findex.qualname[d]
                       for d in self.findex.scope_children.get(fn, {})
                       .values()],
            "static_ann": sorted(static_ann),
            "traced_ann": sorted(traced_ann),
            "param_types": ptypes,
            "calls": calls,
            "impure": impure,
            "module_writes": module_writes,
            "foreign": foreign,
            "returns_fns": sorted(set(returns_fns)),
            "acquires": acquires,
            "blocking": blocking,
            "cb_stores": cb_stores,
            "cb_invokes": cb_invokes,
        }


def summarize_module(tree: ast.Module, relpath: str,
                     modname: Optional[str] = None) -> Dict:
    return _Extractor(tree, relpath,
                      modname or module_name(relpath)).run()


# ---------------------------------------------------------------------------
# the index
# ---------------------------------------------------------------------------

class PackageIndex:
    """Cross-module resolution over a set of module summaries.

    Function ids are ``"<module>::<qualname>"``; class ids are
    ``(module, ClassName)``.  All resolution is best-effort and
    returns nothing rather than guessing wildly — the one deliberate
    heuristic is the unique-method fallback (an ``obj.meth(...)`` call
    resolves when exactly one class in the whole package defines
    ``meth`` and the name is specific enough), which trace/thread
    closures need for duck-typed callees."""

    def __init__(self, summaries: Dict[str, Dict],
                 aux: Iterable[str] = ()):
        #: module name -> summary
        self.modules = summaries
        #: modules indexed only to SEED reachability (scripts/ entry
        #: points) — cross-module passes must not report findings in
        #: them, only follow their edges into the package
        self.aux_modules: Set[str] = set(aux)
        self.functions: Dict[str, Dict] = {}
        self.func_module: Dict[str, str] = {}
        self._methods_by_name: Dict[str, List[str]] = {}
        self._classes_by_name: Dict[str, List[Tuple[str, str]]] = {}
        for mod, s in summaries.items():
            for qn, f in s["functions"].items():
                fid = f"{mod}::{qn}"
                self.functions[fid] = f
                self.func_module[fid] = mod
                if f["cls"] is not None:
                    self._methods_by_name.setdefault(
                        qn.rsplit(".", 1)[-1], []).append(fid)
            for cname in s["classes"]:
                self._classes_by_name.setdefault(cname, []).append(
                    (mod, cname))

    # -- stats ----------------------------------------------------------
    @property
    def n_modules(self) -> int:
        return len(self.modules)

    def is_aux(self, mod: str) -> bool:
        return mod in self.aux_modules

    # -- symbol resolution ---------------------------------------------
    def resolve_import(self, mod: str, name: str
                       ) -> Optional[Tuple[str, Optional[str]]]:
        """An imported alias in ``mod`` -> (target module, attr|None),
        following one re-export hop (``from a import b`` where ``a``
        itself imported ``b`` from elsewhere)."""
        s = self.modules.get(mod)
        if s is None:
            return None
        hit = s["imports"].get(name)
        if hit is None:
            return None
        base, attr = hit
        if attr is None:
            return (base, None)
        sub = f"{base}.{attr}"
        if sub in self.modules:
            return (sub, None)
        if base in self.modules:
            tgt = self.modules[base]
            if attr in tgt["functions"] or attr in tgt["classes"]:
                return (base, attr)
            # re-export hop (package __init__)
            re_hit = tgt["imports"].get(attr)
            if re_hit is not None:
                b2, a2 = re_hit
                if a2 is None:
                    return (b2, None) if b2 in self.modules else None
                if f"{b2}.{a2}" in self.modules:
                    return (f"{b2}.{a2}", None)
                if b2 in self.modules:
                    return (b2, a2)
        return (base, attr)

    def resolve_class(self, mod: str, parts: Sequence[str],
                      _depth: int = 0) -> Optional[Tuple[str, str]]:
        """A class reference (possibly dotted / imported / unique-named
        elsewhere in the package) -> (module, ClassName)."""
        if _depth > 8:
            return None
        parts = list(parts)
        s = self.modules.get(mod)
        if s is not None and len(parts) == 1 and parts[0] in s["classes"]:
            return (mod, parts[0])
        if s is not None and parts:
            hop = self.resolve_import(mod, parts[0])
            if hop is not None:
                tmod, attr = hop
                rest = ([attr] if attr else []) + parts[1:]
                if not rest:
                    return None
                if len(rest) == 1 and tmod in self.modules and \
                        rest[0] in self.modules[tmod]["classes"]:
                    return (tmod, rest[0])
                if tmod in self.modules:
                    return self.resolve_class(tmod, rest, _depth + 1)
                # walk module chain: tmod.a.b.Cls
                chain, cls = rest[:-1], rest[-1]
                target = tmod + ("." + ".".join(chain) if chain else "")
                if target in self.modules and \
                        cls in self.modules[target]["classes"]:
                    return (target, cls)
        # unique name across the package
        cands = self._classes_by_name.get(parts[-1], [])
        if len(cands) == 1:
            return cands[0]
        return None

    def class_mro(self, mod: str, cname: str,
                  _depth: int = 0) -> List[Tuple[str, str]]:
        """(module, class) chain, subclass first, bases folded across
        modules."""
        out = [(mod, cname)]
        if _depth > 6:
            return out
        cls = self.modules.get(mod, {}).get("classes", {}).get(cname)
        for bp in (cls or {}).get("bases", []):
            hit = self.resolve_class(mod, bp)
            if hit is not None and hit not in out:
                out.extend(h for h in
                           self.class_mro(hit[0], hit[1], _depth + 1)
                           if h not in out)
        return out

    def class_facts(self, mod: str, cname: str) -> Dict:
        """Lock/guard facts with cross-module bases folded in."""
        lock_attrs: Set[str] = set()
        guarded: Set[str] = set()
        for m, c in self.class_mro(mod, cname):
            cls = self.modules.get(m, {}).get("classes", {}).get(c)
            if cls:
                lock_attrs.update(cls["lock_attrs"])
                guarded.update(cls["guarded"])
        return {"lock_attrs": lock_attrs, "guarded": guarded}

    def resolve_method(self, mod: str, cname: str, meth: str
                       ) -> Optional[str]:
        for m, c in self.class_mro(mod, cname):
            fid = f"{m}::{c}.{meth}"
            if fid in self.functions:
                return fid
            # nested classes / multi-level qualnames — require a dot
            # boundary (ThreadServer.run must not satisfy Server.run)
            s = self.modules.get(m)
            if s:
                for qn in s["functions"]:
                    if qn == f"{c}.{meth}" or \
                            qn.endswith(f".{c}.{meth}"):
                        return f"{m}::{qn}"
        return None

    def resolve_module_fn(self, mod: str, parts: Sequence[str]
                          ) -> Optional[str]:
        """A non-method dotted call -> fid, through import aliases and
        module chains."""
        parts = list(parts)
        s = self.modules.get(mod)
        if s is None or not parts:
            return None
        if len(parts) == 1:
            # top-level def in this module (any enclosing scope)
            if parts[0] in s["functions"]:
                return f"{mod}::{parts[0]}"
            hop = self.resolve_import(mod, parts[0])
            if hop is not None:
                tmod, attr = hop
                if attr is not None and tmod in self.modules and \
                        attr in self.modules[tmod]["functions"]:
                    return f"{tmod}::{attr}"
            return None
        hop = self.resolve_import(mod, parts[0])
        if hop is not None:
            tmod, attr = hop
            rest = ([attr] if attr else []) + parts[1:]
            chain, fn = rest[:-1], rest[-1]
            target = tmod + ("." + ".".join(chain) if chain else "")
            if target in self.modules and \
                    fn in self.modules[target]["functions"]:
                return f"{target}::{fn}"
            # attr of an imported CLASS (Cls.method reference)
            if tmod in self.modules and chain and \
                    chain[0] in self.modules[tmod]["classes"]:
                return self.resolve_method(tmod, chain[0], fn)
        return None

    def resolve_call(self, fid: str, call: Dict) -> List[str]:
        """A recorded call entry -> candidate callee fids."""
        mod = self.func_module[fid]
        fn = self.functions[fid]
        if "type" in call:
            hit = self.resolve_class(mod, call["type"])
            if hit is None:
                return []
            m = self.resolve_method(hit[0], hit[1], call["meth"])
            return [m] if m else []
        if "via" in call:
            # pick = self._sampler(s); pick(x) -> _sampler's returns
            target = self._resolve_parts(fid, call["via"])
            out: List[str] = []
            for t in target:
                tmod = self.func_module[t]
                for rqn in self.functions[t].get("returns_fns", ()):
                    rfid = f"{tmod}::{rqn}"
                    if rfid in self.functions:
                        out.append(rfid)
            return out
        return self._resolve_parts(fid, call.get("parts", []))

    def _resolve_parts(self, fid: str, parts: Sequence[str]
                       ) -> List[str]:
        mod = self.func_module[fid]
        cls = self.functions[fid]["cls"]
        return self.resolve_in_module(mod, parts, cls=cls)

    def resolve_in_module(self, mod: str, parts: Sequence[str],
                          cls: Optional[str] = None) -> List[str]:
        """Resolve a dotted reference as seen from ``mod`` (optionally
        from inside class ``cls``) — the fid-free core used both for
        calls and for module-level Thread targets."""
        if not parts or mod not in self.modules:
            return []
        parts = list(parts)
        if parts[0] in ("self", "cls") and len(parts) == 2 and cls:
            m = self.resolve_method(mod, cls, parts[1])
            return [m] if m else []
        if parts[0] in ("self", "cls"):
            return []
        hit = self.resolve_module_fn(mod, parts)
        if hit is not None:
            return [hit]
        if len(parts) == 1:
            # a sibling method referenced bare inside its own class
            # scope resolves through FuncIndex at extraction; here a
            # bare unresolved name is a builtin or external — skip.
            # (local defs are in functions under their qualname tail)
            s = self.modules[mod]
            cands = [qn for qn in s["functions"]
                     if qn == parts[0] or qn.endswith("." + parts[0])]
            if len(cands) == 1:
                return [f"{mod}::{cands[0]}"]
            return []
        # unique-method fallback: obj.meth(...) with exactly one
        # candidate class method in the whole package.  Never applied
        # when the call is rooted at an imported name that resolved to
        # nothing above — ``np.dtype(...)`` targets numpy, not the one
        # package class that happens to define a ``dtype`` method.
        if parts[0] in self.modules[mod]["imports"]:
            return []
        meth = parts[-1]
        if meth not in _FALLBACK_DENY and \
                (len(meth) >= _FALLBACK_MIN_LEN or meth.startswith("_")):
            cands = self._methods_by_name.get(meth, [])
            if len(cands) == 1:
                return [cands[0]]
        return []

    # -- closures -------------------------------------------------------
    def closure(self, seeds: Iterable[str]
                ) -> Dict[str, Optional[str]]:
        """Call-graph closure from ``seeds``: fid -> predecessor fid
        (None for seeds).  Nested defs ride along with their parent.

        Deterministic BFS over SORTED seeds/neighbors: every run
        assigns the same (shortest, ties lexicographic) predecessor
        chain, so the reach chains rendered into finding messages —
        and therefore baseline keys — are stable across processes
        (str hash randomization must not leak into the report)."""
        from collections import deque
        parent: Dict[str, Optional[str]] = {}
        frontier = deque(sorted(
            s for s in set(seeds) if s in self.functions))
        for s in frontier:
            parent.setdefault(s, None)
        while frontier:
            fid = frontier.popleft()
            f = self.functions[fid]
            mod = self.func_module[fid]
            nxt: List[str] = []
            for call in f["calls"]:
                nxt.extend(self.resolve_call(fid, call))
            nxt.extend(f"{mod}::{qn}" for qn in f.get("nested", ()))
            for t in sorted(set(nxt)):
                if t in self.functions and t not in parent:
                    parent[t] = fid
                    frontier.append(t)
        return parent

    def chain(self, parent: Dict[str, Optional[str]], fid: str,
              limit: int = 4) -> str:
        """Render ``seed -> ... -> fid`` (shortened) for messages."""
        hops = [fid]
        cur = parent.get(fid)
        while cur is not None and len(hops) < 32:
            hops.append(cur)
            cur = parent.get(cur)
        hops.reverse()
        if len(hops) > limit:
            hops = hops[:1] + ["..."] + hops[-(limit - 1):]
        return " -> ".join(h if h == "..." else self.render_fid(h)
                           for h in hops)

    def render_fid(self, fid: str) -> str:
        mod, qn = fid.split("::", 1)
        return f"{self.modules[mod]['path']}::{qn}"

    # -- thread seeds ---------------------------------------------------
    def thread_seeds(self) -> List[str]:
        """Every function another thread can enter: Thread targets
        (``self`` methods AND module/imported functions), plus public
        methods of classes that start threads or own locks."""
        seeds: List[str] = []
        for mod, s in self.modules.items():
            for cname, cls in s["classes"].items():
                entries = set(cls["thread_targets"])
                if cls["starts_threads"] or cls["lock_attrs"]:
                    entries |= {m for m in cls["methods"]
                                if not m.startswith("_")}
                for m in entries:
                    fid = self.resolve_method(mod, cname, m)
                    if fid:
                        seeds.append(fid)
            for tp in s["thread_target_fns"]:
                # resolve in the module that spawns the thread — a
                # launcher module with no defs of its own still seeds
                seeds.extend(self.resolve_in_module(mod, tp))
        return seeds

    def entry_seeds(self) -> List[str]:
        """Functions the aux (``scripts/``) modules' module-level code
        calls — the bare-entry-point reachability the thread closure
        alone misses (a script's main thread IS a thread context)."""
        seeds: List[str] = []
        for mod in sorted(self.aux_modules):
            s = self.modules.get(mod) or {}
            for parts in s.get("entry_calls", ()):
                seeds.extend(self.resolve_in_module(mod, parts))
        return seeds

    # -- trace seeds ----------------------------------------------------
    def traced_local_fids(self) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {}
        for mod, s in self.modules.items():
            for qn, static in s["traced_local"].items():
                out[f"{mod}::{qn}"] = static
        return out


# ---------------------------------------------------------------------------
# build + cache
# ---------------------------------------------------------------------------

def _iter_py(pkg_dir: str) -> Iterable[str]:
    for root, dirs, files in os.walk(pkg_dir):
        dirs[:] = sorted(d for d in dirs
                         if d not in ("__pycache__", ".git"))
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(root, f)


class IndexStats:
    def __init__(self):
        self.modules = 0
        self.parsed = 0
        self.cache_hits = 0
        self.elapsed_s = 0.0


def build_index(pkg_dir: str, root: Optional[str] = None,
                cache_path: Optional[str] = None,
                run_local_passes: bool = True
                ) -> Tuple["PackageIndex", List, IndexStats]:
    """Index every module under ``pkg_dir``.

    Returns ``(index, local_findings, stats)`` — local findings are the
    per-module jit/concurrency passes' output, cached per file beside
    the summaries; cross-module findings are computed by the callers
    (``jit_lint.lint_package`` / ``concurrency_lint.lint_package``)
    from the returned index."""
    import time as _time
    from deeplearning4j_tpu.analysis import concurrency_lint, jit_lint
    from deeplearning4j_tpu.analysis.findings import Finding

    t0 = _time.perf_counter()
    root = os.path.abspath(root or os.getcwd())
    # reported paths are root-relative (baseline keys), but MODULE
    # NAMES must anchor where the package's own imports do — linting a
    # directory outside `root` (scratch trees, tmp fixtures) must
    # still resolve its internal imports.  A package directory is
    # imported fully qualified, so walk UP through the whole
    # __init__.py chain (linting `pkg/sub/` must name modules
    # `pkg.sub.x` or the subpackage's absolute imports of itself never
    # resolve); a flat directory of modules imports its siblings bare
    # (`from b import helper`), so names anchor at the directory.
    modbase = os.path.abspath(pkg_dir)
    while os.path.exists(os.path.join(modbase, "__init__.py")):
        parent = os.path.dirname(modbase)
        if parent == modbase:
            break
        modbase = parent
    stats = IndexStats()

    cache: Dict = {"version": CACHE_VERSION, "files": {}}
    if cache_path and os.path.exists(cache_path):
        try:
            with open(cache_path) as fh:
                loaded = json.load(fh)
            if loaded.get("version") == CACHE_VERSION:
                cache = loaded
        except (OSError, ValueError):
            pass

    summaries: Dict[str, Dict] = {}
    local_findings: List = []
    files_out: Dict[str, Dict] = {}
    for path in _iter_py(pkg_dir):
        rel = os.path.relpath(os.path.abspath(path), root)
        modname = module_name(
            os.path.relpath(os.path.abspath(path), modbase))
        st = os.stat(path)
        stats.modules += 1
        entry = cache["files"].get(rel)
        # a hit must ALSO have been summarized under the same module
        # name — a cache shared between runs with different anchors
        # (subpackage vs whole package) must not inject truncated
        # names that silently break import resolution
        if entry is not None and entry["mtime"] == st.st_mtime and \
                entry["size"] == st.st_size and \
                entry["summary"]["module"] == modname:
            stats.cache_hits += 1
            summaries[entry["summary"]["module"]] = entry["summary"]
            local_findings.extend(
                Finding.from_dict(d) for d in entry["findings"])
            files_out[rel] = entry
            continue
        stats.parsed += 1
        try:
            with open(path, "rb") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except SyntaxError as e:
            f = Finding(rule="PARSE000", severity="error", path=rel,
                        line=e.lineno or 0, symbol="<module>",
                        message=f"file does not parse: {e.msg}")
            local_findings.append(f)
            files_out[rel] = {"mtime": st.st_mtime, "size": st.st_size,
                              "summary": {"module": modname,
                                          "path": rel, "imports": {},
                                          "classes": {}, "functions": {},
                                          "module_state": {},
                                          "module_locks": [],
                                          "thread_target_fns": [],
                                          "entry_calls": [],
                                          "traced_local": {}},
                              "findings": [f.to_dict()]}
            summaries[modname] = files_out[rel]["summary"]
            continue
        summary = summarize_module(tree, rel, modname)
        flist: List = []
        if run_local_passes:
            flist.extend(jit_lint.lint_tree(tree, rel))
            flist.extend(concurrency_lint.lint_tree(tree, rel))
        summaries[summary["module"]] = summary
        local_findings.extend(flist)
        files_out[rel] = {"mtime": st.st_mtime, "size": st.st_size,
                          "summary": summary,
                          "findings": [f.to_dict() for f in flist]}

    # skip the write on a fully-warm run — every entry came from the
    # cache verbatim, so the merged content is what is already on disk
    if cache_path and stats.parsed > 0:
        try:
            # merge, don't replace: a shared cache file serving several
            # linted directories must keep the other directories'
            # entries warm (stale entries for deleted files are inert —
            # they are keyed by paths that no longer get walked)
            merged = dict(cache["files"])
            merged.update(files_out)
            with open(cache_path, "w") as fh:
                json.dump({"version": CACHE_VERSION,
                           "files": merged}, fh)
        except OSError:
            pass

    stats.elapsed_s = _time.perf_counter() - t0
    return PackageIndex(summaries), local_findings, stats


def emit_index_telemetry(stats: IndexStats) -> None:
    """Count an index build into the process metrics registry
    (asserted by ``scripts/check_telemetry.py`` ANALYSIS_SERIES)."""
    from deeplearning4j_tpu import telemetry
    telemetry.counter(
        "lint_modules_indexed_total",
        "modules indexed by the whole-package analysis (cache hits "
        "included — a hit still contributes its summary)",
    ).inc(stats.modules)
    telemetry.histogram(
        "lint_runtime_seconds",
        "wall time of one whole-package index+lint run",
        buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
    ).observe(stats.elapsed_s)
