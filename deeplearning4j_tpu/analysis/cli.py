"""CLI for the static-analysis passes.

::

    python -m deeplearning4j_tpu.analysis [paths...]
        --format=text|json        report format (default text)
        --baseline=FILE           filter findings through a baseline
        --rules=jit,conc          subset of AST passes (default both)
        --graph=FILE.sdz          also lint a serialized SameDiff zip
        --min-severity=warning    drop findings below this severity
        --telemetry               count findings into the process
                                  metrics registry
                                  (lint_findings_total{rule=,severity=})

Exit code: 1 when any finding is NOT covered by the baseline (all
findings are "new" when no baseline is given), else 0.  The CI wrapper
with diff-style reporting and ``--update-baseline`` lives in
``scripts/lint_gate.py``.
"""
from __future__ import annotations

import argparse
import ast
import json
import os
import sys
import time
from typing import Iterable, List, Optional, Sequence

from deeplearning4j_tpu.analysis import concurrency_lint, jit_lint
from deeplearning4j_tpu.analysis.findings import (SEVERITIES, Baseline,
                                                  Finding, sort_findings)

_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}
_AST_PASSES = {"jit": jit_lint, "conc": concurrency_lint}


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def lint_paths(paths: Sequence[str], rules: Sequence[str] = ("jit", "conc"),
               root: Optional[str] = None) -> List[Finding]:
    """Run the AST passes over every .py file under ``paths``.
    ``root`` relativizes reported paths (default: cwd)."""
    root = os.path.abspath(root or os.getcwd())
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(path), root)
        try:
            with open(path, "rb") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except SyntaxError as e:
            findings.append(Finding(
                rule="PARSE000", severity="error", path=rel,
                line=e.lineno or 0, symbol="<module>",
                message=f"file does not parse: {e.msg}"))
            continue
        for r in rules:
            findings.extend(
                Finding(**{**f.to_dict(), "path": rel})
                for f in _AST_PASSES[r].lint_tree(tree, rel))
    return findings


def lint_graph_file(path: str) -> List[Finding]:
    from deeplearning4j_tpu.analysis import graph_lint
    from deeplearning4j_tpu.autodiff.samediff import SameDiff
    sd = SameDiff.load(path)
    return graph_lint.lint_samediff(sd, name=os.path.basename(path))


def emit_telemetry(findings: Sequence[Finding]) -> None:
    """Count findings into the process registry so report tooling
    (check_telemetry / chaos_smoke) covers the analysis subsystem."""
    from deeplearning4j_tpu import telemetry
    fam = telemetry.counter(
        "lint_findings_total",
        "static-analysis findings emitted, by rule and severity",
        labelnames=("rule", "severity"))
    for f in findings:
        fam.labels(rule=f.rule, severity=f.severity).inc()


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analysis",
        description="dl4j-tpu-lint: trace-safety, lock-discipline and "
                    "graph-IR static analysis")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: the package)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help="ANALYSIS_BASELINE.json to filter through")
    ap.add_argument("--rules", default="jit,conc",
                    help="comma list of AST passes (jit,conc)")
    ap.add_argument("--graph", action="append", default=[],
                    help="serialized SameDiff zip to graph-lint "
                         "(repeatable)")
    ap.add_argument("--min-severity", choices=SEVERITIES, default="info")
    ap.add_argument("--telemetry", action="store_true",
                    help="count findings into the metrics registry")
    args = ap.parse_args(argv)

    rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    bad = [r for r in rules if r not in _AST_PASSES]
    if bad:
        ap.error(f"unknown rules {bad}; choose from "
                 f"{sorted(_AST_PASSES)}")
    paths = args.paths or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]

    # anchor reported paths (= baseline keys) to the baseline file's
    # directory, so the documented invocation works from any cwd; bare
    # runs relativize against cwd as before
    root = (os.path.dirname(os.path.abspath(args.baseline))
            if args.baseline else None)
    t0 = time.perf_counter()
    findings = lint_paths(paths, rules=rules, root=root)
    for g in args.graph:
        findings.extend(lint_graph_file(g))
    cut = _SEV_RANK[args.min_severity]
    findings = [f for f in findings if _SEV_RANK[f.severity] <= cut]
    findings = sort_findings(findings)

    if args.baseline:
        baseline = Baseline.load(args.baseline)
        new, baselined, stale = baseline.diff(findings)
    else:
        new, baselined, stale = findings, [], []

    if args.telemetry:
        emit_telemetry(findings)

    elapsed = time.perf_counter() - t0
    if args.format == "json":
        print(json.dumps({
            "ok": not new,
            "elapsed_s": round(elapsed, 3),
            "counts": _counts(findings),
            "new": [f.to_dict() for f in new],
            "baselined": len(baselined),
            "stale_baseline_keys": stale,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        if baselined:
            print(f"-- {len(baselined)} finding(s) covered by baseline")
        if stale:
            print(f"-- {len(stale)} stale baseline key(s) "
                  f"(fixed debt; prune with lint_gate --update-baseline)")
        c = _counts(findings)
        print(f"== {len(findings)} finding(s) "
              f"({c.get('error', 0)} error, {c.get('warning', 0)} "
              f"warning, {c.get('info', 0)} info), {len(new)} new, "
              f"in {elapsed:.2f}s")
    return 1 if new else 0


def _counts(findings: Sequence[Finding]):
    out = {}
    for f in findings:
        out[f.severity] = out.get(f.severity, 0) + 1
    return out


if __name__ == "__main__":
    sys.exit(main())
