"""CLI for the static-analysis passes.

::

    python -m deeplearning4j_tpu.analysis [paths...]
        --format=text|json        report format (default text)
        --baseline=FILE           filter findings through a baseline
        --rules=jit,conc          subset of AST passes (default both)
        --graph=FILE.sdz          also lint a serialized SameDiff zip
        --min-severity=warning    drop findings below this severity
        --telemetry               count findings into the process
                                  metrics registry
                                  (lint_findings_total{rule=,severity=},
                                  lint_modules_indexed_total,
                                  lint_runtime_seconds)
        --no-cross                per-module rules only (PR 4 mode)
        --cache=FILE / --no-cache per-file-mtime index cache (default
                                  .dl4j_lint_cache.json beside the
                                  baseline / under the linted package)
        --seed-dir=DIR            extra aux directory (scripts/) whose
                                  entry points seed the lock-order
                                  pass's thread-reachability

Default mode is WHOLE-PACKAGE: directory paths (and the no-path
default, the installed package) are linted through the cross-module
package index — per-module rules plus JIT106/CONC205/CONC206 and the
lock-order deadlock rules CONC301/302/303 over the
package-wide call graph, with summaries and per-file findings cached
by (mtime, size) so warm runs re-parse only what changed.  Explicit
FILE paths fall back to per-module-only linting (a single file has no
package to resolve against).

Exit code: 1 when any finding is NOT covered by the baseline (all
findings are "new" when no baseline is given), else 0.  The CI wrapper
with diff-style reporting, ``--update-baseline``, ``--changed-only``
and ``--audit-baseline`` lives in ``scripts/lint_gate.py``.
"""
from __future__ import annotations

import argparse
import ast
import json
import os
import sys
import time
from typing import Iterable, List, Optional, Sequence

from deeplearning4j_tpu.analysis import concurrency_lint, jit_lint
from deeplearning4j_tpu.analysis.findings import (SEVERITIES, Baseline,
                                                  Finding, sort_findings)

_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}
_AST_PASSES = {"jit": jit_lint, "conc": concurrency_lint}


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def lint_paths(paths: Sequence[str], rules: Sequence[str] = ("jit", "conc"),
               root: Optional[str] = None) -> List[Finding]:
    """Run the AST passes over every .py file under ``paths``.
    ``root`` relativizes reported paths (default: cwd)."""
    root = os.path.abspath(root or os.getcwd())
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(path), root)
        try:
            with open(path, "rb") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except SyntaxError as e:
            findings.append(Finding(
                rule="PARSE000", severity="error", path=rel,
                line=e.lineno or 0, symbol="<module>",
                message=f"file does not parse: {e.msg}"))
            continue
        for r in rules:
            findings.extend(
                Finding(**{**f.to_dict(), "path": rel})
                for f in _AST_PASSES[r].lint_tree(tree, rel))
    return findings


def lint_package(pkg_dir: str, root: Optional[str] = None,
                 cache_path: Optional[str] = None,
                 rules: Sequence[str] = ("jit", "conc"),
                 cross: bool = True,
                 seed_dirs: Sequence[str] = ()):
    """Whole-package mode: per-module findings (cached per file) plus
    the cross-module JIT106/CONC205/CONC206 and CONC301/302/303
    passes over the package index.  Returns ``(findings, stats)``.

    ``seed_dirs`` (e.g. ``scripts/``) are indexed WITHOUT local passes
    and merged as aux modules: their entry points seed the lock-order
    pass's thread-reachability, but no findings are reported in them."""
    from deeplearning4j_tpu.analysis import lock_order, package_index
    index, findings, stats = package_index.build_index(
        pkg_dir, root=root, cache_path=cache_path)
    if "jit" not in rules:
        findings = [f for f in findings if not f.rule.startswith("JIT")]
    if "conc" not in rules:
        findings = [f for f in findings if not f.rule.startswith("CONC")]
    if cross:
        if "jit" in rules:
            findings = findings + jit_lint.lint_package(index)
        if "conc" in rules:
            findings = findings + concurrency_lint.lint_package(index)
            cross_index = index
            if seed_dirs:
                merged = dict(index.modules)
                aux = set()
                for d in seed_dirs:
                    aux_idx, _, aux_st = package_index.build_index(
                        d, root=root, cache_path=cache_path,
                        run_local_passes=False)
                    _merge_stats(stats, aux_st)
                    for m, s in aux_idx.modules.items():
                        if m not in merged:
                            merged[m] = s
                            aux.add(m)
                cross_index = package_index.PackageIndex(merged,
                                                         aux=aux)
            findings = findings + lock_order.lint_package(cross_index)
    return findings, stats


def default_cache_path(anchor_dir: str) -> str:
    return os.path.join(anchor_dir, ".dl4j_lint_cache.json")


def _merge_stats(total, st):
    """Accumulate IndexStats across several linted directories so the
    report/telemetry reflect the whole run, not the last path."""
    if total is None:
        return st
    total.modules += st.modules
    total.parsed += st.parsed
    total.cache_hits += st.cache_hits
    total.elapsed_s += st.elapsed_s
    return total


def lint_graph_file(path: str) -> List[Finding]:
    from deeplearning4j_tpu.analysis import graph_lint
    from deeplearning4j_tpu.autodiff.samediff import SameDiff
    sd = SameDiff.load(path)
    return graph_lint.lint_samediff(sd, name=os.path.basename(path))


def emit_telemetry(findings: Sequence[Finding]) -> None:
    """Count findings into the process registry so report tooling
    (check_telemetry / chaos_smoke) covers the analysis subsystem."""
    from deeplearning4j_tpu import telemetry
    fam = telemetry.counter(
        "lint_findings_total",
        "static-analysis findings emitted, by rule and severity",
        labelnames=("rule", "severity"))
    for f in findings:
        fam.labels(rule=f.rule, severity=f.severity).inc()


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analysis",
        description="dl4j-tpu-lint: trace-safety, lock-discipline and "
                    "graph-IR static analysis")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: the package)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help="ANALYSIS_BASELINE.json to filter through")
    ap.add_argument("--rules", default="jit,conc",
                    help="comma list of AST passes (jit,conc)")
    ap.add_argument("--graph", action="append", default=[],
                    help="serialized SameDiff zip to graph-lint "
                         "(repeatable)")
    ap.add_argument("--min-severity", choices=SEVERITIES, default="info")
    ap.add_argument("--telemetry", action="store_true",
                    help="count findings into the metrics registry")
    ap.add_argument("--no-cross", action="store_true",
                    help="per-module rules only (skip the package "
                         "index and JIT106/CONC205/CONC206)")
    ap.add_argument("--cache", default=None,
                    help="index cache file (default: "
                         ".dl4j_lint_cache.json beside the baseline, "
                         "or under the linted directory)")
    ap.add_argument("--no-cache", action="store_true")
    ap.add_argument("--seed-dir", action="append", default=[],
                    help="extra directory (e.g. scripts/) indexed "
                         "only to seed thread/entry-point "
                         "reachability for the lock-order pass "
                         "(repeatable; no findings reported in it)")
    args = ap.parse_args(argv)

    rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    bad = [r for r in rules if r not in _AST_PASSES]
    if bad:
        ap.error(f"unknown rules {bad}; choose from "
                 f"{sorted(_AST_PASSES)}")
    paths = args.paths or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]

    # anchor reported paths (= baseline keys) to the baseline file's
    # directory, so the documented invocation works from any cwd; bare
    # runs relativize against cwd as before
    root = (os.path.dirname(os.path.abspath(args.baseline))
            if args.baseline else None)
    t0 = time.perf_counter()
    stats = None
    findings = []
    for p in paths:
        if os.path.isdir(p):
            # whole-package mode: cross-module index per directory
            # (file arguments fall back per-file, per path — a stray
            # file in the list must not demote the directories)
            # default cache: beside the baseline when one anchors the
            # run, else INSIDE the linted directory (never a parent
            # the user didn't name)
            cache = None
            if not args.no_cache:
                cache = args.cache or default_cache_path(
                    root or os.path.abspath(p))
            fs, st = lint_package(p, root=root, cache_path=cache,
                                  rules=rules,
                                  cross=not args.no_cross,
                                  seed_dirs=args.seed_dir)
            findings.extend(fs)
            stats = _merge_stats(stats, st)
        else:
            findings.extend(lint_paths([p], rules=rules, root=root))
    for g in args.graph:
        findings.extend(lint_graph_file(g))
    cut = _SEV_RANK[args.min_severity]
    findings = [f for f in findings if _SEV_RANK[f.severity] <= cut]
    findings = sort_findings(findings)

    if args.baseline:
        baseline = Baseline.load(args.baseline)
        new, baselined, stale = baseline.diff(findings)
    else:
        new, baselined, stale = findings, [], []

    elapsed = time.perf_counter() - t0
    if args.telemetry:
        emit_telemetry(findings)
        if stats is not None:
            from deeplearning4j_tpu.analysis.package_index import (
                emit_index_telemetry)
            stats.elapsed_s = elapsed
            emit_index_telemetry(stats)

    if args.format == "json":
        print(json.dumps({
            "ok": not new,
            "elapsed_s": round(elapsed, 3),
            "modules_indexed": stats.modules if stats else None,
            "index_cache_hits": stats.cache_hits if stats else None,
            "counts": _counts(findings),
            "new": [f.to_dict() for f in new],
            "baselined": len(baselined),
            "stale_baseline_keys": stale,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        if baselined:
            print(f"-- {len(baselined)} finding(s) covered by baseline")
        if stale:
            print(f"-- {len(stale)} stale baseline key(s) "
                  f"(fixed debt; prune with lint_gate --update-baseline)")
        c = _counts(findings)
        idx = (f", {stats.modules} modules indexed "
               f"({stats.cache_hits} cached)" if stats else "")
        print(f"== {len(findings)} finding(s) "
              f"({c.get('error', 0)} error, {c.get('warning', 0)} "
              f"warning, {c.get('info', 0)} info), {len(new)} new, "
              f"in {elapsed:.2f}s{idx}")
    return 1 if new else 0


def _counts(findings: Sequence[Finding]):
    out = {}
    for f in findings:
        out[f.severity] = out.get(f.severity, 0) + 1
    return out


if __name__ == "__main__":
    sys.exit(main())
