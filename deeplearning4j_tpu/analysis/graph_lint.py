"""``graph_lint`` — static validation of graph IR before build.

Validates :class:`~deeplearning4j_tpu.autodiff.samediff.SameDiff`
graphs (and ``ComputationGraphConfiguration`` vertex graphs) WITHOUT
executing them on device: structural checks are pure host walks, and
shape/dtype inference goes through ``jax.eval_shape`` — abstract
evaluation only, no device memory is allocated for activations.

This is the pass that would have caught the
``fold_flatten_reshapes`` axis bug class at rewrite time instead of at
numerics-parity time: a rewrite that orphans a vertex, breaks an op's
arity, or changes an inferred output shape/dtype shows up here
immediately (see ``rewrites.optimize_for_tpu``'s
``DL4J_TPU_REWRITE_CHECK=1`` mode, which wraps every pass in a
shape-signature parity assertion built on :func:`infer_shapes`).

Rules
-----
GRAPH301 (error)   dangling input: an op consumes a name that no
                   variable declares and no op produces.
GRAPH302 (warning) dead vertex: none of an op's outputs are consumed,
                   designated outputs, or loss variables — dead compute
                   that a rewrite or importer forgot to prune.
GRAPH303 (error)   fan-in arity mismatch: an op's input count cannot
                   satisfy its registered lowering's signature.
GRAPH304 (warning) float64 leak: a CONSTANT/VARIABLE carries float64
                   values — under jax's default x64-disabled config it
                   silently downcasts; with x64 enabled it promotes
                   every downstream op to f64 (2x HBM, no MXU).
GRAPH305 (error)   shape inference failed: abstract evaluation of the
                   graph raised — the graph cannot trace.
GRAPH306 (warning) inferred f64 output: an output abstractly evaluates
                   to float64 from float32 inputs (silent promotion in
                   the op chain).
GRAPH307 (info)    skipped: dynamic control flow — a while_loop/cond
                   node's subgraphs execute via ``_exec_while`` /
                   ``_exec_cond``, outside the registry, so the arity
                   and inference rules cannot see inside them.  The
                   skip used to be SILENT (ROADMAP small note); now
                   every dynamic-control-flow node reports exactly
                   what was not checked, so scan/while-heavy graphs
                   (the speculative-decode era's shape) are never
                   invisibly half-linted.
"""
from __future__ import annotations

import inspect
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.analysis.findings import Finding

#: FALLBACK dimension for unknown (None/-1) placeholder dims: primary
#: inference now propagates SYMBOLIC dimension variables through
#: ``jax.eval_shape`` (axis 0 of every placeholder shares the batch
#: symbol ``b``; other unknown axes get fresh ``d<i>`` symbols), so an
#: unknown batch stays ``'b'`` in the inferred shape instead of being
#: baked to a number — a rewrite that silently ties an output to the
#: probe value can no longer masquerade as shape-correct.  The probe
#: is used only when symbolic inference fails (e.g. a lowering that
#: needs concrete sizes); 2 keeps broadcast bugs visible where 1
#: would hide them.
PROBE_DIM = 2


def _op_index(sd) -> int:
    return {id(n): i for i, n in enumerate(sd.ops)}


def _finding(rule, severity, graph_name, symbol, message, hint=""):
    return Finding(rule=rule, severity=severity,
                   path=f"<graph:{graph_name}>", line=0, symbol=symbol,
                   message=message, fix_hint=hint)


def _arity_bounds(fn) -> Tuple[int, Optional[int]]:
    """(min, max) positional-input arity of an op lowering; max None
    means unbounded (*args)."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return 0, None
    lo = hi = 0
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            hi += 1
            if p.default is p.empty:
                lo += 1
        elif p.kind == p.VAR_POSITIONAL:
            return lo, None
    return lo, hi


def lint_samediff(sd, name: str = "samediff",
                  infer: bool = True) -> List[Finding]:
    """Run every structural + inference rule on one SameDiff graph."""
    from deeplearning4j_tpu.autodiff.ops import OP_REGISTRY

    findings: List[Finding] = []
    produced = {o for n in sd.ops for o in n.outputs}
    consumed: Dict[str, int] = {}
    for n in sd.ops:
        for i in n.inputs:
            consumed[i] = consumed.get(i, 0) + 1
    protected = set(sd.outputs or ()) | set(sd.loss_variables)

    for idx, node in enumerate(sd.ops):
        sym = f"{node.op_name}#{idx}"
        # GRAPH301: dangling inputs
        for inp in node.inputs:
            if inp not in sd.vars and inp not in produced:
                findings.append(_finding(
                    "GRAPH301", "error", name, sym,
                    f"op '{node.op_name}' consumes undeclared name "
                    f"'{inp}'",
                    "declare the variable or fix the rewrite that "
                    "renamed it"))
        # GRAPH302: dead vertices
        if not any(o in consumed or o in protected
                   for o in node.outputs):
            findings.append(_finding(
                "GRAPH302", "warning", name, sym,
                f"dead vertex: no output of '{node.op_name}' "
                f"(outputs {node.outputs}) is consumed, designated, "
                "or a loss variable",
                "prune it (rewrites should drop orphaned nodes) or "
                "designate the output"))
        # GRAPH307: dynamic control flow — announce the blind spot
        # instead of skipping silently.  The body subgraphs run
        # through _exec_while/_exec_cond rather than the registry
        # lowering, so GRAPH303's arity probe and the eval_shape
        # inference below never enter them; a per-node diagnostic
        # keeps that limitation visible in the report.
        if node.op_name in ("while_loop", "cond"):
            inner = sorted(k for k in ("cond", "body", "then",
                                       "orelse")
                           if k in (node.attrs or {}))
            findings.append(_finding(
                "GRAPH307", "info", name, sym,
                f"skipped: dynamic control flow — '{node.op_name}' "
                f"subgraph(s) {inner} execute outside the registry "
                "and were not arity-checked or shape-inferred",
                "lint the subgraphs directly (lint_samediff on "
                "node.attrs['body'] etc.) when they carry "
                "nontrivial structure"))
        # GRAPH303: arity vs the registered lowering
        opdef = OP_REGISTRY.get(node.op_name)
        if opdef is not None and node.op_name not in ("while_loop",
                                                      "cond"):
            lo, hi = _arity_bounds(opdef.fn)
            n_in = len(node.inputs)
            if n_in < lo or (hi is not None and n_in > hi):
                bound = f">= {lo}" if hi is None else \
                    (f"exactly {lo}" if lo == hi else f"{lo}..{hi}")
                findings.append(_finding(
                    "GRAPH303", "error", name, sym,
                    f"op '{node.op_name}' has {n_in} inputs but its "
                    f"lowering takes {bound}",
                    "fix the node's input list"))

    # GRAPH304: stored f64 leaves
    for vname, val in sd.values.items():
        if np.asarray(val).dtype == np.float64:
            var = sd.vars.get(vname)
            kind = var.var_type if var is not None else "value"
            findings.append(_finding(
                "GRAPH304", "warning", name, f"{kind}:{vname}",
                f"{kind.lower()} '{vname}' is stored as float64 "
                "(x64-off jax silently downcasts it; x64-on promotes "
                "the whole downstream graph)",
                "store as float32 (np.float32 scalar or "
                ".astype) at creation"))

    if infer and not findings_has_errors(findings):
        findings.extend(_infer_findings(sd, name))
    return findings


def findings_has_errors(findings: Sequence[Finding]) -> bool:
    return any(f.severity == "error" for f in findings)


def infer_shapes(sd, outputs: Optional[Sequence[str]] = None,
                 probe_dim: int = PROBE_DIM,
                 symbolic: bool = True) -> Dict[str, Tuple]:
    """Abstract shape/dtype inference over a SameDiff graph via
    ``jax.eval_shape`` — no device buffers are created for
    placeholders or activations.

    Unknown placeholder dims (None/-1) become SYMBOLIC dimension
    variables (``jax.export.symbolic_shape``): axis 0 of every
    placeholder shares the batch symbol ``b`` (two placeholders with
    unknown batch agree, matching how the graphs are fed), other
    unknown axes get fresh ``d<i>`` symbols.  Symbolic output dims are
    reported as their expression STRING (``'b'``, ``'2*b'``), which is
    stable across calls — rewrite-parity comparisons work on graphs
    with open batch dims.  When symbolic inference fails (a lowering
    needing concrete sizes, or a jax without shape polymorphism) the
    unknown dims fall back to ``probe_dim``.

    Returns ``{output_name: (shape, dtype_str)}``.  Raises whatever
    the (fallback) trace raises — callers turn that into GRAPH305."""
    outs = list(outputs) if outputs is not None else _terminal_outputs(sd)
    if not outs:
        return {}
    ph = [v for v in sd.vars.values() if v.var_type == "PLACEHOLDER"]
    has_unknown = any(
        d is None or int(d) < 0 for v in ph for d in (v.shape or ()))
    if symbolic and has_unknown:
        try:
            return _eval_shapes(sd, outs, _symbolic_feeds(ph))
        except Exception:
            pass   # fall back to the probe below
    feeds = {}
    import jax
    for v in ph:
        shape = tuple((probe_dim if (d is None or int(d) < 0) else int(d))
                      for d in (v.shape or ()))
        feeds[v.name] = jax.ShapeDtypeStruct(shape, np.dtype(v.dtype))
    return _eval_shapes(sd, outs, feeds)


def _symbolic_feeds(placeholders) -> Dict:
    """ShapeDtypeStructs with symbolic dim variables for the unknown
    dims — ONE shared scope so the batch symbol is the same variable
    everywhere it appears."""
    import jax
    from jax import export

    names: List[str] = []
    templates = []              # (var, [int | name])
    fresh = 0
    for v in placeholders:
        dims = []
        for axis, d in enumerate(v.shape or ()):
            if d is None or int(d) < 0:
                if axis == 0:
                    name = "b"
                else:
                    name = f"d{fresh}"
                    fresh += 1
                if name not in names:
                    names.append(name)
                dims.append(name)
            else:
                dims.append(int(d))
        templates.append((v, dims))
    syms = dict(zip(names, export.symbolic_shape(",".join(names)))) \
        if names else {}
    return {v.name: jax.ShapeDtypeStruct(
                tuple(syms[d] if isinstance(d, str) else d
                      for d in dims), np.dtype(v.dtype))
            for v, dims in templates}


def _eval_shapes(sd, outs, feeds) -> Dict[str, Tuple]:
    import jax

    needed = sd._needed_for(outs)

    def run(feed_vals):
        env = sd._run_graph(sd._param_values(), feed_vals, needed)
        return [env[o] for o in outs]

    res = jax.eval_shape(run, feeds)

    def dim(d):
        try:
            return int(d)
        except Exception:        # symbolic _DimExpr: report its name
            return str(d)

    return {o: (tuple(dim(d) for d in r.shape),
                str(np.dtype(r.dtype)))
            for o, r in zip(outs, res)}


def _terminal_outputs(sd) -> List[str]:
    if sd.outputs:
        return list(sd.outputs)
    consumed = {i for n in sd.ops for i in n.inputs}
    outs = [o for n in sd.ops for o in n.outputs if o not in consumed]
    return outs + [l for l in sd.loss_variables if l not in outs]


def _infer_findings(sd, name: str) -> List[Finding]:
    findings: List[Finding] = []
    try:
        shapes = infer_shapes(sd)
    except Exception as e:  # noqa: BLE001 — any trace failure is the finding
        findings.append(_finding(
            "GRAPH305", "error", name, "<trace>",
            f"shape inference failed: {type(e).__name__}: {e}",
            "the graph cannot trace — fix the structure before build"))
        return findings
    f32_world = not any(
        np.asarray(v).dtype == np.float64 for v in sd.values.values())
    for out, (shape, dtype) in sorted(shapes.items()):
        if dtype == "float64" and f32_world:
            findings.append(_finding(
                "GRAPH306", "warning", name, f"output:{out}",
                f"output '{out}' infers as float64 {shape} from "
                "float32 inputs — an op in the chain silently "
                "promotes",
                "find the promoting op (Python float scalars in "
                "attrs are the usual culprit) and cast"))
    return findings


# ---------------------------------------------------------------------------
# ComputationGraph configuration checks
# ---------------------------------------------------------------------------

def lint_computation_graph(conf, name: str = "graph") -> List[Finding]:
    """Structural checks on a built ``ComputationGraphConfiguration``:
    the builder already rejects unknown inputs and arity at build
    time, but graphs can also arrive via ``from_dict``/``from_json``
    (import paths) where nothing re-validates."""
    findings: List[Finding] = []
    known = set(conf.network_inputs) | set(conf.vertex_inputs)
    for vname, ins in conf.vertex_inputs.items():
        for i in ins:
            if i not in known:
                findings.append(_finding(
                    "GRAPH301", "error", name, vname,
                    f"vertex '{vname}' consumes unknown input '{i}'",
                    "fix the vertex wiring"))
    # GRAPH302: vertices no network output depends on
    needed = set(conf.network_outputs)
    frontier = list(needed)
    while frontier:
        v = frontier.pop()
        for i in conf.vertex_inputs.get(v, ()):
            if i not in needed:
                needed.add(i)
                frontier.append(i)
    for vname in conf.vertex_inputs:
        if vname not in needed:
            findings.append(_finding(
                "GRAPH302", "warning", name, vname,
                f"dead vertex: '{vname}' feeds no network output",
                "remove it or add it to set_outputs"))
    return findings
