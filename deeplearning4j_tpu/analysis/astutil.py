"""Small shared AST helpers for the analysis passes.

Nothing here is jax- or threading-specific: dotted-name flattening,
parent links, scope-aware function indexing, and attribute-access
iteration.  Both AST passes (:mod:`jit_lint`, :mod:`concurrency_lint`)
work on plain ``ast`` trees — no imports of the linted code ever
happen, so linting a file can never execute it.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)
ScopeDef = FuncDef + (ast.ClassDef, ast.Module)


def dotted(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """Flatten ``a.b.c`` / ``a`` into ``("a","b","c")`` / ``("a",)``;
    None for anything that is not a pure Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return tuple(reversed(parts))


def dotted_str(node: ast.AST) -> Optional[str]:
    d = dotted(node)
    return ".".join(d) if d else None


def add_parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    """Child -> parent links for the whole tree."""
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


class FuncIndex:
    """Every function/method in a module, with qualified names and
    scope chains, so a reference like ``jax.jit(tick)`` or a call
    ``self._step(...)`` can be resolved to its def without importing
    anything."""

    def __init__(self, tree: ast.Module, parents: Dict[ast.AST, ast.AST]):
        self.tree = tree
        self.parents = parents
        self.defs: List[ast.AST] = [
            n for n in ast.walk(tree) if isinstance(n, FuncDef)]
        self.qualname: Dict[ast.AST, str] = {}
        self.owner_class: Dict[ast.AST, Optional[ast.ClassDef]] = {}
        # scope node -> directly nested function defs
        self.scope_children: Dict[ast.AST, Dict[str, ast.AST]] = {}
        for fn in self.defs:
            chain = self._scope_chain(fn)
            names = [getattr(s, "name", "") for s in chain
                     if not isinstance(s, ast.Module)]
            self.qualname[fn] = ".".join(names + [fn.name])
            self.owner_class[fn] = next(
                (s for s in reversed(chain)
                 if isinstance(s, ast.ClassDef)), None)
            scope = chain[-1] if chain else tree
            self.scope_children.setdefault(scope, {})[fn.name] = fn
        # method name -> defs (for attr-call resolution within module)
        self.by_method_name: Dict[str, List[ast.AST]] = {}
        for fn in self.defs:
            self.by_method_name.setdefault(fn.name, []).append(fn)

    def _scope_chain(self, node: ast.AST) -> List[ast.AST]:
        """Enclosing scopes of ``node``, outermost first, excluding
        ``node`` itself."""
        chain: List[ast.AST] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ScopeDef):
                chain.append(cur)
            cur = self.parents.get(cur)
        return list(reversed(chain))

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, FuncDef):
                return cur
            cur = self.parents.get(cur)
        return None

    def resolve_name(self, name: str, at: ast.AST) -> Optional[ast.AST]:
        """Resolve a bare ``name`` reference at node ``at`` to a
        function def, searching the enclosing scopes innermost-out,
        then the module."""
        fn = self.enclosing_function(at)
        scopes: List[ast.AST] = []
        cur: Optional[ast.AST] = fn
        while cur is not None:
            scopes.append(cur)
            cur = self.parents.get(cur)
        scopes.append(self.tree)
        for scope in scopes:
            hit = self.scope_children.get(scope, {}).get(name)
            if hit is not None:
                return hit
        return None

    def resolve_attr_method(self, attr: str, at: ast.AST
                            ) -> List[ast.AST]:
        """Resolve ``something.attr(...)`` to candidate method defs:
        prefer methods of the class enclosing ``at``; fall back to any
        same-named method in the module (cross-class, heuristic)."""
        fn = self.enclosing_function(at)
        cls = self.owner_class.get(fn) if fn is not None else None
        cands = self.by_method_name.get(attr, [])
        if cls is not None:
            own = [c for c in cands if self.owner_class.get(c) is cls]
            if own:
                return own
        return cands


def attr_accesses(node: ast.AST, base: str = "self"
                  ) -> Iterator[Tuple[ast.Attribute, str, str]]:
    """Yield ``(attr_node, attr_name, kind)`` for every ``base.X``
    access under ``node``.  ``kind``: "store" for assignment targets
    (plain, augmented, subscript/attribute element stores, deletes),
    else "load".  ``base.X[i] = v`` and ``base.X.append`` count as a
    store and a load respectively — mutation through a method call is
    invisible to syntax."""
    for n in ast.walk(node):
        if not isinstance(n, ast.Attribute):
            continue
        if not (isinstance(n.value, ast.Name) and n.value.id == base):
            continue
        if isinstance(n.ctx, (ast.Store, ast.Del)):
            yield n, n.attr, "store"
        else:
            yield n, n.attr, "load"


def subscript_store_bases(node: ast.AST, base: str = "self"
                          ) -> Iterator[Tuple[ast.Attribute, str]]:
    """Yield ``(attr_node, name)`` for ``base.X[...] = v`` /
    ``del base.X[...]`` / ``base.X[...] += v`` element stores — the
    attribute itself is a Load syntactically, but the ACCESS mutates
    the named container."""
    for n in ast.walk(node):
        targets: List[ast.AST] = []
        if isinstance(n, ast.Assign):
            targets = list(n.targets)
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            targets = [n.target]
        elif isinstance(n, ast.Delete):
            targets = list(n.targets)
        for t in targets:
            for tt in ast.walk(t):
                if isinstance(tt, ast.Subscript) and \
                        isinstance(tt.value, ast.Attribute) and \
                        isinstance(tt.value.value, ast.Name) and \
                        tt.value.value.id == base:
                    yield tt.value, tt.value.attr


def call_name(call: ast.Call) -> Optional[Tuple[str, ...]]:
    return dotted(call.func)
