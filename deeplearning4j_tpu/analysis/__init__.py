"""Static analysis + runtime sanitizers for the dl4j-tpu stack.

Three passes over a shared findings model (see ISSUE/README "Static
analysis & sanitizers"):

* :mod:`~deeplearning4j_tpu.analysis.jit_lint` — trace-safety (host
  impurity inside jit-traced functions);
* :mod:`~deeplearning4j_tpu.analysis.concurrency_lint` — lock
  discipline (guarded attributes accessed outside their lock on
  thread-reachable paths);
* :mod:`~deeplearning4j_tpu.analysis.graph_lint` — graph-IR validation
  (dead vertices, arity, ``jax.eval_shape`` inference, f64 leaks).

CLI: ``python -m deeplearning4j_tpu.analysis`` (see
:mod:`~deeplearning4j_tpu.analysis.cli`); CI gate:
``scripts/lint_gate.py`` against ``ANALYSIS_BASELINE.json``.

Runtime companion: :mod:`~deeplearning4j_tpu.analysis.sanitize`
(``DL4J_TPU_SANITIZE=nan,donation``) dynamically confirms the two
statically-flagged bug classes in the fit loop and the decode tick.
"""
from deeplearning4j_tpu.analysis.findings import (Baseline, Finding,
                                                  SEVERITIES,
                                                  sort_findings)
from deeplearning4j_tpu.analysis import sanitize
from deeplearning4j_tpu.analysis.sanitize import SanitizerError

__all__ = ["Baseline", "Finding", "SEVERITIES", "sort_findings",
           "sanitize", "SanitizerError", "lint_paths", "lint_samediff",
           "lint_computation_graph"]


def lint_paths(*a, **kw):
    from deeplearning4j_tpu.analysis.cli import lint_paths as impl
    return impl(*a, **kw)


def lint_samediff(*a, **kw):
    from deeplearning4j_tpu.analysis.graph_lint import (
        lint_samediff as impl)
    return impl(*a, **kw)


def lint_computation_graph(*a, **kw):
    from deeplearning4j_tpu.analysis.graph_lint import (
        lint_computation_graph as impl)
    return impl(*a, **kw)
