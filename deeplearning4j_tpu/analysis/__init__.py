"""Static analysis + runtime sanitizers for the dl4j-tpu stack.

Four passes over a shared findings model (see ISSUE/README "Static
analysis & sanitizers"):

* :mod:`~deeplearning4j_tpu.analysis.jit_lint` — trace-safety (host
  impurity inside jit-traced functions);
* :mod:`~deeplearning4j_tpu.analysis.concurrency_lint` — lock
  discipline (guarded attributes accessed outside their lock on
  thread-reachable paths);
* :mod:`~deeplearning4j_tpu.analysis.lock_order` — deadlock lint
  (whole-package lock-order graph: ABBA cycles, lock-held blocking
  calls, callback-table thread reachability);
* :mod:`~deeplearning4j_tpu.analysis.graph_lint` — graph-IR validation
  (dead vertices, arity, symbolic-dim ``jax.eval_shape`` inference,
  f64 leaks).

Whole-package mode (PR 8): :mod:`~deeplearning4j_tpu.analysis.package_index`
builds a cross-module symbol table + call graph (imports, inheritance,
lock provenance, ``Static``/``Traced``/class-typed annotations from
:mod:`~deeplearning4j_tpu.analysis.annotations`) with a per-file-mtime
on-disk cache; ``jit_lint.lint_package`` walks trace contexts through
cross-module callees (JIT106), ``concurrency_lint.lint_package``
checks module-level state and foreign lock-guarded attributes
(CONC205/CONC206), and ``lock_order.lint_package`` builds the
interprocedural lock-order graph (CONC301/302/303) with thread roots
seeded from ``Thread(target=...)`` spawns plus the entry calls of aux
seed directories (``scripts/``).

CLI: ``python -m deeplearning4j_tpu.analysis`` (see
:mod:`~deeplearning4j_tpu.analysis.cli`); CI gate:
``scripts/lint_gate.py`` against ``ANALYSIS_BASELINE.json``
(``--changed-only`` for pre-commit loops, ``--audit-baseline`` for
debt hygiene, ``--prune`` to retire fixed debt, ``--check`` to fail
CI while pruneable stale keys remain).

Runtime companion: :mod:`~deeplearning4j_tpu.analysis.sanitize`
(``DL4J_TPU_SANITIZE=nan,donation``) dynamically confirms the two
statically-flagged bug classes in the fit loop and the decode tick.
"""
from deeplearning4j_tpu.analysis.findings import (Baseline, Finding,
                                                  SEVERITIES,
                                                  sort_findings)
from deeplearning4j_tpu.analysis import sanitize
from deeplearning4j_tpu.analysis.annotations import Static, Traced
from deeplearning4j_tpu.analysis.sanitize import SanitizerError

__all__ = ["Baseline", "Finding", "SEVERITIES", "Static", "Traced",
           "sort_findings", "sanitize", "SanitizerError", "lint_paths",
           "lint_package", "lint_samediff", "lint_computation_graph"]


def lint_paths(*a, **kw):
    from deeplearning4j_tpu.analysis.cli import lint_paths as impl
    return impl(*a, **kw)


def lint_package(*a, **kw):
    from deeplearning4j_tpu.analysis.cli import lint_package as impl
    return impl(*a, **kw)


def lint_samediff(*a, **kw):
    from deeplearning4j_tpu.analysis.graph_lint import (
        lint_samediff as impl)
    return impl(*a, **kw)


def lint_computation_graph(*a, **kw):
    from deeplearning4j_tpu.analysis.graph_lint import (
        lint_computation_graph as impl)
    return impl(*a, **kw)
