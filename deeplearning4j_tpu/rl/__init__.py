"""Reinforcement learning (RL4J: ``rl4j-core
org.deeplearning4j.rl4j.**``): MDP protocol, replay buffer, deep
Q-learning with a target network, epsilon-greedy policy.

TPU-first: the Q-network is a framework MultiLayerNetwork whose TD
update is the same single jitted train step as supervised fit — replay
sampling and environment stepping stay host-side (they're control flow,
not FLOPs).
"""
from deeplearning4j_tpu.rl.mdp import MDP, SimpleGridWorld
from deeplearning4j_tpu.rl.dqn import (DQNPolicy, QLearningConfiguration,
                                       QLearningDiscrete, ReplayBuffer)
from deeplearning4j_tpu.rl.a3c import (A3CConfiguration, A3CDiscrete,
                                       ACPolicy,
                                       AsyncNStepQConfiguration,
                                       AsyncNStepQLearningDiscrete)

__all__ = ["MDP", "SimpleGridWorld", "QLearningDiscrete",
           "QLearningConfiguration", "ReplayBuffer", "DQNPolicy",
           "A3CDiscrete", "A3CConfiguration", "ACPolicy",
           "AsyncNStepQLearningDiscrete", "AsyncNStepQConfiguration"]
