"""Deep Q-learning (``org.deeplearning4j.rl4j.learning.sync.qlearning
.discrete.QLearningDiscreteDense`` + ``QLearning.QLConfiguration``,
``ExpReplay``, ``DQNPolicy``/``EpsGreedy``)."""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.rl.mdp import MDP


@dataclasses.dataclass
class QLearningConfiguration:
    """``QLearning.QLConfiguration`` surface (subset)."""

    seed: int = 123
    max_epoch_step: int = 200
    max_step: int = 5000
    exp_replay_size: int = 10000
    batch_size: int = 64
    target_dqn_update_freq: int = 100
    update_start: int = 100
    gamma: float = 0.99
    eps_start: float = 1.0
    eps_end: float = 0.05
    eps_decay_steps: int = 2000
    learning_rate: float = 1e-3


class ReplayBuffer:
    """``ExpReplay``: fixed-size ring of (s, a, r, s', done)."""

    def __init__(self, capacity: int, obs_size: int, seed: int = 0):
        self.capacity = int(capacity)
        self._s = np.zeros((capacity, obs_size), np.float32)
        self._a = np.zeros(capacity, np.int32)
        self._r = np.zeros(capacity, np.float32)
        self._s2 = np.zeros((capacity, obs_size), np.float32)
        self._d = np.zeros(capacity, np.float32)
        self._n = 0
        self._i = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self):
        return self._n

    def add(self, s, a, r, s2, done):
        i = self._i
        self._s[i], self._a[i], self._r[i] = s, a, r
        self._s2[i], self._d[i] = s2, float(done)
        self._i = (i + 1) % self.capacity
        self._n = min(self._n + 1, self.capacity)

    def sample(self, batch_size: int):
        idx = self._rng.integers(0, self._n, batch_size)
        return (self._s[idx], self._a[idx], self._r[idx], self._s2[idx],
                self._d[idx])


class DQNPolicy:
    """Greedy policy over a trained Q-network (``DQNPolicy``)."""

    def __init__(self, q_net):
        self.q_net = q_net

    def next_action(self, obs: np.ndarray) -> int:
        q = np.asarray(self.q_net.output(obs[None]))
        return int(q[0].argmax())

    def play(self, mdp: MDP, max_steps: int = 1000) -> float:
        obs = mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            obs, r, done = mdp.step(self.next_action(obs))
            total += r
            if done:
                break
        return total


class QLearningDiscrete:
    """Synchronous DQN: epsilon-greedy exploration, replay buffer,
    target-network bootstrapping, Q-regression through the framework's
    jitted train step (mse head)."""

    def __init__(self, mdp: MDP, conf: Optional[QLearningConfiguration]
                 = None, hidden: int = 64):
        from deeplearning4j_tpu import (MultiLayerNetwork,
                                        NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.conf.layers_core import (DenseLayer,
                                                            OutputLayer)
        from deeplearning4j_tpu.optimize.updaters import Adam

        self.mdp = mdp
        self.conf = conf or QLearningConfiguration()
        c = self.conf

        def build():
            cfg = (NeuralNetConfiguration.builder().seed(c.seed)
                   .updater(Adam(learning_rate=c.learning_rate)).list()
                   .layer(DenseLayer(n_in=mdp.obs_size, n_out=hidden,
                                     activation="relu"))
                   .layer(DenseLayer(n_out=hidden, activation="relu"))
                   .layer(OutputLayer(n_out=mdp.n_actions,
                                      activation="identity", loss="mse"))
                   .build())
            return MultiLayerNetwork(cfg).init()

        self.q_net = build()
        self.target_net = build()
        self._sync_target()
        self.replay = ReplayBuffer(c.exp_replay_size, mdp.obs_size, c.seed)
        self._rng = np.random.default_rng(c.seed)
        self.step_count = 0
        self.episode_rewards: List[float] = []

    def _sync_target(self):
        import jax
        import jax.numpy as jnp
        # DEEP copy: q_net.fit donates its param buffers every step, so
        # aliased arrays in the target net would be invalidated.
        self.target_net.params_tree = jax.tree_util.tree_map(
            lambda a: jnp.array(a, copy=True), self.q_net.params_tree)

    def _epsilon(self) -> float:
        c = self.conf
        frac = min(1.0, self.step_count / max(1, c.eps_decay_steps))
        return c.eps_start + frac * (c.eps_end - c.eps_start)

    def _act(self, obs) -> int:
        if self._rng.random() < self._epsilon():
            return int(self._rng.integers(0, self.mdp.n_actions))
        q = np.asarray(self.q_net.output(obs[None]))
        return int(q[0].argmax())

    def _learn_batch(self):
        c = self.conf
        s, a, r, s2, d = self.replay.sample(c.batch_size)
        q_next = np.asarray(self.target_net.output(s2))
        target_value = r + c.gamma * (1.0 - d) * q_next.max(-1)
        # regression target: current Q with the taken action replaced
        target = np.asarray(self.q_net.output(s)).copy()
        target[np.arange(len(a)), a] = target_value
        self.q_net.fit(DataSet(s, target.astype(np.float32)))

    def train(self) -> List[float]:
        """Run until ``max_step`` env steps; returns per-episode
        rewards."""
        c = self.conf
        while self.step_count < c.max_step:
            obs = self.mdp.reset()
            ep_reward, done, ep_steps = 0.0, False, 0
            while not done and ep_steps < c.max_epoch_step:
                action = self._act(obs)
                obs2, r, done = self.mdp.step(action)
                self.replay.add(obs, action, r, obs2, done)
                obs = obs2
                ep_reward += r
                ep_steps += 1
                self.step_count += 1
                if (self.step_count >= c.update_start
                        and len(self.replay) >= c.batch_size):
                    self._learn_batch()
                if self.step_count % c.target_dqn_update_freq == 0:
                    self._sync_target()
                if self.step_count >= c.max_step:
                    break
            self.episode_rewards.append(ep_reward)
        return self.episode_rewards

    def get_policy(self) -> DQNPolicy:
        return DQNPolicy(self.q_net)
