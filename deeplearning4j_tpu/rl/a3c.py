"""Asynchronous advantage actor-critic (A3C) + async n-step Q-learning.

Parity surface: ``rl4j-core org.deeplearning4j.rl4j.learning.async.**``
(``A3CDiscrete``, ``AsyncNStepQLearningDiscrete``) [UNVERIFIED] — the
reference runs actor THREADS with local network copies applying
asynchronous gradients to a shared global network.

TPU-first translation: actors are host threads (environment stepping is
cheap numpy control flow; the GIL releases during jitted device calls),
each takes a parameter snapshot, collects a t_max rollout, computes
gradients with ONE jitted call, and applies them to the shared
parameters under a lock — the Hogwild-style async semantic with the
math on the accelerator.  Both learners share the
``_AsyncActorLearner`` scaffolding (rollout template, truncation
bootstrapping, locked updates, thread fan-out); they differ only in
action selection, the bootstrap value, and the gradient function.

THROUGHPUT CAVEAT (do not benchmark this): Python thread actors are
GIL-bound by construction — this module exists for SEMANTIC parity
with rl4j's async learners (gridworld-scale convergence), not speed.
The TPU path is the jitted LEARNER (batched rollout gradients on
device); scale actors via vectorized environments feeding that
learner, not via more threads here.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Callable, List, Optional

import numpy as np

from deeplearning4j_tpu.rl.mdp import MDP


def discounted_returns(rewards, bootstrap, dones, gamma):
    """Backward-accumulated n-step returns; a True in ``dones`` resets
    the accumulator (rollouts break at terminal steps, so at most the
    final entry is True)."""
    out = np.zeros(len(rewards), np.float32)
    acc = bootstrap
    for i in range(len(rewards) - 1, -1, -1):
        acc = rewards[i] + gamma * (0.0 if dones[i] else acc)
        out[i] = acc
    return out


class _AsyncActorLearner:
    """Shared async-learning scaffolding.  Subclasses set, in __init__:
    ``conf`` (n_threads/t_max/gamma/max_step/max_epoch_step/seed),
    ``mdp_factory``, ``_updater``, ``_opt_state``, and implement
    ``_get_params``/``_set_params``, ``_snapshot``, ``_select_action``,
    ``_bootstrap_value``, ``_rollout_grads``, and optionally
    ``_post_apply`` (e.g. target-network sync)."""

    def _init_shared(self):
        self._lock = threading.Lock()
        self._step_lock = threading.Lock()  # cheap, never held with _lock
        self.step_count = 0
        self.episode_rewards: List[float] = []

    # -- subclass surface ----------------------------------------------
    def _get_params(self):
        raise NotImplementedError

    def _set_params(self, params):
        raise NotImplementedError

    def _snapshot(self):
        with self._lock:
            return self._get_params()

    def _select_action(self, snap, obs, rng) -> int:
        raise NotImplementedError

    def _bootstrap_value(self, snap, obs) -> float:
        raise NotImplementedError

    def _rollout_grads(self, snap, obs_batch, actions, returns):
        raise NotImplementedError

    def _post_apply(self):
        pass

    # -- shared machinery ----------------------------------------------
    def _apply(self, grads):
        import jax
        with self._lock:
            params = self._get_params()
            updates, self._opt_state = self._updater.update(
                grads, self._opt_state, params, self.step_count)
            params = jax.tree_util.tree_map(
                lambda p, u: p - u, params, updates)
            self._opt_state = self._updater.finalize(self._opt_state,
                                                     params)
            self._set_params(params)
            self._post_apply()

    def _actor(self, tid: int):
        import jax.numpy as jnp
        c = self.conf
        mdp = self.mdp_factory()
        rng = np.random.default_rng(c.seed * 1009 + tid)
        obs = mdp.reset()
        ep_reward, ep_steps = 0.0, 0
        while self.step_count < c.max_step:
            snap = self._snapshot()
            os_, as_, rs_, ds_ = [], [], [], []
            boot_obs = obs     # state to bootstrap from if truncated
            for _ in range(c.t_max):
                a = self._select_action(snap, obs, rng)
                obs2, r, done = mdp.step(a)
                os_.append(obs)
                as_.append(a)
                rs_.append(r)
                ds_.append(done)
                obs = boot_obs = obs2
                ep_reward += r
                ep_steps += 1
                with self._step_lock:  # += is a lost-update race
                    self.step_count += 1
                if done or ep_steps >= c.max_epoch_step:
                    with self._lock:
                        self.episode_rewards.append(ep_reward)
                    # boot_obs keeps the PRE-reset state: an epoch-limit
                    # truncation still bootstraps from where the
                    # rollout actually stopped
                    obs, ep_reward, ep_steps = mdp.reset(), 0.0, 0
                    break
            bootstrap = 0.0 if ds_[-1] else \
                self._bootstrap_value(snap, boot_obs)
            returns = discounted_returns(rs_, bootstrap, ds_, c.gamma)
            grads = self._rollout_grads(
                snap, jnp.asarray(np.stack(os_), jnp.float32),
                jnp.asarray(np.asarray(as_), jnp.int32),
                jnp.asarray(returns))
            self._apply(grads)
        mdp.close()

    def train(self) -> List[float]:
        threads = [threading.Thread(target=self._actor, args=(t,))
                   for t in range(self.conf.n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return self.episode_rewards


# ---------------------------------------------------------------------------
# A3C
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class A3CConfiguration:
    n_threads: int = 2
    t_max: int = 8                 # rollout length per async update
    gamma: float = 0.95
    entropy_beta: float = 0.01
    value_coef: float = 0.5
    learning_rate: float = 3e-3
    max_step: int = 6000           # total env steps across all actors
    max_epoch_step: int = 100
    seed: int = 0


def _build_ac_graph(obs_size: int, n_actions: int, hidden: int,
                    lr: float, seed: int):
    from deeplearning4j_tpu import NeuralNetConfiguration
    from deeplearning4j_tpu.models.computation_graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers_core import (DenseLayer,
                                                        OutputLayer)
    from deeplearning4j_tpu.optimize.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(learning_rate=lr))
            .graph()
            .add_inputs("obs")
            .set_input_types(InputType.feed_forward(obs_size))
            .add_layer("h1", DenseLayer(n_out=hidden, activation="relu"),
                       "obs")
            .add_layer("h2", DenseLayer(n_out=hidden, activation="relu"),
                       "h1")
            .add_layer("policy", OutputLayer(n_out=n_actions,
                                             activation="identity",
                                             loss="mse"), "h2")
            .add_layer("value", OutputLayer(n_out=1,
                                            activation="identity",
                                            loss="mse"), "h2")
            .set_outputs("policy", "value")
            .build())
    return ComputationGraph(conf).init()


class A3CDiscrete(_AsyncActorLearner):
    """A3C over a discrete-action MDP; ``mdp_factory()`` builds one
    environment per actor thread.  The actor-critic network is a
    framework ``ComputationGraph`` with policy/value heads; the A3C
    loss (policy-gradient x advantage + entropy bonus + value
    regression — rl4j ``ActorCriticLoss``) is a custom jitted function
    over the graph's pure forward."""

    def __init__(self, mdp_factory: Callable[[], MDP],
                 conf: Optional[A3CConfiguration] = None,
                 hidden: int = 64):
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.optimize.updaters import Adam

        self.conf = conf or A3CConfiguration()
        c = self.conf
        self.mdp_factory = mdp_factory
        probe = mdp_factory()
        self.n_actions = probe.n_actions
        self.graph = _build_ac_graph(probe.obs_size, self.n_actions,
                                     hidden, c.learning_rate, c.seed)
        probe.close()
        self._updater = Adam(learning_rate=c.learning_rate)
        self._opt_state = self._updater.init_state(self.graph.params_tree)
        self._init_shared()

        graph, beta, vc = self.graph, c.entropy_beta, c.value_coef

        def loss_fn(params, obs, actions, returns):
            outs = graph._forward_infer(params, graph.state_tree,
                                        {"obs": obs})
            logits = outs["policy"].astype(jnp.float32)
            value = outs["value"].astype(jnp.float32)[:, 0]
            logp = jax.nn.log_softmax(logits, -1)
            p = jnp.exp(logp)
            adv = jax.lax.stop_gradient(returns - value)
            taken = jnp.take_along_axis(
                logp, actions[:, None].astype(jnp.int32), 1)[:, 0]
            policy_loss = -jnp.mean(taken * adv)
            entropy = -jnp.mean(jnp.sum(p * logp, -1))
            value_loss = jnp.mean(jnp.square(returns - value))
            return policy_loss - beta * entropy + vc * value_loss

        self._grads = jax.jit(jax.grad(loss_fn))
        self._policy_fwd = jax.jit(
            lambda params, obs: jax.nn.softmax(
                graph._forward_infer(params, graph.state_tree,
                                     {"obs": obs})["policy"], -1))
        self._value_fwd = jax.jit(
            lambda params, obs: graph._forward_infer(
                params, graph.state_tree, {"obs": obs})["value"])

    def _get_params(self):
        return self.graph.params_tree

    def _set_params(self, params):
        self.graph.params_tree = params

    def _select_action(self, snap, obs, rng) -> int:
        import jax.numpy as jnp
        probs = np.asarray(self._policy_fwd(
            snap, jnp.asarray(obs[None], jnp.float32)))[0]
        return int(rng.choice(self.n_actions, p=probs / probs.sum()))

    def _bootstrap_value(self, snap, obs) -> float:
        import jax.numpy as jnp
        return float(np.asarray(self._value_fwd(
            snap, jnp.asarray(obs[None], jnp.float32)))[0, 0])

    def _rollout_grads(self, snap, obs_batch, actions, returns):
        return self._grads(snap, obs_batch, actions, returns)

    def get_policy(self):
        return ACPolicy(self)


class ACPolicy:
    """Greedy policy over the trained actor head (rl4j ``ACPolicy``)."""

    def __init__(self, learner: A3CDiscrete):
        self.learner = learner

    def next_action(self, obs: np.ndarray) -> int:
        import jax.numpy as jnp
        probs = np.asarray(self.learner._policy_fwd(
            self.learner.graph.params_tree,
            jnp.asarray(obs[None], jnp.float32)))[0]
        return int(probs.argmax())

    def play(self, mdp: MDP, max_steps: int = 1000) -> float:
        obs = mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            obs, r, done = mdp.step(self.next_action(obs))
            total += r
            if done:
                break
        return total


# ---------------------------------------------------------------------------
# Async n-step Q-learning
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class AsyncNStepQConfiguration:
    n_threads: int = 2
    t_max: int = 5                 # the n of n-step
    gamma: float = 0.95
    learning_rate: float = 3e-3
    max_step: int = 6000
    max_epoch_step: int = 100
    target_update_freq: int = 200
    eps_start: float = 1.0
    eps_end: float = 0.05
    eps_decay_steps: int = 3000
    seed: int = 0


class AsyncNStepQLearningDiscrete(_AsyncActorLearner):
    """Async n-step Q-learning (rl4j ``AsyncNStepQLearningDiscrete``):
    actors collect n-step rollouts, compute TD targets against a shared
    target network, and apply gradients to the shared Q-network —
    replay-free asynchronous Q-learning."""

    def __init__(self, mdp_factory: Callable[[], MDP],
                 conf: Optional[AsyncNStepQConfiguration] = None,
                 hidden: int = 64):
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu import (MultiLayerNetwork,
                                        NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.conf.layers_core import (DenseLayer,
                                                            OutputLayer)
        from deeplearning4j_tpu.optimize.updaters import Adam

        self.conf = conf or AsyncNStepQConfiguration()
        c = self.conf
        self.mdp_factory = mdp_factory
        probe = mdp_factory()
        self.n_actions = probe.n_actions

        cfg = (NeuralNetConfiguration.builder().seed(c.seed)
               .updater(Adam(learning_rate=c.learning_rate)).list()
               .layer(DenseLayer(n_in=probe.obs_size, n_out=hidden,
                                 activation="relu"))
               .layer(DenseLayer(n_out=hidden, activation="relu"))
               .layer(OutputLayer(n_out=self.n_actions,
                                  activation="identity", loss="mse"))
               .build())
        self.q_net = MultiLayerNetwork(cfg).init()
        probe.close()
        self._target = jax.tree_util.tree_map(
            lambda a: jnp.array(a, copy=True), self.q_net.params_tree)
        self._updater = Adam(learning_rate=c.learning_rate)
        self._opt_state = self._updater.init_state(self.q_net.params_tree)
        self._init_shared()

        net = self.q_net

        def q_of(params, obs):
            h, _ = net._forward_layers(params, net.state_tree, obs,
                                       False, None)
            return h

        def loss_fn(params, obs, actions, targets):
            q = q_of(params, obs).astype(jnp.float32)
            taken = jnp.take_along_axis(
                q, actions[:, None].astype(jnp.int32), 1)[:, 0]
            return jnp.mean(jnp.square(targets - taken))

        self._grads = jax.jit(jax.grad(loss_fn))
        self._q_fwd = jax.jit(q_of)

    def _get_params(self):
        return self.q_net.params_tree

    def _set_params(self, params):
        self.q_net.params_tree = params

    def _snapshot(self):
        with self._lock:
            return (self.q_net.params_tree, self._target)

    def _epsilon(self) -> float:
        c = self.conf
        frac = min(1.0, self.step_count / max(1, c.eps_decay_steps))
        return c.eps_start + frac * (c.eps_end - c.eps_start)

    def _select_action(self, snap, obs, rng) -> int:
        import jax.numpy as jnp
        if rng.random() < self._epsilon():
            return int(rng.integers(0, self.n_actions))
        q = np.asarray(self._q_fwd(
            snap[0], jnp.asarray(obs[None], jnp.float32)))
        return int(q[0].argmax())

    def _bootstrap_value(self, snap, obs) -> float:
        import jax.numpy as jnp
        q = np.asarray(self._q_fwd(
            snap[1], jnp.asarray(obs[None], jnp.float32)))
        return float(q[0].max())

    def _rollout_grads(self, snap, obs_batch, actions, returns):
        return self._grads(snap[0], obs_batch, actions, returns)

    def _post_apply(self):
        if self.step_count % self.conf.target_update_freq < \
                self.conf.t_max:
            import jax
            import jax.numpy as jnp
            self._target = jax.tree_util.tree_map(
                lambda a: jnp.array(a, copy=True),
                self.q_net.params_tree)
