"""MDP protocol (``org.deeplearning4j.rl4j.mdp.MDP``) + an in-repo test
environment (the gym-java-client dependency has no analogue offline)."""
from __future__ import annotations

from typing import Tuple

import numpy as np


class MDP:
    """reset() -> observation; step(action) -> (obs, reward, done);
    ``n_actions``/``obs_size`` describe the spaces."""

    n_actions: int
    obs_size: int

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool]:
        raise NotImplementedError

    def close(self):
        pass


class SimpleGridWorld(MDP):
    """Deterministic n x n grid: start at (0,0), goal at (n-1,n-1),
    actions U/D/L/R, -0.01 per step, +1 at the goal, episode cap
    4*n steps.  Observation = normalized (row, col)."""

    def __init__(self, n: int = 5):
        self.n = int(n)
        self.n_actions = 4
        self.obs_size = 2
        self._pos = (0, 0)
        self._steps = 0

    def _obs(self) -> np.ndarray:
        return np.asarray([self._pos[0] / (self.n - 1),
                           self._pos[1] / (self.n - 1)], np.float32)

    def reset(self) -> np.ndarray:
        self._pos = (0, 0)
        self._steps = 0
        return self._obs()

    def step(self, action: int):
        dr, dc = [(-1, 0), (1, 0), (0, -1), (0, 1)][int(action)]
        r = min(max(self._pos[0] + dr, 0), self.n - 1)
        c = min(max(self._pos[1] + dc, 0), self.n - 1)
        self._pos = (r, c)
        self._steps += 1
        at_goal = self._pos == (self.n - 1, self.n - 1)
        done = at_goal or self._steps >= 4 * self.n
        reward = 1.0 if at_goal else -0.01
        return self._obs(), reward, done
