"""The one epoch/iteration loop shared by every training entry point.

DL4J triplicated this control flow across ``MultiLayerNetwork.fit``,
``ComputationGraph.fit`` and ``ParallelWrapper.fit``; here the loop —
epoch listeners, tBPTT segmentation, iteration listeners firing BEFORE the
counter increments (so checkpoints record the step they were taken at),
recurrent-carry clearing between batches — lives once, parameterized by
the step function (plain solver step, or the sharded-mesh step).
"""
from __future__ import annotations

from typing import Callable, Optional


def run_fit(model, iterator, n_epochs: int,
            step_fn: Optional[Callable] = None,
            reset_target=None) -> Optional[float]:
    """Drive ``step_fn(batch_dict) -> loss`` over an iterator for
    ``n_epochs``.  ``model`` supplies listeners/counters/_batch_dict;
    ``reset_target`` is the iterator whose ``reset()`` is called at epoch
    end (the unwrapped iterator when async prefetch is stacked on top).
    Without ``step_fn`` the model's own solver step is used (the plain
    single-device path); ShardedTrainer passes its mesh step."""
    from deeplearning4j_tpu.data.dataset import tbptt_segments

    if step_fn is None:
        def step_fn(batch):
            (model.params_tree, model.opt_state, model.state_tree,
             loss) = model._solver.step(
                model.params_tree, model.opt_state, model.state_tree,
                model.iteration_count, batch, model._rng.next_key())
            return loss

    tbptt_len = (model.conf.tbptt_fwd_length
                 if getattr(model.conf, "backprop_type", "standard")
                 == "truncated_bptt" else 0)
    last_loss = None
    for _ in range(n_epochs):
        for lst in model.listeners:
            lst.on_epoch_start(model, model.epoch_count)
        for ds in iterator:
            model.last_batch_size = ds.num_examples()
            chunks = tbptt_segments(ds, tbptt_len) if tbptt_len else [ds]
            for chunk in chunks:
                loss = step_fn(model._batch_dict(chunk))
                last_loss = loss
                # Listeners fire BEFORE the counter increments, so a
                # checkpoint taken in iteration_done records the step it
                # was taken at and resume agrees exactly.
                for lst in model.listeners:
                    lst.iteration_done(model, model.iteration_count,
                                       model.epoch_count, loss)
                model.iteration_count += 1
            # Recurrent carry flows ACROSS tBPTT chunks of one batch (that
            # is the point of truncated BPTT) but never across batches.
            if model._has_rnn():
                model.rnn_clear_previous_state()
        # Increment BEFORE epoch listeners so a checkpoint taken in
        # on_epoch_end records "N epochs completed" and resumes exactly.
        model.epoch_count += 1
        for lst in model.listeners:
            lst.on_epoch_end(model, model.epoch_count - 1)
        (reset_target if reset_target is not None else iterator).reset()
    return None if last_loss is None else float(last_loss)
