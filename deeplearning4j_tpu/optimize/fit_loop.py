"""The one epoch/iteration loop shared by every training entry point.

DL4J triplicated this control flow across ``MultiLayerNetwork.fit``,
``ComputationGraph.fit`` and ``ParallelWrapper.fit``; here the loop —
epoch listeners, tBPTT segmentation, iteration listeners firing BEFORE the
counter increments (so checkpoints record the step they were taken at),
recurrent-carry clearing between batches — lives once, parameterized by
the step function (plain solver step, or the sharded-mesh step).

Fault tolerance rides the same single loop (resilience layer):

* ``resume=True`` restores the newest checkpoint from the attached
  ``CheckpointListener`` and fast-forwards the iterator to the exact
  batch, so a restarted process replays nothing and skips nothing;
* a SIGTERM/SIGINT (see ``resilience.PreemptionGuard``) is polled at
  step boundaries: the loop forces one final checkpoint save + wait,
  then unwinds with ``TrainingPreempted``;
* the chaos injector's training sites live here (step exceptions,
  NaN-poisoned batches, data stalls, simulated preemption) so injected
  faults traverse exactly the code real ones would.
"""
from __future__ import annotations

import logging
import time
from typing import Callable, Optional

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.analysis import sanitize as _sanitize
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience import preemption as _preemption
from deeplearning4j_tpu.resilience.errors import TrainingPreempted

log = logging.getLogger("deeplearning4j_tpu")

# Structural fit-loop telemetry — fires for EVERY training entry point
# (plain fit, ShardedTrainer, tBPTT) without any listener attached.
# "data wait" vs "step" is the first question a slow run asks: is the
# chip starved by the input pipeline or is the step itself the cost?
# Host-side split: step time here is dispatch + any blocking the solver
# does; time INSIDE the XLA program shows up in whichever of the two
# the device queue back-pressures into.
_ITERS = telemetry.counter(
    "train_iterations_total", "optimizer steps driven by run_fit")
_EPOCHS = telemetry.counter("train_epochs_total", "completed epochs")
_EXAMPLES = telemetry.counter(
    "train_examples_total", "examples consumed from the iterator")
_DATA_WAIT = telemetry.histogram(
    "train_data_wait_seconds",
    "host wall time blocked on the data iterator per batch")
_STEP_TIME = telemetry.histogram(
    "train_step_dispatch_seconds",
    "host wall time in step_fn per tBPTT chunk (dispatch + listener "
    "sync, not device completion)")


def _checkpoint_listener(model):
    """The first CheckpointListener attached to the model, or None.
    Lazy import: the parallel package imports this module."""
    from deeplearning4j_tpu.parallel.checkpoint import CheckpointListener
    for lst in model.listeners:
        if isinstance(lst, CheckpointListener):
            return lst
    return None


def _preemption_save(ck, model) -> Optional[int]:
    """Force the final pre-exit checkpoint: save at the just-completed
    iteration (unless a periodic hook this step already did) and BLOCK
    until every async shard write lands — the one save that must not
    be in flight when the process dies.  Returns the newest step on
    disk (None without a checkpointer: state is lost)."""
    if ck is None:
        log.warning("preempted with no CheckpointListener attached — "
                    "training state is NOT saved")
        return None
    label = model.iteration_count - 1
    try:
        if label >= 0 and label not in ck.ckpt.all_steps():
            ck.ckpt.save(label, ck._state(model), force=True)
        # wait() can also re-raise an EARLIER async write's failure —
        # that must not mask TrainingPreempted (resume falls back to
        # the newest checkpoint that did land)
        ck.ckpt.wait()
    except Exception:
        log.exception("forced preemption checkpoint at step %d failed; "
                      "resume will use the previous one", label)
    steps = ck.ckpt.all_steps()
    return steps[-1] if steps else None


def run_fit(model, iterator, n_epochs: int,
            step_fn: Optional[Callable] = None,
            reset_target=None, resume: bool = False) -> Optional[float]:
    """Drive ``step_fn(batch_dict) -> loss`` over an iterator for
    ``n_epochs``.  ``model`` supplies listeners/counters/_batch_dict;
    ``reset_target`` is the iterator whose ``reset()`` is called at epoch
    end (the unwrapped iterator when async prefetch is stacked on top).
    Without ``step_fn`` the model's own solver step is used (the plain
    single-device path); ShardedTrainer passes its mesh step.

    ``resume=True`` restores the newest checkpoint from the attached
    ``CheckpointListener`` (params, optimizer state, counters, RNG
    stream) and fast-forwards the iterator past the batches the
    checkpointed epoch already consumed — the continuation is
    bit-identical to the uninterrupted run at batch granularity.  In
    resume mode ``n_epochs`` is the TOTAL epoch target, not an
    increment: a run preempted in epoch 3 of 5 resumes for the
    remaining 2."""
    from deeplearning4j_tpu.data.dataset import tbptt_segments

    if step_fn is None:
        def step_fn(batch):
            (model.params_tree, model.opt_state, model.state_tree,
             loss) = model._solver.step(
                model.params_tree, model.opt_state, model.state_tree,
                model.iteration_count, batch, model._rng.next_key(),
                lr_scale=getattr(model, "_lr_backoff", 1.0))
            return loss

    skip_batches = 0
    if resume:
        ck = _checkpoint_listener(model)
        if ck is None:
            raise ValueError("resume=True requires a CheckpointListener "
                             "among model.listeners")
        step = ck.restore_into(model)
        if step is not None:
            skip_batches = int(getattr(model, "batch_in_epoch", 0))
            _preemption.RESUMES.inc()
            log.info("resumed from checkpoint step %d (epoch %d, "
                     "%d batches into it)", step, model.epoch_count,
                     skip_batches)
        if model.epoch_count >= n_epochs:
            return None
        epochs_to_run = n_epochs - model.epoch_count
    else:
        epochs_to_run = n_epochs

    tbptt_len = (model.conf.tbptt_fwd_length
                 if getattr(model.conf, "backprop_type", "standard")
                 == "truncated_bptt" else 0)
    last_loss = None
    tracer = telemetry.get_tracer()
    for _ in range(epochs_to_run):
        for lst in model.listeners:
            lst.on_epoch_start(model, model.epoch_count)
        data_it = iter(iterator)
        if skip_batches:
            # resumed mid-epoch: fast-forward past the batches the
            # checkpointed position already consumed
            for _ in range(skip_batches):
                try:
                    next(data_it)
                except StopIteration:
                    break
            skip_batches = 0
        else:
            model.batch_in_epoch = 0
        while True:
            t_fetch = time.perf_counter()
            _faults.maybe_stall("data_stall", model.iteration_count)
            try:
                ds = next(data_it)
            except StopIteration:
                break
            _DATA_WAIT.observe(time.perf_counter() - t_fetch)
            model.last_batch_size = ds.num_examples()
            _EXAMPLES.inc(model.last_batch_size)
            chunks = tbptt_segments(ds, tbptt_len) if tbptt_len else [ds]
            for ci, chunk in enumerate(chunks):
                t_step = time.perf_counter()
                batch = _faults.corrupt_batch(model.iteration_count,
                                              model._batch_dict(chunk))
                _faults.maybe_fail("step_exception",
                                   model.iteration_count)
                with tracer.span("train/step",
                                 iteration=model.iteration_count):
                    loss = step_fn(batch)
                if _sanitize.active("nan"):
                    # DL4J_TPU_SANITIZE=nan — one device sync per step;
                    # the opt-in dynamic confirmation of jit_lint's
                    # NaN findings (the solver's bad-step SELECT keeps
                    # params clean, but the loss still reports NaN)
                    _sanitize.check_finite(
                        "train/loss", loss,
                        detail=f"iteration {model.iteration_count}")
                last_loss = loss
                # batch_in_epoch counts COMPLETED batches and advances
                # with the batch's LAST chunk, BEFORE listeners fire —
                # so a checkpoint taken in iteration_done stores a
                # batch position consistent with its step counter.
                if ci == len(chunks) - 1:
                    model.batch_in_epoch = \
                        getattr(model, "batch_in_epoch", 0) + 1
                # Listeners fire BEFORE the counter increments, so a
                # checkpoint taken in iteration_done records the step it
                # was taken at and resume agrees exactly.
                for lst in model.listeners:
                    lst.iteration_done(model, model.iteration_count,
                                       model.epoch_count, loss)
                _STEP_TIME.observe(time.perf_counter() - t_step)
                _ITERS.inc()
                model.iteration_count += 1
                # chaos site: simulated SIGTERM after iteration N
                if _faults.fires("preempt", model.iteration_count - 1):
                    _preemption.request_preemption()
                # act on preemption only at BATCH boundaries: a forced
                # save mid-batch (tBPTT chunk) would store an
                # iteration/RNG position the batch-granular
                # batch_in_epoch cannot express, and resume would
                # replay chunks under shifted step indices.  The poll
                # is fleet-coordinated when a FleetCoordinator is
                # installed: the flag or-reduces over the global mesh
                # so EVERY rank answers identically here and the forced
                # saves all carry the same step label.
                if ci == len(chunks) - 1 and \
                        _preemption.poll_preemption():
                    _preemption.PREEMPTIONS.inc()
                    final = _preemption_save(_checkpoint_listener(model),
                                             model)
                    raise TrainingPreempted(final)
            # Recurrent carry flows ACROSS tBPTT chunks of one batch (that
            # is the point of truncated BPTT) but never across batches.
            if model._has_rnn():
                model.rnn_clear_previous_state()
        # Increment BEFORE epoch listeners so a checkpoint taken in
        # on_epoch_end records "N epochs completed" and resumes exactly.
        model.epoch_count += 1
        model.batch_in_epoch = 0
        _EPOCHS.inc()
        for lst in model.listeners:
            lst.on_epoch_end(model, model.epoch_count - 1)
        (reset_target if reset_target is not None else iterator).reset()
    return None if last_loss is None else float(last_loss)
