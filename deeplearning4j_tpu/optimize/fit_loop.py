"""The one epoch/iteration loop shared by every training entry point.

DL4J triplicated this control flow across ``MultiLayerNetwork.fit``,
``ComputationGraph.fit`` and ``ParallelWrapper.fit``; here the loop —
epoch listeners, tBPTT segmentation, iteration listeners firing BEFORE the
counter increments (so checkpoints record the step they were taken at),
recurrent-carry clearing between batches — lives once, parameterized by
the step function (plain solver step, or the sharded-mesh step).
"""
from __future__ import annotations

import time
from typing import Callable, Optional

from deeplearning4j_tpu import telemetry

# Structural fit-loop telemetry — fires for EVERY training entry point
# (plain fit, ShardedTrainer, tBPTT) without any listener attached.
# "data wait" vs "step" is the first question a slow run asks: is the
# chip starved by the input pipeline or is the step itself the cost?
# Host-side split: step time here is dispatch + any blocking the solver
# does; time INSIDE the XLA program shows up in whichever of the two
# the device queue back-pressures into.
_ITERS = telemetry.counter(
    "train_iterations_total", "optimizer steps driven by run_fit")
_EPOCHS = telemetry.counter("train_epochs_total", "completed epochs")
_EXAMPLES = telemetry.counter(
    "train_examples_total", "examples consumed from the iterator")
_DATA_WAIT = telemetry.histogram(
    "train_data_wait_seconds",
    "host wall time blocked on the data iterator per batch")
_STEP_TIME = telemetry.histogram(
    "train_step_dispatch_seconds",
    "host wall time in step_fn per tBPTT chunk (dispatch + listener "
    "sync, not device completion)")


def run_fit(model, iterator, n_epochs: int,
            step_fn: Optional[Callable] = None,
            reset_target=None) -> Optional[float]:
    """Drive ``step_fn(batch_dict) -> loss`` over an iterator for
    ``n_epochs``.  ``model`` supplies listeners/counters/_batch_dict;
    ``reset_target`` is the iterator whose ``reset()`` is called at epoch
    end (the unwrapped iterator when async prefetch is stacked on top).
    Without ``step_fn`` the model's own solver step is used (the plain
    single-device path); ShardedTrainer passes its mesh step."""
    from deeplearning4j_tpu.data.dataset import tbptt_segments

    if step_fn is None:
        def step_fn(batch):
            (model.params_tree, model.opt_state, model.state_tree,
             loss) = model._solver.step(
                model.params_tree, model.opt_state, model.state_tree,
                model.iteration_count, batch, model._rng.next_key())
            return loss

    tbptt_len = (model.conf.tbptt_fwd_length
                 if getattr(model.conf, "backprop_type", "standard")
                 == "truncated_bptt" else 0)
    last_loss = None
    tracer = telemetry.get_tracer()
    for _ in range(n_epochs):
        for lst in model.listeners:
            lst.on_epoch_start(model, model.epoch_count)
        data_it = iter(iterator)
        while True:
            t_fetch = time.perf_counter()
            try:
                ds = next(data_it)
            except StopIteration:
                break
            _DATA_WAIT.observe(time.perf_counter() - t_fetch)
            model.last_batch_size = ds.num_examples()
            _EXAMPLES.inc(model.last_batch_size)
            chunks = tbptt_segments(ds, tbptt_len) if tbptt_len else [ds]
            for chunk in chunks:
                t_step = time.perf_counter()
                with tracer.span("train/step",
                                 iteration=model.iteration_count):
                    loss = step_fn(model._batch_dict(chunk))
                last_loss = loss
                # Listeners fire BEFORE the counter increments, so a
                # checkpoint taken in iteration_done records the step it
                # was taken at and resume agrees exactly.
                for lst in model.listeners:
                    lst.iteration_done(model, model.iteration_count,
                                       model.epoch_count, loss)
                _STEP_TIME.observe(time.perf_counter() - t_step)
                _ITERS.inc()
                model.iteration_count += 1
            # Recurrent carry flows ACROSS tBPTT chunks of one batch (that
            # is the point of truncated BPTT) but never across batches.
            if model._has_rnn():
                model.rnn_clear_previous_state()
        # Increment BEFORE epoch listeners so a checkpoint taken in
        # on_epoch_end records "N epochs completed" and resumes exactly.
        model.epoch_count += 1
        _EPOCHS.inc()
        for lst in model.listeners:
            lst.on_epoch_end(model, model.epoch_count - 1)
        (reset_target if reset_target is not None else iterator).reset()
    return None if last_loss is None else float(last_loss)
