"""Optimization: updaters, LR schedules, the solver (train-step assembly),
and the training-listener bus.

TPU-native twin of ``org.deeplearning4j.optimize`` + the updater math in
``org.nd4j.linalg.learning``.  DL4J applies updaters in-place on one
flattened parameter vector through ``UpdaterBlock`` views; here updaters are
pure pytree transforms fused by XLA into the compiled train step.
"""

from deeplearning4j_tpu.optimize.updaters import (
    Adam, AdamW, AdaDelta, AdaGrad, AdaMax, AMSGrad, Ema, Nadam, Nesterovs,
    NoOp, RmsProp, Sgd, updater_from_dict,
)
from deeplearning4j_tpu.optimize.schedules import schedule_from_spec

__all__ = [
    "Sgd", "Adam", "AdamW", "AdaMax", "Nesterovs", "RmsProp", "AdaGrad",
    "AdaDelta", "AMSGrad", "Nadam", "NoOp", "Ema", "updater_from_dict",
    "schedule_from_spec",
]
