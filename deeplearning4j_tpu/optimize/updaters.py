"""Updaters (optimizers) as pure pytree transforms.

Parity with ND4J's updater zoo (reference: ``org.nd4j.linalg.learning.config.
{Sgd,Adam,AdamW,AdaMax,Nesterovs,RmsProp,AdaGrad,AdaDelta,AMSGrad,Nadam,NoOp,Ema}``
with math in ``org.nd4j.linalg.learning.{Adam,Nesterovs,...}Updater``).

DL4J semantics kept for loss-curve parity:

* Adam bias correction uses ``alpha_t = lr * sqrt(1-b2^t)/(1-b1^t)`` applied
  to the raw moments (same fixed point as the PyTorch/Keras form);
* Nesterovs uses DL4J's ``v' = mu*v - lr*g;  update = -(mu*v' - (1+mu)*... )``
  — concretely DL4J applies ``params += mu*mu*v - (1+mu)*lr*g`` (momentum
  look-ahead), reproduced here exactly;
* AdaGrad epsilon inside the sqrt denominator, DL4J default eps=1e-6.

Each updater is a dataclass: ``init_state(params)`` and
``update(grads, state, params, step) -> (updates, new_state)`` where
``new_params = params - updates`` (minimization).  All math is jnp, so the
whole update fuses into the compiled train step (no per-param kernel
launches, no UpdaterBlock views).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.optimize.schedules import schedule_from_spec

_UPDATER_REGISTRY: Dict[str, type] = {}


def register_updater(cls):
    _UPDATER_REGISTRY[cls.__name__] = cls
    return cls


def updater_from_dict(d) -> "BaseUpdater":
    if d is None:
        return Sgd()
    if isinstance(d, BaseUpdater):
        return d
    d = dict(d)
    type_name = d.pop("type")
    cls = _UPDATER_REGISTRY.get(type_name)
    if cls is None:
        raise ValueError(f"Unknown updater type {type_name!r}; "
                         f"available: {sorted(_UPDATER_REGISTRY)}")
    return cls(**d)


def _tmap(fn, *trees):
    return jax.tree_util.tree_map(fn, *trees)


def _zeros_like(params):
    return _tmap(jnp.zeros_like, params)


@dataclasses.dataclass
class BaseUpdater:
    learning_rate: Any = 0.1  # float or schedule spec dict

    def to_dict(self):
        d = {"type": type(self).__name__}
        for f in dataclasses.fields(self):
            d[f.name] = getattr(self, f.name)
        return d

    def lr_at(self, step):
        return schedule_from_spec(self.learning_rate)(step)

    def init_state(self, params):
        return {}

    def update(self, grads, state, params, step):
        raise NotImplementedError

    def finalize(self, state, new_params):
        """Hook called by the trainers AFTER the final parameters are
        computed (i.e. after decoupled weight decay is folded in) —
        lets state transforms like Ema track the ACTUAL new params."""
        return state


@register_updater
@dataclasses.dataclass
class NoOp(BaseUpdater):
    def update(self, grads, state, params, step):
        return _tmap(jnp.zeros_like, grads), state


@register_updater
@dataclasses.dataclass
class Sgd(BaseUpdater):
    def update(self, grads, state, params, step):
        lr = self.lr_at(step)
        return _tmap(lambda g: lr * g, grads), state


@register_updater
@dataclasses.dataclass
class Nesterovs(BaseUpdater):
    learning_rate: Any = 0.1
    momentum: float = 0.9

    def init_state(self, params):
        return {"v": _zeros_like(params)}

    def update(self, grads, state, params, step):
        lr, mu = self.lr_at(step), self.momentum
        v_new = _tmap(lambda v, g: mu * v - lr * g, state["v"], grads)
        # DL4J NesterovsUpdater: update applied = -(mu * v_new - lr * g)
        #   i.e. params += mu*v_new - lr*g  (look-ahead step)
        updates = _tmap(lambda vn, g: -(mu * vn - lr * g), v_new, grads)
        return updates, {"v": v_new}


@register_updater
@dataclasses.dataclass
class Adam(BaseUpdater):
    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, params):
        return {"m": _zeros_like(params), "v": _zeros_like(params)}

    def update(self, grads, state, params, step):
        lr = self.lr_at(step)
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        t = jnp.asarray(step + 1, jnp.float32)
        m = _tmap(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        v = _tmap(lambda v, g: b2 * v + (1 - b2) * g * g, state["v"], grads)
        alpha = lr * jnp.sqrt(1 - b2**t) / (1 - b1**t)
        updates = _tmap(lambda m, v: alpha * m / (jnp.sqrt(v) + eps), m, v)
        return updates, {"m": m, "v": v}


@register_updater
@dataclasses.dataclass
class AdamW(Adam):
    weight_decay: float = 1e-2

    def update(self, grads, state, params, step):
        updates, st = super().update(grads, state, params, step)
        lr = self.lr_at(step)
        wd = self.weight_decay
        updates = _tmap(lambda u, p: u + lr * wd * p, updates, params)
        return updates, st


@register_updater
@dataclasses.dataclass
class AMSGrad(Adam):
    def init_state(self, params):
        return {"m": _zeros_like(params), "v": _zeros_like(params),
                "vhat": _zeros_like(params)}

    def update(self, grads, state, params, step):
        lr = self.lr_at(step)
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        t = jnp.asarray(step + 1, jnp.float32)
        m = _tmap(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        v = _tmap(lambda v, g: b2 * v + (1 - b2) * g * g, state["v"], grads)
        vhat = _tmap(jnp.maximum, state["vhat"], v)
        alpha = lr * jnp.sqrt(1 - b2**t) / (1 - b1**t)
        updates = _tmap(lambda m, vh: alpha * m / (jnp.sqrt(vh) + eps), m, vhat)
        return updates, {"m": m, "v": v, "vhat": vhat}


@register_updater
@dataclasses.dataclass
class Nadam(Adam):
    def update(self, grads, state, params, step):
        lr = self.lr_at(step)
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        t = jnp.asarray(step + 1, jnp.float32)
        m = _tmap(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        v = _tmap(lambda v, g: b2 * v + (1 - b2) * g * g, state["v"], grads)
        mc = 1.0 / (1 - b1**t)
        vc = 1.0 / (1 - b2**t)
        updates = _tmap(
            lambda m, v, g: lr * (b1 * m * mc + (1 - b1) * g * mc)
            / (jnp.sqrt(v * vc) + eps),
            m, v, grads)
        return updates, {"m": m, "v": v}


@register_updater
@dataclasses.dataclass
class AdaMax(Adam):
    def init_state(self, params):
        return {"m": _zeros_like(params), "u": _zeros_like(params)}

    def update(self, grads, state, params, step):
        lr = self.lr_at(step)
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        t = jnp.asarray(step + 1, jnp.float32)
        m = _tmap(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        u = _tmap(lambda u, g: jnp.maximum(b2 * u, jnp.abs(g)), state["u"], grads)
        alpha = lr / (1 - b1**t)
        updates = _tmap(lambda m, u: alpha * m / (u + eps), m, u)
        return updates, {"m": m, "u": u}


@register_updater
@dataclasses.dataclass
class RmsProp(BaseUpdater):
    learning_rate: Any = 1e-1
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def init_state(self, params):
        return {"g2": _zeros_like(params)}

    def update(self, grads, state, params, step):
        lr, d, eps = self.lr_at(step), self.rms_decay, self.epsilon
        g2 = _tmap(lambda a, g: d * a + (1 - d) * g * g, state["g2"], grads)
        updates = _tmap(lambda g, a: lr * g / (jnp.sqrt(a) + eps), grads, g2)
        return updates, {"g2": g2}


@register_updater
@dataclasses.dataclass
class AdaGrad(BaseUpdater):
    learning_rate: Any = 1e-1
    epsilon: float = 1e-6

    def init_state(self, params):
        return {"g2": _zeros_like(params)}

    def update(self, grads, state, params, step):
        lr, eps = self.lr_at(step), self.epsilon
        g2 = _tmap(lambda a, g: a + g * g, state["g2"], grads)
        updates = _tmap(lambda g, a: lr * g / (jnp.sqrt(a + eps)), grads, g2)
        return updates, {"g2": g2}


@register_updater
@dataclasses.dataclass
class AdaDelta(BaseUpdater):
    learning_rate: Any = 1.0  # unused by the algorithm; kept for interface
    rho: float = 0.95
    epsilon: float = 1e-6

    def init_state(self, params):
        return {"g2": _zeros_like(params), "dx2": _zeros_like(params)}

    def update(self, grads, state, params, step):
        rho, eps = self.rho, self.epsilon
        g2 = _tmap(lambda a, g: rho * a + (1 - rho) * g * g, state["g2"], grads)
        dx = _tmap(lambda g, a, d: g * jnp.sqrt(d + eps) / jnp.sqrt(a + eps),
                   grads, g2, state["dx2"])
        dx2 = _tmap(lambda d, x: rho * d + (1 - rho) * x * x, state["dx2"], dx)
        return dx, {"g2": g2, "dx2": dx2}


@register_updater
@dataclasses.dataclass
class Ema(BaseUpdater):
    """Wrapper updater maintaining an exponential moving average of the
    PARAMETERS inside the optimizer state — the TPU-native form of the
    reference's model-averaging semantic
    (``ParameterAveragingTrainingMaster`` averages replicas/time
    [UNVERIFIED]; here replicas are already exact via GSPMD all-reduce,
    so the useful axis is time: Polyak/EMA averaging).

    Wraps ANY base updater, so it works unchanged from both trainers
    (MultiLayerNetwork/ComputationGraph solver and ShardedTrainer).
    Fetch the averaged weights with ``Ema.params_from_state(opt_state)``
    (e.g. for eval/checkpoint); ``decay=0`` degenerates to tracking the
    raw parameters.
    """

    base: Any = None        # BaseUpdater | serialized dict | None=Sgd
    decay: float = 0.999

    def _resolved(self) -> "BaseUpdater":
        return updater_from_dict(self.base)

    def to_dict(self):
        d = super().to_dict()
        if isinstance(d.get("base"), BaseUpdater):
            d["base"] = d["base"].to_dict()
        return d

    def lr_at(self, step):
        return self._resolved().lr_at(step)

    def init_state(self, params):
        # jnp.copy, NOT asarray: the solver donates params and
        # opt_state separately — aliased buffers would double-donate.
        return {"base": self._resolved().init_state(params),
                "ema": _tmap(jnp.copy, params)}

    def update(self, grads, state, params, step):
        updates, base_state = self._resolved().update(
            grads, state["base"], params, step)
        # the EMA itself advances in finalize(), AFTER the trainer has
        # folded decoupled weight decay into the updates — tracking
        # (params - updates) here would drift by lr*wd*p per step
        return updates, {"base": base_state, "ema": state["ema"]}

    def finalize(self, state, new_params):
        d = self.decay
        ema = _tmap(lambda e, p: d * e + (1 - d) * p,
                    state["ema"], new_params)
        return {"base": state["base"], "ema": ema}

    @staticmethod
    def params_from_state(opt_state):
        """The averaged parameter pytree held in the optimizer state."""
        return opt_state["ema"]
