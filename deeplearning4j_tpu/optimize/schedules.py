"""Learning-rate schedules.

Parity with ND4J's ``ISchedule`` family (reference:
``org.nd4j.linalg.schedule.{ExponentialSchedule,InverseSchedule,MapSchedule,
PolySchedule,RampSchedule,SigmoidSchedule,StepSchedule,CycleSchedule}``).
A schedule is a pure fn(step) -> lr so it traces into the compiled step
(step is a traced scalar; all branches are jnp math, no Python control
flow on step).
"""
from __future__ import annotations

import jax.numpy as jnp


def schedule_from_spec(spec):
    """spec: float (fixed) or dict {"type": ..., ...} -> fn(step)->lr.

    Schedules are stepped per ITERATION (DL4J ScheduleType.ITERATION); for
    epoch-based scheduling pass iterations_per_epoch when building the dict.
    """
    if spec is None:
        return lambda step: 0.0
    if isinstance(spec, (int, float)):
        v = float(spec)
        return lambda step: v
    t = str(spec.get("type", "fixed")).lower()
    if t == "fixed":
        v = float(spec["value"])
        return lambda step: v

    lr = float(spec.get("initial", spec.get("value", 0.1)))
    if t == "exponential":
        gamma = float(spec.get("gamma", 0.99))
        return lambda step: lr * jnp.power(gamma, step)
    if t == "inverse":
        gamma, power = float(spec.get("gamma", 0.99)), float(spec.get("power", 1.0))
        return lambda step: lr / jnp.power(1.0 + gamma * step, power)
    if t == "poly":
        power, max_iter = float(spec.get("power", 1.0)), float(spec["max_iter"])
        return lambda step: lr * jnp.power(
            1.0 - jnp.minimum(step, max_iter) / max_iter, power)
    if t == "step":
        decay, step_size = float(spec.get("decay", 0.1)), float(spec["step"])
        return lambda step: lr * jnp.power(decay, jnp.floor(step / step_size))
    if t == "sigmoid":
        gamma, step_size = float(spec.get("gamma", 0.99)), float(spec["step"])
        return lambda step: lr / (1.0 + jnp.exp(-gamma * (step - step_size)))
    if t == "ramp":  # warmup to lr over `warmup` steps, then constant
        warmup = float(spec.get("warmup", 1000))
        return lambda step: lr * jnp.minimum(1.0, (step + 1) / warmup)
    if t == "warmup_cosine":  # TPU-era staple (not in DL4J): linear warmup + cosine
        warmup = float(spec.get("warmup", 1000))
        total = float(spec["max_iter"])
        def fn(step):
            warm = lr * jnp.minimum(1.0, (step + 1) / warmup)
            prog = jnp.clip((step - warmup) / jnp.maximum(total - warmup, 1), 0, 1)
            return jnp.where(step < warmup, warm,
                             0.5 * lr * (1 + jnp.cos(jnp.pi * prog)))
        return fn
    if t == "cycle":
        cycle_len = float(spec["cycle_length"])
        max_lr = float(spec.get("max", lr * 10))
        def fn(step):
            pos = (step % cycle_len) / cycle_len
            tri = 1.0 - jnp.abs(2.0 * pos - 1.0)
            return lr + (max_lr - lr) * tri
        return fn
    if t == "map":
        # {"type":"map","values":{"0":0.1,"1000":0.01}} — piecewise constant
        points = sorted((int(k), float(v)) for k, v in spec["values"].items())
        def fn(step):
            out = jnp.asarray(points[0][1])
            for s, v in points:
                out = jnp.where(step >= s, v, out)
            return out
        return fn
    raise ValueError(f"Unknown schedule type {t!r}")
