"""Training listeners.

Parity with ``org.deeplearning4j.optimize.api.TrainingListener`` and the
built-ins in ``org.deeplearning4j.optimize.listeners.{ScoreIterationListener,
PerformanceListener,CollectScoresIterationListener,TimeIterationListener,
EvaluativeListener,CheckpointListener}``.

The listener bus fires OUTSIDE the compiled step, on host: loss values
arrive as jax Arrays whose device->host read is the only sync point; a
listener that ignores the loss never blocks the device queue.
"""
from __future__ import annotations

import logging
import time
from typing import List, Optional

log = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    """Event protocol (subset of DL4J's; extend as needed)."""

    def iteration_done(self, model, iteration: int, epoch: int, score) -> None:
        pass

    def on_epoch_start(self, model, epoch: int) -> None:
        pass

    def on_epoch_end(self, model, epoch: int) -> None:
        pass


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (``ScoreIterationListener``)."""

    def __init__(self, print_iterations: int = 10):
        self.print_iterations = max(1, int(print_iterations))

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.print_iterations == 0:
            log.info("Score at iteration %d is %s", iteration, float(score))


class PerformanceListener(TrainingListener):
    """Throughput reporting (``PerformanceListener``): examples/sec,
    iterations/sec, averaged over the reporting window."""

    def __init__(self, frequency: int = 100, report_batch: bool = True):
        self.frequency = max(1, int(frequency))
        self.report_batch = report_batch
        self._t0: Optional[float] = None
        self._examples = 0
        self._iters = 0

    def iteration_done(self, model, iteration, epoch, score):
        now = time.perf_counter()
        bs = getattr(model, "last_batch_size", 0)
        self._examples += bs
        self._iters += 1
        if self._t0 is None:
            self._t0 = now
            self._examples = 0
            self._iters = 0
            return
        if self._iters >= self.frequency:
            dt = now - self._t0
            log.info(
                "iter %d (epoch %d): %.1f iters/sec, %.1f examples/sec, score %s",
                iteration, epoch, self._iters / dt, self._examples / dt,
                float(score))
            self._t0 = now
            self._examples = 0
            self._iters = 0


class CollectScoresListener(TrainingListener):
    """Collect (iteration, score) pairs in memory
    (``CollectScoresIterationListener``)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, int(frequency))
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(score)))


class TimeIterationListener(TrainingListener):
    """ETA logging (``TimeIterationListener``)."""

    def __init__(self, total_iterations: int, frequency: int = 100):
        self.total = total_iterations
        self.frequency = max(1, int(frequency))
        self._start = time.perf_counter()
        self._count = 0

    def iteration_done(self, model, iteration, epoch, score):
        self._count += 1
        if self._count % self.frequency == 0:
            elapsed = time.perf_counter() - self._start
            rate = self._count / elapsed
            remaining = (self.total - self._count) / max(rate, 1e-9)
            log.info("iteration %d/%d, ETA %.1fs", self._count, self.total,
                     remaining)


class EvaluativeListener(TrainingListener):
    """Periodic evaluation on a held-out iterator (``EvaluativeListener``)."""

    def __init__(self, iterator, frequency: int = 1, unit: str = "epoch"):
        self.iterator = iterator
        self.frequency = max(1, int(frequency))
        self.unit = unit  # 'epoch' | 'iteration'
        self.last_evaluation = None

    def _run(self, model):
        self.iterator.reset()
        self.last_evaluation = model.evaluate(self.iterator)
        log.info("Evaluation:\n%s", self.last_evaluation.stats())

    def iteration_done(self, model, iteration, epoch, score):
        if self.unit == "iteration" and iteration % self.frequency == 0:
            self._run(model)

    def on_epoch_end(self, model, epoch):
        if self.unit == "epoch" and (epoch + 1) % self.frequency == 0:
            self._run(model)


class CheckpointListener(TrainingListener):
    """Periodic checkpoints with keep-last-K rotation
    (``CheckpointListener``: every N epochs/iterations, keepLast)."""

    def __init__(self, directory, every_n_epochs: Optional[int] = None,
                 every_n_iterations: Optional[int] = None, keep_last: int = 3):
        import os
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.every_n_epochs = every_n_epochs
        self.every_n_iterations = every_n_iterations
        self.keep_last = keep_last
        self._saved: List[str] = []

    def _save(self, model, tag: str):
        import os
        from deeplearning4j_tpu.utils.model_serializer import write_model
        path = os.path.join(self.directory, f"checkpoint_{tag}.zip")
        write_model(model, path, save_updater=True)
        self._saved.append(path)
        while len(self._saved) > self.keep_last:
            old = self._saved.pop(0)
            if os.path.exists(old):
                os.remove(old)
        log.info("Checkpoint saved: %s", path)

    def iteration_done(self, model, iteration, epoch, score):
        if self.every_n_iterations and iteration > 0 \
                and iteration % self.every_n_iterations == 0:
            self._save(model, f"iter_{iteration}")

    def on_epoch_end(self, model, epoch):
        if self.every_n_epochs and (epoch + 1) % self.every_n_epochs == 0:
            self._save(model, f"epoch_{epoch}")
