"""Solver: assembles the single compiled train step.

Replaces DL4J's ``Solver`` → ``StochasticGradientDescent`` →
``BaseOptimizer`` chain (reference: ``org.deeplearning4j.optimize.solvers.
{Solver,StochasticGradientDescent,BaseOptimizer}``).  Where DL4J runs
``computeGradientAndScore`` (thousands of eager ops, one JNI crossing each)
then applies the updater in-place, here the WHOLE iteration — forward, loss,
backward (jax.grad), gradient normalization, updater math, parameter
update — is one XLA program.  Parameter and optimizer-state buffers are
donated, so the update is in-place in HBM (the workspace behavior DL4J got
from flattened-vector views).
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.analysis import sanitize as _sanitize
from deeplearning4j_tpu.optimize.updaters import BaseUpdater


def check_numerics_enabled() -> bool:
    """NaN/Inf debug mode (``OpProfiler`` ``checkForNAN``/``checkForINF``
    analogue): ``DL4J_TPU_CHECK_NUMERICS=1`` makes every train step
    validate its loss and updated params host-side, naming the offending
    leaves.  Costs one device sync per step — a debug mode, as upstream."""
    import os
    return os.environ.get("DL4J_TPU_CHECK_NUMERICS", "") in ("1", "true")


def check_numerics(loss, params, step_idx: int):
    import numpy as np
    l = np.asarray(jax.device_get(loss))
    bad = []
    if not np.isfinite(l).all():
        bad.append(f"loss={float(l)}")
    for path, leaf in jax.tree_util.tree_leaves_with_path(
            jax.device_get(params)):
        a = np.asarray(leaf)
        if not np.isfinite(a).all():
            name = "/".join(str(getattr(k, "key", k)) for k in path)
            n_bad = int((~np.isfinite(a)).sum())
            bad.append(f"params[{name}]: {n_bad}/{a.size} non-finite")
    if bad:
        raise FloatingPointError(
            f"Non-finite values after train step {step_idx} "
            f"(DL4J_TPU_CHECK_NUMERICS): " + "; ".join(bad[:8]))


def finite_step_ok(loss, grads, trainable_tree=None):
    """Scalar bool tracer: True iff the loss and every (trainable)
    gradient leaf are finite.  Exact per-leaf ``isfinite`` — a sum
    probe can overflow on large finite trees and false-positive;
    FROZEN leaves (``trainable_tree`` mask 0) are excluded — their
    grads are zeroed downstream and must not veto the step."""
    ok = jnp.isfinite(loss)
    mask_leaves = (jax.tree_util.tree_leaves(trainable_tree)
                   if trainable_tree is not None else None)
    for i, g in enumerate(jax.tree_util.tree_leaves(grads)):
        if mask_leaves is not None:
            g = jnp.where(mask_leaves[i] > 0, g, jnp.zeros_like(g))
        ok = ok & jnp.isfinite(g).all()
    return ok


def apply_updates_if(ok, params, updates, lr_scale):
    """``params - updates * lr_scale`` where ``ok``, else the old
    params.  ``lr_scale`` is the bad-step policy's backoff multiplier
    (cast per-leaf: bf16 updates stay bf16); ``jnp.where`` — not a
    multiply — skips the bad step, since ``0 * NaN`` would smear NaN
    into the params."""
    return jax.tree_util.tree_map(
        lambda p, u: jnp.where(ok, p - (u * lr_scale).astype(u.dtype),
                               p), params, updates)


def select_step(ok, new_tree, old_tree):
    """Per-leaf select between the post-step and pre-step tree (same
    structure required) — how optimizer/model state sits out a
    non-finite step."""
    return jax.tree_util.tree_map(
        lambda new, old: jnp.where(ok, new, old), new_tree, old_tree)


def normalize_gradients(grads, kind: Optional[str], threshold: float):
    """DL4J ``GradientNormalization`` semantics
    (``org.deeplearning4j.nn.conf.GradientNormalization``)."""
    if not kind or kind == "none":
        return grads
    if kind == "clip_element_wise_absolute_value":
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, -threshold, threshold), grads)
    if kind == "clip_l2_per_layer":
        def clip(g):
            n = jnp.linalg.norm(g.reshape(-1))
            return g * jnp.minimum(1.0, threshold / (n + 1e-12))
        return jax.tree_util.tree_map(clip, grads)
    if kind == "renormalize_l2_per_layer":
        return jax.tree_util.tree_map(
            lambda g: g / (jnp.linalg.norm(g.reshape(-1)) + 1e-12), grads)
    if kind == "clip_l2_per_param_type":
        # DL4J ClipL2PerParamType: one clip per parameter TYPE (all the
        # W's together, all the b's together, ...) across layers.
        leaves_with_path = jax.tree_util.tree_leaves_with_path(grads)
        norms = {}
        for path, leaf in leaves_with_path:
            ptype = str(path[-1])
            norms[ptype] = norms.get(ptype, 0.0) + jnp.sum(jnp.square(leaf))

        def clip_by_type(path, g):
            n = jnp.sqrt(norms[str(path[-1])])
            return g * jnp.minimum(1.0, threshold / (n + 1e-12))

        return jax.tree_util.tree_map_with_path(clip_by_type, grads)
    if kind == "clip_global_norm":
        leaves = jax.tree_util.tree_leaves(grads)
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
        scale = jnp.minimum(1.0, threshold / (gn + 1e-12))
        return jax.tree_util.tree_map(lambda g: g * scale, grads)
    raise ValueError(f"Unknown gradient normalization {kind!r}")


class Solver:
    """Owns the compiled step for one model.

    `score_fn(params, model_state, batch, rng, training) ->
    (loss, new_model_state)` is supplied by the network class; `batch` is a
    dict with 'features', 'labels', optional masks.
    """

    def __init__(
        self,
        score_fn: Callable,
        updater: BaseUpdater,
        grad_normalization: Optional[str] = None,
        grad_norm_threshold: float = 1.0,
        minimize: bool = True,
        decay_tree=None,
        trainable_tree=None,
    ):
        self.score_fn = score_fn
        self.updater = updater
        self.grad_normalization = grad_normalization
        self.grad_norm_threshold = grad_norm_threshold
        self.minimize = minimize
        # decay_tree: pytree of per-leaf weight-decay coefficients matching
        # the params structure (0.0 = no decay).  Applied DECOUPLED
        # (update += lr*wd*param), matching DL4J's WeightDecay
        # regularization (applyLR=true default), distinct from l2 which
        # contributes to the loss.
        self.decay_tree = decay_tree
        # trainable_tree: pytree of 1.0/0.0 masks matching params —
        # 0.0 leaves are FROZEN (DL4J FrozenLayer/TransferLearning's
        # setFeatureExtractor): their update is zeroed after decay, so
        # the parameter value never moves.
        self.trainable_tree = trainable_tree
        self._step = jax.jit(self._step_impl, donate_argnums=(0, 1, 2))

    def init_opt_state(self, params):
        return self.updater.init_state(params)

    def _step_impl(self, params, opt_state, model_state, step_idx, batch,
                   rng, lr_scale):
        def loss_of(p):
            loss, new_state = self.score_fn(p, model_state, batch, rng, True)
            return (loss if self.minimize else -loss), new_state

        (loss, new_model_state), grads = jax.value_and_grad(
            loss_of, has_aux=True)(params)
        if not self.minimize:
            loss = -loss  # report the true (maximized) score, not -score
        # Bad-step guard (resilience layer): a non-finite loss or any
        # non-finite gradient must not move params / optimizer state /
        # model state — the loss is still RETURNED non-finite so the
        # host-side BadStepPolicy sees it and applies LR backoff or
        # rollback.  The reduction costs nothing next to the backward.
        ok = finite_step_ok(loss, grads, self.trainable_tree)
        if self.trainable_tree is not None:
            # zero frozen grads BEFORE normalization and the updater:
            # they must not inflate clip_global_norm or accumulate
            # momentum/Adam state (DL4J FrozenLayer contributes no
            # gradients at all)
            grads = jax.tree_util.tree_map(
                lambda g, m: g * m, grads, self.trainable_tree)
        grads = normalize_gradients(
            grads, self.grad_normalization, self.grad_norm_threshold)
        old_opt_state = opt_state
        updates, opt_state = self.updater.update(grads, opt_state, params, step_idx)
        if self.decay_tree is not None:
            lr = self.updater.lr_at(step_idx)
            updates = jax.tree_util.tree_map(
                lambda u, p, wd: u + lr * wd * p, updates, params,
                self.decay_tree)
        if self.trainable_tree is not None:
            # updates masked too: weight decay and bias-correction terms
            # must not move frozen leaves either
            updates = jax.tree_util.tree_map(
                lambda u, m: u * m, updates, self.trainable_tree)
        params = apply_updates_if(ok, params, updates, lr_scale)
        opt_state = self.updater.finalize(opt_state, params)
        opt_state = select_step(ok, opt_state, old_opt_state)
        # model state (batchnorm stats, rnn carry) keeps its old value
        # on a bad step too — but only when the structures line up: an
        # RNN's first chunk GROWS the state tree (empty -> carry), and
        # that structural change must go through regardless (the carry
        # of a skipped step is cleared at the next batch boundary).
        if jax.tree_util.tree_structure(new_model_state) == \
                jax.tree_util.tree_structure(model_state):
            new_model_state = select_step(ok, new_model_state,
                                          model_state)
        return params, opt_state, new_model_state, loss

    def step(self, params, opt_state, model_state, step_idx, batch, rng,
             lr_scale: float = 1.0):
        """One optimization iteration; returns (params, opt_state,
        model_state, loss).  Donated inputs must not be reused by caller.
        ``lr_scale`` multiplies the final update (BadStepPolicy backoff);
        passed traced, so changing it does not recompile."""
        # use-after-donate ledger (DL4J_TPU_SANITIZE=donation): the
        # step donates all three trees — a caller that re-reads an old
        # tree instead of the returned one trips here, not as silent
        # garbage.  Off: one frozenset lookup.  Ledger-marked BEFORE
        # the dispatch (a host-side weakref record, not a buffer read
        # — JIT105): a failed dispatch may have consumed the donated
        # buffers anyway, so the conservative marking stands.
        _sanitize.check_not_donated("solver/step", params, opt_state,
                                    model_state)
        _sanitize.mark_donated("solver/step", params, opt_state,
                               model_state)
        out = self._step(params, opt_state, model_state,
                         jnp.asarray(step_idx, jnp.int32), batch, rng,
                         float(lr_scale))
        if check_numerics_enabled():
            check_numerics(out[3], out[0], int(step_idx))
        return out
