"""RecordReader → DataSet bridge
(``org.deeplearning4j.datasets.datavec.RecordReaderDataSetIterator`` and
``SequenceRecordReaderDataSetIterator``).

Records batch into ONE contiguous numpy array per slot (features, one-hot
or regression labels) so the trainer performs a single sharded device_put
per batch; wrap in ``AsyncDataSetIterator`` for the prefetch thread.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterator import DataSetIterator
from deeplearning4j_tpu.datavec.records import RecordReader


def _one_hot(idx, n):
    out = np.zeros((len(idx), n), np.float32)
    out[np.arange(len(idx)), np.asarray(idx, np.int64)] = 1.0
    return out


class RecordReaderDataSetIterator(DataSetIterator):
    """(reader, batch_size, label_index, n_classes) — DL4J's main ETL
    bridge.  ``label_index=-1`` means the LAST column; ``n_classes=None``
    means regression (label kept as float, no one-hot).  Records whose
    first value is an ndarray (ImageRecordReader) stack it as features."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, n_classes: Optional[int] = None,
                 transform_process=None):
        self.reader = reader
        self._batch = batch_size
        self.label_index = label_index
        self.n_classes = n_classes
        self.tp = transform_process

    def batch_size(self):
        return self._batch

    def total_outcomes(self):
        return self.n_classes

    def _records(self):
        recs = iter(self.reader)
        if self.tp is not None:
            recs = iter(self.tp.execute(recs))
        return recs

    def _to_dataset(self, rows: List[List]) -> DataSet:
        first = rows[0]
        if isinstance(first[0], np.ndarray) and first[0].ndim >= 2:
            # image records: [array, label]
            feats = np.stack([r[0] for r in rows]).astype(np.float32)
            labs = [r[1] for r in rows]
        else:
            li = self.label_index if self.label_index >= 0 \
                else len(first) + self.label_index
            feats = np.asarray(
                [[v for i, v in enumerate(r) if i != li] for r in rows],
                np.float32)
            labs = [r[li] for r in rows]
        if self.n_classes is not None:
            labels = _one_hot([int(l) for l in labs], self.n_classes)
        else:
            labels = np.asarray(labs, np.float32)
            if labels.ndim == 1:
                labels = labels[:, None]
        return DataSet(feats, labels)

    def __iter__(self):
        rows: List[List] = []
        for rec in self._records():
            rows.append(rec)
            if len(rows) == self._batch:
                yield self._maybe_preprocess(self._to_dataset(rows))
                rows = []
        if rows:
            yield self._maybe_preprocess(self._to_dataset(rows))

    def reset(self):
        self.reader.reset()


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Sequence reader → [b, t, f] DataSet with per-timestep one-hot
    labels and padding masks for ragged lengths (DL4J's ALIGN_END
    simplification: we align START and mask the tail)."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, n_classes: Optional[int] = None):
        self.reader = reader
        self._batch = batch_size
        self.label_index = label_index
        self.n_classes = n_classes

    def _to_dataset(self, seqs: List[List[List]]) -> DataSet:
        li_of = lambda row: (self.label_index if self.label_index >= 0
                             else len(row) + self.label_index)
        t_max = max(len(s) for s in seqs)
        n_feat = len(seqs[0][0]) - 1
        b = len(seqs)
        feats = np.zeros((b, t_max, n_feat), np.float32)
        mask = np.zeros((b, t_max), np.float32)
        if self.n_classes is not None:
            labels = np.zeros((b, t_max, self.n_classes), np.float32)
        else:
            labels = np.zeros((b, t_max, 1), np.float32)
        for bi, seq in enumerate(seqs):
            for ti, row in enumerate(seq):
                li = li_of(row)
                feats[bi, ti] = [v for i, v in enumerate(row) if i != li]
                mask[bi, ti] = 1.0
                if self.n_classes is not None:
                    labels[bi, ti, int(row[li])] = 1.0
                else:
                    labels[bi, ti, 0] = float(row[li])
        return DataSet(feats, labels, features_mask=mask, labels_mask=mask)

    def __iter__(self):
        seqs: List[List[List]] = []
        for seq in self.reader:
            seqs.append(seq)
            if len(seqs) == self._batch:
                yield self._maybe_preprocess(self._to_dataset(seqs))
                seqs = []
        if seqs:
            yield self._maybe_preprocess(self._to_dataset(seqs))

    def reset(self):
        self.reader.reset()
