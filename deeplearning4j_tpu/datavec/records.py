"""Record readers (``org.datavec.api.records.reader.RecordReader`` and the
impls in ``org.datavec.api.records.reader.impl.**``: CSVRecordReader,
LineRecordReader, CollectionRecordReader, CSVSequenceRecordReader).

A record is a list of values (strings/numbers); a sequence record is a
list of records.  Readers are plain Python iterators — DL4J's
InputSplit/Configuration plumbing collapses to constructor args.
"""
from __future__ import annotations

import csv
import io
import os
from typing import Iterable, Iterator, List, Optional, Sequence


class RecordReader:
    """Iterable over records, resettable (``RecordReader.next/hasNext/
    reset``)."""

    def __iter__(self) -> Iterator[List]:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class LineRecordReader(RecordReader):
    """One record per line (``impl.LineRecordReader``)."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self):
        with open(self.path) as f:
            for line in f:
                yield [line.rstrip("\n")]


class CSVRecordReader(RecordReader):
    """CSV rows as records (``impl.csv.CSVRecordReader``): optional
    skipped header lines and custom delimiter, numeric auto-parsing."""

    def __init__(self, path: Optional[str] = None, skip_lines: int = 0,
                 delimiter: str = ",", text: Optional[str] = None,
                 parse_numbers: bool = True):
        if (path is None) == (text is None):
            raise ValueError("Give exactly one of path= or text=")
        self.path, self.text = path, text
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self.parse_numbers = parse_numbers

    @staticmethod
    def _parse(v: str):
        try:
            return int(v)
        except ValueError:
            try:
                return float(v)
            except ValueError:
                return v

    def __iter__(self):
        f = open(self.path) if self.path else io.StringIO(self.text)
        try:
            rd = csv.reader(f, delimiter=self.delimiter)
            for i, row in enumerate(rd):
                if i < self.skip_lines or not row:
                    continue
                yield ([self._parse(v) for v in row] if self.parse_numbers
                       else list(row))
        finally:
            f.close()


class CollectionRecordReader(RecordReader):
    """In-memory records (``impl.collection.CollectionRecordReader``) —
    the fixture/mock reader the reference test suites lean on."""

    def __init__(self, records: Sequence[Sequence]):
        self.records = [list(r) for r in records]

    def __iter__(self):
        return iter(self.records)


class CSVSequenceRecordReader(RecordReader):
    """One sequence per FILE of CSV rows
    (``impl.csv.CSVSequenceRecordReader``): yields [timesteps][columns]."""

    def __init__(self, paths: Sequence[str], skip_lines: int = 0,
                 delimiter: str = ","):
        self.paths = list(paths)
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def __iter__(self):
        for p in self.paths:
            rd = CSVRecordReader(p, self.skip_lines, self.delimiter)
            yield list(rd)
