"""Column schema (``org.datavec.api.transform.schema.Schema``): named,
typed columns with a fluent builder; TransformProcess validates against
and rewrites it."""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

COLUMN_TYPES = ("double", "integer", "long", "categorical", "string",
                "time", "bytes")


@dataclasses.dataclass
class ColumnMeta:
    name: str
    col_type: str
    categories: Optional[List[str]] = None  # for categorical


class Schema:
    def __init__(self, columns: Optional[List[ColumnMeta]] = None):
        self.columns: List[ColumnMeta] = columns or []

    # -- fluent builder (Schema.Builder.addColumn*) --
    class Builder:
        def __init__(self):
            self._cols: List[ColumnMeta] = []

        def add_column_double(self, *names):
            for n in names:
                self._cols.append(ColumnMeta(n, "double"))
            return self

        def add_column_integer(self, *names):
            for n in names:
                self._cols.append(ColumnMeta(n, "integer"))
            return self

        def add_column_string(self, *names):
            for n in names:
                self._cols.append(ColumnMeta(n, "string"))
            return self

        def add_column_categorical(self, name, categories: Sequence[str]):
            self._cols.append(ColumnMeta(name, "categorical",
                                         list(categories)))
            return self

        def build(self) -> "Schema":
            return Schema(self._cols)

    @staticmethod
    def builder() -> "Schema.Builder":
        return Schema.Builder()

    def names(self) -> List[str]:
        return [c.name for c in self.columns]

    def index_of(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(f"No column {name!r}; have {self.names()}")

    def column(self, name: str) -> ColumnMeta:
        return self.columns[self.index_of(name)]

    def to_dict(self) -> dict:
        return {"columns": [dataclasses.asdict(c) for c in self.columns]}

    @staticmethod
    def from_dict(d: dict) -> "Schema":
        return Schema([ColumnMeta(**c) for c in d["columns"]])
