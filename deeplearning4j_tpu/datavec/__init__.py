"""DataVec-equivalent ETL: record readers, schema transforms, image pipeline.

Reference: ``datavec/datavec-api org.datavec.api.**`` (RecordReader zoo,
``TransformProcess`` schema-based column transforms) and
``datavec-data-image org.datavec.image.recordreader.ImageRecordReader``
(JavaCV native decode).  TPU-first shape: everything here is HOST-side
numpy ETL feeding the device via the async-prefetch iterator; decoded
batches are handed to jax as one contiguous array per batch (one
device_put, sharded by the trainer), never element-wise.
"""
from deeplearning4j_tpu.datavec.records import (
    CollectionRecordReader, CSVRecordReader, CSVSequenceRecordReader,
    LineRecordReader, RecordReader)
from deeplearning4j_tpu.datavec.schema import Schema
from deeplearning4j_tpu.datavec.transform import TransformProcess
from deeplearning4j_tpu.datavec.image import ImageRecordReader
from deeplearning4j_tpu.datavec.iterator import (
    RecordReaderDataSetIterator, SequenceRecordReaderDataSetIterator)

__all__ = [
    "RecordReader", "CSVRecordReader", "CSVSequenceRecordReader",
    "LineRecordReader", "CollectionRecordReader", "Schema",
    "TransformProcess", "ImageRecordReader", "RecordReaderDataSetIterator",
    "SequenceRecordReaderDataSetIterator",
]
