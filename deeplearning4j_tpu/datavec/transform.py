"""TransformProcess (``org.datavec.api.transform.TransformProcess``):
an ordered, serializable list of schema-aware column transforms applied
record-by-record on the host.

Implemented transform subset (the ones the reference examples lean on):
remove/keep columns, categorical→integer, categorical→one-hot,
integer→categorical, double math ops, min-max normalize, string map,
filter rows, conditional replace.
"""
from __future__ import annotations

import dataclasses
import json
import math
from typing import Any, Callable, Dict, List, Optional, Sequence

from deeplearning4j_tpu.datavec.schema import ColumnMeta, Schema

_MATH_OPS = {
    "add": lambda a, b: a + b, "subtract": lambda a, b: a - b,
    "multiply": lambda a, b: a * b, "divide": lambda a, b: a / b,
    "modulus": lambda a, b: a % b, "reverse_subtract": lambda a, b: b - a,
    "reverse_divide": lambda a, b: b / a, "scalar_max": max,
    "scalar_min": min,
}


@dataclasses.dataclass
class _Step:
    kind: str
    args: Dict[str, Any]


class TransformProcess:
    """Built fluently against an input Schema; ``execute`` maps records,
    ``final_schema`` reports the output schema; JSON round-trips."""

    def __init__(self, initial_schema: Schema,
                 steps: Optional[List[_Step]] = None):
        self.initial_schema = initial_schema
        self.steps = steps or []

    class Builder:
        def __init__(self, schema: Schema):
            self._tp = TransformProcess(schema)

        def _add(self, kind, **args):
            self._tp.steps.append(_Step(kind, args))
            return self

        def remove_columns(self, *names):
            return self._add("remove_columns", names=list(names))

        def keep_columns(self, *names):
            return self._add("keep_columns", names=list(names))

        def categorical_to_integer(self, *names):
            return self._add("categorical_to_integer", names=list(names))

        def categorical_to_one_hot(self, *names):
            return self._add("categorical_to_one_hot", names=list(names))

        def integer_to_categorical(self, name, categories):
            return self._add("integer_to_categorical", name=name,
                             categories=list(categories))

        def double_math_op(self, name, op, scalar):
            if op not in _MATH_OPS:
                raise ValueError(f"Unknown math op {op!r}")
            return self._add("double_math_op", name=name, op=op,
                             scalar=scalar)

        def normalize_min_max(self, name, min_val, max_val):
            return self._add("normalize_min_max", name=name,
                             min=min_val, max=max_val)

        def string_map(self, name, mapping: Dict[str, str]):
            return self._add("string_map", name=name, mapping=dict(mapping))

        def filter_invalid(self, *names):
            """Drop records with NaN/None/empty in the named columns."""
            return self._add("filter_invalid", names=list(names))

        def replace_less_than(self, name, threshold, replacement):
            return self._add("replace_less_than", name=name,
                             threshold=threshold, replacement=replacement)

        def build(self) -> "TransformProcess":
            self._tp.final_schema()  # validate the chain eagerly
            return self._tp

    @staticmethod
    def builder(schema: Schema) -> "TransformProcess.Builder":
        return TransformProcess.Builder(schema)

    # ------------------------------------------------------------------
    def _apply_schema(self, schema: Schema, step: _Step) -> Schema:
        cols = list(schema.columns)
        k, a = step.kind, step.args
        if k in ("remove_columns", "keep_columns"):
            for n in a["names"]:
                schema.index_of(n)  # KeyError on unknown column
            if k == "remove_columns":
                return Schema([c for c in cols if c.name not in a["names"]])
            return Schema([c for c in cols if c.name in a["names"]])
        if k == "categorical_to_integer":
            out = []
            for c in cols:
                if c.name in a["names"]:
                    if c.col_type != "categorical":
                        raise ValueError(f"{c.name} is not categorical")
                    out.append(ColumnMeta(c.name, "integer"))
                else:
                    out.append(c)
            return Schema(out)
        if k == "categorical_to_one_hot":
            out = []
            for c in cols:
                if c.name in a["names"]:
                    if c.col_type != "categorical":
                        raise ValueError(f"{c.name} is not categorical")
                    out.extend(ColumnMeta(f"{c.name}[{cat}]", "double")
                               for cat in c.categories)
                else:
                    out.append(c)
            return Schema(out)
        if k == "integer_to_categorical":
            return Schema([ColumnMeta(c.name, "categorical",
                                      list(a["categories"]))
                           if c.name == a["name"] else c for c in cols])
        if k in ("double_math_op", "normalize_min_max",
                 "replace_less_than"):
            schema.index_of(a["name"])
            return schema
        if k in ("string_map", "filter_invalid"):
            for n in (a.get("names") or [a.get("name")]):
                schema.index_of(n)
            return schema
        raise ValueError(f"Unknown step kind {k!r}")

    def final_schema(self) -> Schema:
        s = self.initial_schema
        for step in self.steps:
            s = self._apply_schema(s, step)
        return s

    # ------------------------------------------------------------------
    def _apply_record(self, schema: Schema, step: _Step, rec: List):
        k, a = step.kind, step.args
        if k == "remove_columns":
            keep = [i for i, c in enumerate(schema.columns)
                    if c.name not in a["names"]]
            return [rec[i] for i in keep]
        if k == "keep_columns":
            keep = [i for i, c in enumerate(schema.columns)
                    if c.name in a["names"]]
            return [rec[i] for i in keep]
        if k == "categorical_to_integer":
            rec = list(rec)
            for n in a["names"]:
                i = schema.index_of(n)
                cats = schema.columns[i].categories
                try:
                    rec[i] = cats.index(str(rec[i]))
                except ValueError:
                    raise ValueError(
                        f"Value {rec[i]!r} not in categories of {n}: {cats}")
            return rec
        if k == "categorical_to_one_hot":
            out = []
            for i, c in enumerate(schema.columns):
                if c.name in a["names"]:
                    hot = [0.0] * len(c.categories)
                    hot[c.categories.index(str(rec[i]))] = 1.0
                    out.extend(hot)
                else:
                    out.append(rec[i])
            return out
        if k == "integer_to_categorical":
            i = schema.index_of(a["name"])
            rec = list(rec)
            rec[i] = a["categories"][int(rec[i])]
            return rec
        if k == "double_math_op":
            i = schema.index_of(a["name"])
            rec = list(rec)
            rec[i] = _MATH_OPS[a["op"]](float(rec[i]), a["scalar"])
            return rec
        if k == "normalize_min_max":
            i = schema.index_of(a["name"])
            rec = list(rec)
            rng = a["max"] - a["min"]
            rec[i] = (float(rec[i]) - a["min"]) / (rng or 1.0)
            return rec
        if k == "string_map":
            i = schema.index_of(a["name"])
            rec = list(rec)
            rec[i] = a["mapping"].get(str(rec[i]), rec[i])
            return rec
        if k == "filter_invalid":
            for n in a["names"]:
                v = rec[schema.index_of(n)]
                if v is None or v == "" or (
                        isinstance(v, float) and math.isnan(v)):
                    return None
            return rec
        if k == "replace_less_than":
            i = schema.index_of(a["name"])
            rec = list(rec)
            if float(rec[i]) < a["threshold"]:
                rec[i] = a["replacement"]
            return rec
        raise ValueError(f"Unknown step kind {k!r}")

    def execute(self, records) -> List[List]:
        """Apply all steps to an iterable of records (drops filtered)."""
        # Schemas are record-independent: compute the per-step input
        # schema chain once, not once per record.
        schemas = [self.initial_schema]
        for step in self.steps:
            schemas.append(self._apply_schema(schemas[-1], step))
        out = []
        for rec in records:
            cur: Optional[List] = list(rec)
            for schema, step in zip(schemas, self.steps):
                cur = self._apply_record(schema, step, cur)
                if cur is None:
                    break
            if cur is not None:
                out.append(cur)
        return out

    # ------------------------------------------------------------------
    def to_json(self) -> str:
        return json.dumps({
            "initial_schema": self.initial_schema.to_dict(),
            "steps": [{"kind": s.kind, "args": s.args} for s in self.steps],
        })

    @staticmethod
    def from_json(s: str) -> "TransformProcess":
        d = json.loads(s)
        return TransformProcess(
            Schema.from_dict(d["initial_schema"]),
            [_Step(x["kind"], x["args"]) for x in d["steps"]])
