"""Image pipeline (``org.datavec.image.recordreader.ImageRecordReader`` +
``NativeImageLoader``'s JavaCV decode).

Host-side decode→resize→scale with OpenCV (already native C++ SIMD — the
JavaCV indirection the reference needed does not exist here), directory
name = label (DL4J ``ParentPathLabelGenerator``), NHWC float32 output.
Batches assemble into ONE contiguous array so the device sees a single
transfer; async prefetch overlaps the whole thing with device compute
(wrap the iterator — ``AsyncDataSetIterator`` — exactly as DL4J does).
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datavec.records import RecordReader

_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".ppm")


def _decode(path: str, h: int, w: int, channels: int) -> np.ndarray:
    import cv2
    flag = cv2.IMREAD_COLOR if channels == 3 else cv2.IMREAD_GRAYSCALE
    img = cv2.imread(path, flag)
    if img is None:
        raise IOError(f"Cannot decode image {path!r}")
    if (img.shape[0], img.shape[1]) != (h, w):
        img = cv2.resize(img, (w, h), interpolation=cv2.INTER_LINEAR)
    if channels == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    else:
        img = img[..., None]
    return img


def _decode_f32(args):
    """Module-level (picklable) worker for the process pool."""
    path, h, w, c = args
    return _decode(path, h, w, c).astype(np.float32)


class ImageRecordReader(RecordReader):
    """Yields ``[image_hwc_float32, label_index]`` records from a
    directory tree ``root/<label>/<file>`` (ParentPathLabelGenerator) or
    an explicit (paths, labels) list."""

    def __init__(self, height: int, width: int, channels: int = 3,
                 root: Optional[str] = None,
                 paths: Optional[Sequence[str]] = None,
                 labels: Optional[Sequence[int]] = None,
                 label_names: Optional[List[str]] = None,
                 shuffle_seed: Optional[int] = None,
                 n_workers: int = 0):
        """``n_workers > 0`` decodes via a PROCESS pool — thread-based
        prefetch cannot scale Python-side decode past the GIL (measured:
        in-fit decode throughput drops ~4x under dispatch contention);
        per-image decode is embarrassingly parallel across cores."""
        self.h, self.w, self.c = height, width, channels
        self.n_workers = int(n_workers)
        if root is not None:
            self.label_names = sorted(
                d for d in os.listdir(root)
                if os.path.isdir(os.path.join(root, d)))
            self.paths, self.labels = [], []
            for li, lab in enumerate(self.label_names):
                d = os.path.join(root, lab)
                for f in sorted(os.listdir(d)):
                    if f.lower().endswith(_EXTS):
                        self.paths.append(os.path.join(d, f))
                        self.labels.append(li)
        elif paths is not None:
            self.paths = list(paths)
            self.labels = list(labels) if labels is not None else [0] * len(self.paths)
            self.label_names = label_names or sorted(
                {str(l) for l in self.labels})
        else:
            raise ValueError("Give root= or paths=")
        if shuffle_seed is not None:
            rng = np.random.default_rng(shuffle_seed)
            order = rng.permutation(len(self.paths))
            self.paths = [self.paths[i] for i in order]
            self.labels = [self.labels[i] for i in order]

    def n_labels(self) -> int:
        return len(self.label_names)

    def __len__(self):
        return len(self.paths)

    def __iter__(self):
        if self.n_workers > 0:
            import multiprocessing as mp
            # spawn, NOT fork: __iter__ runs inside the async prefetch
            # thread while the main thread's JAX runtime holds internal
            # locks — a fork()ed child can inherit a locked mutex and
            # hang pool startup.  Worker + args are picklable by design.
            ctx = mp.get_context("spawn")
            with ctx.Pool(self.n_workers) as pool:
                args = [(p, self.h, self.w, self.c) for p in self.paths]
                for img, lab in zip(
                        pool.imap(_decode_f32, args, chunksize=16),
                        self.labels):
                    yield [img, lab]
            return
        for p, lab in zip(self.paths, self.labels):
            img = _decode(p, self.h, self.w, self.c).astype(np.float32)
            yield [img, lab]
