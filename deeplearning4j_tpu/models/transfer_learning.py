"""Transfer learning (``org.deeplearning4j.nn.transferlearning
.TransferLearning`` + ``FrozenLayer`` [UNVERIFIED]): take a trained
``MultiLayerNetwork``, freeze a feature-extractor prefix, replace /
remove / append head layers, and fine-tune under a new training
configuration — the workflow the reference's zoo-pretrained examples
are built around.

TPU-first mechanics: freezing is a 0/1 mask pytree that zeroes frozen
grads BEFORE normalization/updater and masks updates after (one fused
op, no per-layer Java ``FrozenLayer`` wrappers); the frozen-layer list
persists in the serialized conf so a reloaded fine-tune keeps its
freeze.  Retained parameters are deep-copied — the jitted step donates
its buffers, so reference sharing would delete the source model's
arrays on the first fit.
"""
from __future__ import annotations

import copy
import dataclasses
from typing import List, Optional

import jax

from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf.builder import NeuralNetConfiguration
from deeplearning4j_tpu.optimize.updaters import BaseUpdater


class TransferLearning:
    """Namespace matching upstream: ``TransferLearning.Builder(model)``."""

    class Builder:
        def __init__(self, model: MultiLayerNetwork):
            model._check_init()
            self._src = model
            self._layers: List = [copy.deepcopy(ly)
                                  for ly in model.layers]
            # which source layer each new slot copies params from
            self._param_src: List[Optional[int]] = list(
                range(len(self._layers)))
            self._freeze_upto = -1
            self._global_overrides = {}

        # -- upstream builder surface ---------------------------------
        def fine_tune_configuration(self, updater=None, l2=None,
                                    seed=None):
            """New training hyperparameters for the fine-tune phase
            (upstream ``FineTuneConfiguration``)."""
            if updater is not None:
                self._global_overrides["updater"] = (
                    updater.to_dict() if isinstance(updater, BaseUpdater)
                    else dict(updater))
            if l2 is not None:
                self._global_overrides["l2"] = float(l2)
                # copied layers carry the SOURCE build's resolved l2;
                # reset so the new global value re-resolves onto them
                for ly in self._layers:
                    if hasattr(ly, "l2"):
                        ly.l2 = None
            if seed is not None:
                self._global_overrides["seed"] = int(seed)
            return self

        def set_feature_extractor(self, layer_idx: int):
            """Freeze layers [0..layer_idx] (inclusive) — they forward
            but never update (upstream ``setFeatureExtractor``)."""
            self._freeze_upto = int(layer_idx)
            return self

        def n_out_replace(self, layer_idx: int, n_out: int):
            """Change layer ``layer_idx``'s output width; that layer
            AND the next layer re-initialize (their shapes change) —
            upstream ``nOutReplace`` semantics."""
            i = int(layer_idx)
            ly = self._layers[i]
            if not hasattr(ly, "n_out"):
                raise ValueError(
                    f"layer {i} ({type(ly).__name__}) has no n_out")
            ly.n_out = int(n_out)
            self._param_src[i] = None
            # Downstream: reset resolved n_in so the rebuild re-infers
            # shapes, through any non-parameterized layers (pooling /
            # activation); the FIRST parameterized consumer is the one
            # whose weights change shape and must re-initialize.
            for j in range(i + 1, len(self._layers)):
                nxt = self._layers[j]
                if hasattr(nxt, "n_in"):
                    nxt.n_in = None
                if nxt.has_params():
                    self._param_src[j] = None
                    break
            return self

        def remove_output_layer_and_processing(self):
            """Drop the last layer (upstream
            ``removeOutputLayerAndProcessing``)."""
            self._layers.pop()
            self._param_src.pop()
            return self

        def remove_layers_from_output(self, n: int):
            for _ in range(int(n)):
                self.remove_output_layer_and_processing()
            return self

        def add_layer(self, layer_conf):
            """Append a fresh (randomly initialized) layer."""
            self._layers.append(layer_conf)
            self._param_src.append(None)
            return self

        # -- build ----------------------------------------------------
        def build(self) -> MultiLayerNetwork:
            if self._freeze_upto >= len(self._layers):
                raise ValueError(
                    f"set_feature_extractor({self._freeze_upto}) is out "
                    f"of range for {len(self._layers)} layers")
            for i in range(self._freeze_upto + 1):
                if self._param_src[i] is None and \
                        self._layers[i].has_params():
                    raise ValueError(
                        f"layer {i} is frozen but replaced/added — a "
                        "fresh random layer inside the feature "
                        "extractor would never train; lower "
                        "set_feature_extractor or move the change "
                        "past it")
            src = self._src
            g = dataclasses.replace(src.conf.global_conf,
                                    **self._global_overrides)
            b = NeuralNetConfiguration.builder()
            b._g = g
            lst = b.list()
            if src.conf.input_type is not None:
                lst.set_input_type(src.conf.input_type)
            if src.conf.backprop_type != "standard":
                lst.backprop_type(src.conf.backprop_type,
                                  src.conf.tbptt_fwd_length,
                                  src.conf.tbptt_bwd_length)
            for ly in self._layers:
                lst.layer(ly)
            model = MultiLayerNetwork(lst.build()).init()

            # COPY retained parameters: the solver's jitted step
            # DONATES its buffers, so sharing arrays by reference would
            # delete the source model's params on the first ft.fit()
            import jax.numpy as jnp
            for i, src_i in enumerate(self._param_src):
                if src_i is None:
                    continue
                model.params_tree[f"layer_{i}"] = jax.tree_util.tree_map(
                    jnp.array, src.params_tree[f"layer_{src_i}"])
                model.state_tree[f"layer_{i}"] = jax.tree_util.tree_map(
                    jnp.array, src.state_tree[f"layer_{src_i}"])

            if self._freeze_upto >= 0:
                # persisted in the conf: save/load keeps the freeze
                model.conf.frozen_layers = list(
                    range(self._freeze_upto + 1))
            return model


def frozen_layer_indices(model: MultiLayerNetwork) -> List[int]:
    """Which layers are frozen (from the persisted conf)."""
    return sorted(getattr(model.conf, "frozen_layers", ()) or ())


def freeze_graph_layers(graph, layer_names) -> None:
    """ComputationGraph freezing (the ``TransferLearning.GraphBuilder``
    ``setFeatureExtractor`` essential): mark the named layer vertices
    frozen — persisted in the graph conf, applied as the same update
    mask the MLN path uses.  Call before the first fit (or rebuild the
    solver) so the mask reaches the compiled step."""
    names = [layer_names] if isinstance(layer_names, str) \
        else list(layer_names)
    known = set(graph.params_tree)
    missing = [n for n in names if n not in known]
    if missing:
        raise ValueError(
            f"unknown layer vertices {missing}; parameterized vertices: "
            f"{sorted(known)}")
    graph.conf.frozen_layers = sorted(set(
        list(getattr(graph.conf, "frozen_layers", []) or []) + names))
    graph._solver = None            # rebuild with the new mask


def _graph_ancestors(vertex_inputs, names, network_inputs):
    """Closure of ``names`` under the input relation (excluding the
    network inputs themselves)."""
    seen, stack = set(), list(names)
    ins = set(network_inputs)
    while stack:
        n = stack.pop()
        if n in seen or n in ins:
            continue
        seen.add(n)
        stack.extend(vertex_inputs.get(n, ()))
    return seen


class GraphBuilder:
    """``TransferLearning.GraphBuilder`` for :class:`ComputationGraph`
    (upstream ``org.deeplearning4j.nn.transferlearning.TransferLearning
    .GraphBuilder`` [UNVERIFIED]): vertex-addressed freeze,
    ``n_out_replace`` on a DAG layer, remove/add vertices, new outputs,
    fine-tune config — same param-copy + 0/1-mask mechanics as the MLN
    builder (no wrapper layers; the mask reaches the jitted step)."""

    def __init__(self, graph):
        graph._check_init()
        self._src = graph
        c = graph.conf
        self._vertices = {n: dataclasses.replace(
            s, layer=copy.deepcopy(s.layer),
            vertex=copy.deepcopy(s.vertex), preprocessor=None)
            for n, s in c.vertices.items()}
        self._vertex_inputs = {n: list(v)
                               for n, v in c.vertex_inputs.items()}
        self._inputs = list(c.network_inputs)
        self._outputs = list(c.network_outputs)
        self._input_types = dict(c.input_types)
        # which source vertex each retained vertex copies params from
        self._param_src = {n: n for n in graph.params_tree
                           if graph.params_tree.get(n)}
        self._freeze = set(c.frozen_layers or ())
        self._global_overrides = {}

    # -- upstream builder surface -------------------------------------
    def fine_tune_configuration(self, updater=None, l2=None, seed=None):
        if updater is not None:
            self._global_overrides["updater"] = (
                updater.to_dict() if isinstance(updater, BaseUpdater)
                else dict(updater))
        if l2 is not None:
            self._global_overrides["l2"] = float(l2)
            for s in self._vertices.values():
                if s.layer is not None and hasattr(s.layer, "l2"):
                    s.layer.l2 = None
        if seed is not None:
            self._global_overrides["seed"] = int(seed)
        return self

    def set_feature_extractor(self, *vertex_names):
        """Freeze the named vertices AND everything upstream of them
        (upstream semantics: the sub-DAG up to the named vertex is the
        frozen featurizer)."""
        missing = [n for n in vertex_names if n not in self._vertices]
        if missing:
            raise ValueError(f"unknown vertices {missing}; have "
                             f"{sorted(self._vertices)}")
        closure = _graph_ancestors(self._vertex_inputs, vertex_names,
                                   self._inputs)
        self._freeze |= {n for n in closure if n in self._param_src
                         or (self._vertices[n].layer is not None
                             and self._vertices[n].layer.has_params())}
        return self

    def n_out_replace(self, vertex_name, n_out, seed=None):
        """New output width for a layer vertex: fresh params there and
        in every direct layer consumer (their input widths change —
        upstream nOutReplace's dual re-initialization)."""
        s = self._vertices.get(vertex_name)
        if s is None or s.layer is None:
            raise ValueError(f"{vertex_name!r} is not a layer vertex")
        if not hasattr(s.layer, "n_out"):
            raise ValueError(
                f"{type(s.layer).__name__} has no n_out to replace")
        s.layer.n_out = int(n_out)
        self._param_src.pop(vertex_name, None)
        for cname, ins in self._vertex_inputs.items():
            if vertex_name in ins:
                cs = self._vertices[cname]
                if cs.layer is not None:
                    self._param_src.pop(cname, None)
                    if hasattr(cs.layer, "n_in"):
                        cs.layer.n_in = None   # re-infer from new width
        if seed is not None:
            self._global_overrides["seed"] = int(seed)
        return self

    def remove_vertex_and_connections(self, vertex_name):
        if vertex_name not in self._vertices:
            raise ValueError(f"unknown vertex {vertex_name!r}")
        self._vertices.pop(vertex_name)
        self._vertex_inputs.pop(vertex_name, None)
        for ins in self._vertex_inputs.values():
            while vertex_name in ins:
                ins.remove(vertex_name)
        self._outputs = [o for o in self._outputs if o != vertex_name]
        self._param_src.pop(vertex_name, None)
        self._freeze.discard(vertex_name)
        return self

    def add_layer(self, name, layer_conf, *inputs):
        if name in self._vertices:
            raise ValueError(f"vertex {name!r} already exists")
        from deeplearning4j_tpu.models.computation_graph import VertexSpec
        self._vertices[name] = VertexSpec(layer=layer_conf)
        self._vertex_inputs[name] = list(inputs)
        return self

    def add_vertex(self, name, vertex, *inputs):
        if name in self._vertices:
            raise ValueError(f"vertex {name!r} already exists")
        from deeplearning4j_tpu.models.computation_graph import VertexSpec
        self._vertices[name] = VertexSpec(vertex=vertex)
        self._vertex_inputs[name] = list(inputs)
        return self

    def set_outputs(self, *names):
        self._outputs = list(names)
        return self

    # -- build --------------------------------------------------------
    def build(self):
        from deeplearning4j_tpu.models.computation_graph import (
            ComputationGraph, _topological_order)
        src = self._src
        for n in self._freeze:
            if n not in self._param_src:
                raise ValueError(
                    f"vertex {n!r} is frozen but replaced/removed/"
                    "fresh — a random frozen vertex would never train; "
                    "unfreeze it or keep its source params")
        g = dataclasses.replace(src.conf.global_conf,
                                **self._global_overrides)
        b = NeuralNetConfiguration.builder()
        b._g = g
        b.grad_normalization = src.conf.grad_normalization
        b.grad_norm_threshold = src.conf.grad_norm_threshold
        gb = b.graph()
        gb.add_inputs(*self._inputs)
        if self._input_types:
            gb.set_input_types(*[self._input_types[i]
                                 for i in self._inputs])
        if src.conf.backprop_type != "standard":
            gb.backprop_type(src.conf.backprop_type,
                             src.conf.tbptt_fwd_length,
                             src.conf.tbptt_bwd_length)
        order = _topological_order(self._inputs, self._vertex_inputs)
        for n in order:
            s = self._vertices[n]
            if s.layer is not None:
                gb.add_layer(n, s.layer, *self._vertex_inputs[n])
            else:
                gb.add_vertex(n, s.vertex, *self._vertex_inputs[n])
        gb.set_outputs(*self._outputs)
        model = ComputationGraph(gb.build()).init()

        import jax.numpy as jnp
        for n, src_n in self._param_src.items():
            if n in model.params_tree:
                model.params_tree[n] = jax.tree_util.tree_map(
                    jnp.array, src.params_tree[src_n])
                model.state_tree[n] = jax.tree_util.tree_map(
                    jnp.array, src.state_tree[src_n])
        if self._freeze:
            model.conf.frozen_layers = sorted(self._freeze)
        return model


TransferLearning.GraphBuilder = GraphBuilder


class TransferLearningHelper:
    """Featurizer split (upstream ``TransferLearningHelper``
    [UNVERIFIED]): run the frozen sub-DAG ONCE per dataset and fine-tune
    only the head on the cached activations — the cheap-epochs workflow
    for frozen-base transfer learning."""

    def __init__(self, graph, frozen_boundary: str):
        graph._check_init()
        if frozen_boundary not in graph.conf.vertices:
            raise ValueError(f"unknown vertex {frozen_boundary!r}")
        self._graph = graph
        self._boundary = frozen_boundary

    def featurize(self, features):
        """Activations at the frozen boundary for a [b, ...] batch —
        feed these to the head-only graph as its input features."""
        acts = self._graph.feed_forward(features)
        return acts[self._boundary]


def mln_to_graph(model: MultiLayerNetwork):
    """Convert a (possibly trained) MultiLayerNetwork into the
    equivalent linear ComputationGraph, copying parameters — upstream
    ``MultiLayerNetwork#toComputationGraph`` [UNVERIFIED].  Layer
    vertices are named ``layer_0..layer_{n-1}``; frozen layers carry
    over by name.  The zoo's published weight sets are MLN-based, so
    this is the bridge into the DAG-side TransferLearning builder."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.models.computation_graph import (
        ComputationGraph)
    model._check_init()
    b = NeuralNetConfiguration.builder()
    b._g = copy.deepcopy(model.conf.global_conf)
    gb = b.graph().add_inputs("input")
    if model.conf.input_type is not None:
        gb.set_input_types(model.conf.input_type)
    prev = "input"
    names = []
    for i, ly in enumerate(model.layers):
        name = f"layer_{i}"
        gb.add_layer(name, copy.deepcopy(ly), prev)
        prev = name
        names.append(name)
    if model.conf.backprop_type != "standard":
        gb.backprop_type(model.conf.backprop_type,
                         model.conf.tbptt_fwd_length,
                         model.conf.tbptt_bwd_length)
    graph = ComputationGraph(gb.set_outputs(prev).build()).init()
    for i, name in enumerate(names):
        graph.params_tree[name] = jax.tree_util.tree_map(
            jnp.array, model.params_tree[f"layer_{i}"])
        graph.state_tree[name] = jax.tree_util.tree_map(
            jnp.array, model.state_tree[f"layer_{i}"])
    frozen = sorted(getattr(model.conf, "frozen_layers", ()) or ())
    if frozen:
        graph.conf.frozen_layers = [f"layer_{i}" for i in frozen]
    return graph
