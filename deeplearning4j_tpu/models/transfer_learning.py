"""Transfer learning (``org.deeplearning4j.nn.transferlearning
.TransferLearning`` + ``FrozenLayer`` [UNVERIFIED]): take a trained
``MultiLayerNetwork``, freeze a feature-extractor prefix, replace /
remove / append head layers, and fine-tune under a new training
configuration — the workflow the reference's zoo-pretrained examples
are built around.

TPU-first mechanics: freezing is a 0/1 mask pytree that zeroes frozen
grads BEFORE normalization/updater and masks updates after (one fused
op, no per-layer Java ``FrozenLayer`` wrappers); the frozen-layer list
persists in the serialized conf so a reloaded fine-tune keeps its
freeze.  Retained parameters are deep-copied — the jitted step donates
its buffers, so reference sharing would delete the source model's
arrays on the first fit.
"""
from __future__ import annotations

import copy
import dataclasses
from typing import List, Optional

import jax

from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf.builder import NeuralNetConfiguration
from deeplearning4j_tpu.optimize.updaters import BaseUpdater


class TransferLearning:
    """Namespace matching upstream: ``TransferLearning.Builder(model)``."""

    class Builder:
        def __init__(self, model: MultiLayerNetwork):
            model._check_init()
            self._src = model
            self._layers: List = [copy.deepcopy(ly)
                                  for ly in model.layers]
            # which source layer each new slot copies params from
            self._param_src: List[Optional[int]] = list(
                range(len(self._layers)))
            self._freeze_upto = -1
            self._global_overrides = {}

        # -- upstream builder surface ---------------------------------
        def fine_tune_configuration(self, updater=None, l2=None,
                                    seed=None):
            """New training hyperparameters for the fine-tune phase
            (upstream ``FineTuneConfiguration``)."""
            if updater is not None:
                self._global_overrides["updater"] = (
                    updater.to_dict() if isinstance(updater, BaseUpdater)
                    else dict(updater))
            if l2 is not None:
                self._global_overrides["l2"] = float(l2)
                # copied layers carry the SOURCE build's resolved l2;
                # reset so the new global value re-resolves onto them
                for ly in self._layers:
                    if hasattr(ly, "l2"):
                        ly.l2 = None
            if seed is not None:
                self._global_overrides["seed"] = int(seed)
            return self

        def set_feature_extractor(self, layer_idx: int):
            """Freeze layers [0..layer_idx] (inclusive) — they forward
            but never update (upstream ``setFeatureExtractor``)."""
            self._freeze_upto = int(layer_idx)
            return self

        def n_out_replace(self, layer_idx: int, n_out: int):
            """Change layer ``layer_idx``'s output width; that layer
            AND the next layer re-initialize (their shapes change) —
            upstream ``nOutReplace`` semantics."""
            i = int(layer_idx)
            ly = self._layers[i]
            if not hasattr(ly, "n_out"):
                raise ValueError(
                    f"layer {i} ({type(ly).__name__}) has no n_out")
            ly.n_out = int(n_out)
            self._param_src[i] = None
            # Downstream: reset resolved n_in so the rebuild re-infers
            # shapes, through any non-parameterized layers (pooling /
            # activation); the FIRST parameterized consumer is the one
            # whose weights change shape and must re-initialize.
            for j in range(i + 1, len(self._layers)):
                nxt = self._layers[j]
                if hasattr(nxt, "n_in"):
                    nxt.n_in = None
                if nxt.has_params():
                    self._param_src[j] = None
                    break
            return self

        def remove_output_layer_and_processing(self):
            """Drop the last layer (upstream
            ``removeOutputLayerAndProcessing``)."""
            self._layers.pop()
            self._param_src.pop()
            return self

        def remove_layers_from_output(self, n: int):
            for _ in range(int(n)):
                self.remove_output_layer_and_processing()
            return self

        def add_layer(self, layer_conf):
            """Append a fresh (randomly initialized) layer."""
            self._layers.append(layer_conf)
            self._param_src.append(None)
            return self

        # -- build ----------------------------------------------------
        def build(self) -> MultiLayerNetwork:
            if self._freeze_upto >= len(self._layers):
                raise ValueError(
                    f"set_feature_extractor({self._freeze_upto}) is out "
                    f"of range for {len(self._layers)} layers")
            for i in range(self._freeze_upto + 1):
                if self._param_src[i] is None and \
                        self._layers[i].has_params():
                    raise ValueError(
                        f"layer {i} is frozen but replaced/added — a "
                        "fresh random layer inside the feature "
                        "extractor would never train; lower "
                        "set_feature_extractor or move the change "
                        "past it")
            src = self._src
            g = dataclasses.replace(src.conf.global_conf,
                                    **self._global_overrides)
            b = NeuralNetConfiguration.builder()
            b._g = g
            lst = b.list()
            if src.conf.input_type is not None:
                lst.set_input_type(src.conf.input_type)
            if src.conf.backprop_type != "standard":
                lst.backprop_type(src.conf.backprop_type,
                                  src.conf.tbptt_fwd_length,
                                  src.conf.tbptt_bwd_length)
            for ly in self._layers:
                lst.layer(ly)
            model = MultiLayerNetwork(lst.build()).init()

            # COPY retained parameters: the solver's jitted step
            # DONATES its buffers, so sharing arrays by reference would
            # delete the source model's params on the first ft.fit()
            import jax.numpy as jnp
            for i, src_i in enumerate(self._param_src):
                if src_i is None:
                    continue
                model.params_tree[f"layer_{i}"] = jax.tree_util.tree_map(
                    jnp.array, src.params_tree[f"layer_{src_i}"])
                model.state_tree[f"layer_{i}"] = jax.tree_util.tree_map(
                    jnp.array, src.state_tree[f"layer_{src_i}"])

            if self._freeze_upto >= 0:
                # persisted in the conf: save/load keeps the freeze
                model.conf.frozen_layers = list(
                    range(self._freeze_upto + 1))
            return model


def frozen_layer_indices(model: MultiLayerNetwork) -> List[int]:
    """Which layers are frozen (from the persisted conf)."""
    return sorted(getattr(model.conf, "frozen_layers", ()) or ())


def freeze_graph_layers(graph, layer_names) -> None:
    """ComputationGraph freezing (the ``TransferLearning.GraphBuilder``
    ``setFeatureExtractor`` essential): mark the named layer vertices
    frozen — persisted in the graph conf, applied as the same update
    mask the MLN path uses.  Call before the first fit (or rebuild the
    solver) so the mask reaches the compiled step."""
    names = [layer_names] if isinstance(layer_names, str) \
        else list(layer_names)
    known = set(graph.params_tree)
    missing = [n for n in names if n not in known]
    if missing:
        raise ValueError(
            f"unknown layer vertices {missing}; parameterized vertices: "
            f"{sorted(known)}")
    graph.conf.frozen_layers = sorted(set(
        list(getattr(graph.conf, "frozen_layers", []) or []) + names))
    graph._solver = None            # rebuild with the new mask
