"""MultiLayerNetwork: the sequential-stack model.

Parity with ``org.deeplearning4j.nn.multilayer.MultiLayerNetwork`` (~4 kLoC
upstream): ``init/fit/output/feedForward/score/evaluate``, listener bus,
epoch/iteration counters, flattened-params view, clone, summary.

TPU-first execution model: ``fit`` drives ONE jitted step per minibatch —
forward + loss + jax.grad backward + updater fused by XLA, with parameter
and optimizer-state buffers donated (updated in place in HBM).  This
replaces DL4J's per-op eager path (Solver → computeGradientAndScore →
thousands of JNI crossings) and its cuDNN helper seam entirely.
"""
from __future__ import annotations

import logging
from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterator import (
    AsyncDataSetIterator, DataSetIterator, ListDataSetIterator)
from deeplearning4j_tpu.eval.classification import Evaluation
from deeplearning4j_tpu.eval.regression import RegressionEvaluation
from deeplearning4j_tpu.eval.roc import ROCMultiClass
from deeplearning4j_tpu.nn.conf.base import BaseLayerConf
from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
from deeplearning4j_tpu.nn.conf.layers_core import BaseOutputLayerConf
from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.optimize.solver import Solver
from deeplearning4j_tpu.optimize.fit_loop import run_fit
from deeplearning4j_tpu.optimize.updaters import updater_from_dict
from deeplearning4j_tpu.runtime.backend import backend
from deeplearning4j_tpu.runtime.dtype import canonical_dtype
from deeplearning4j_tpu.runtime.rng import RngKeyManager

log = logging.getLogger("deeplearning4j_tpu")


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers: Sequence[BaseLayerConf] = conf.layers
        self.params_tree = None
        self.state_tree = None
        self.opt_state = None
        self.listeners: List[TrainingListener] = []
        self.iteration_count = 0
        self.epoch_count = 0
        self.last_batch_size = 0
        self._rng = RngKeyManager(conf.global_conf.seed)
        self._dtype = canonical_dtype(conf.global_conf.dtype)
        cd = getattr(conf.global_conf, "compute_dtype", None)
        self._compute_dtype = (canonical_dtype(cd) if cd
                               else backend().compute_dtype)
        self._updater = updater_from_dict(conf.global_conf.updater)
        self._solver: Optional[Solver] = None
        self._output_fn = jax.jit(self._forward_infer)
        self._score_fn = jax.jit(self._score_batch_infer)

    # ------------------------------------------------------------------
    # Initialization
    # ------------------------------------------------------------------
    def init(self, seed: Optional[int] = None) -> "MultiLayerNetwork":
        """Initialize parameters (DL4J ``MultiLayerNetwork.init()``)."""
        if seed is not None:
            self._rng.reset(seed)
        params, states = {}, {}
        keys = self._rng.next_keys(len(self.layers))
        for i, (ly, key) in enumerate(zip(self.layers, keys)):
            p, s = ly.init(key, self._dtype)
            params[f"layer_{i}"] = p
            states[f"layer_{i}"] = s
        self.params_tree = params
        self.state_tree = states
        self.opt_state = None  # lazily built at first fit
        return self

    def _check_init(self):
        if self.params_tree is None:
            self.init()
        # a trainer holding the authoritative (e.g. pipeline-stacked)
        # params installs this hook; it refreshes params_tree lazily so
        # the per-step hot path never pays the sync (ADVICE r5 perf)
        hook = self.__dict__.get("_param_sync_hook")
        if hook is not None:
            hook()

    # ------------------------------------------------------------------
    # Pure forward/score (traced by XLA)
    # ------------------------------------------------------------------
    def _forward_layers(self, params, state, x, training, rng, upto=None,
                        mask=None):
        """Run layers [0, upto); returns (activation, new_state_tree).
        `mask` is the features mask ([b, t] for sequences) handed to
        mask-aware layers (``USES_MASK``) — DL4J's setMaskArray propagation.
        """
        compute_dtype = self._compute_dtype
        n = len(self.layers) if upto is None else upto
        keys = (jax.random.split(rng, n) if rng is not None
                else [None] * n)
        new_state = dict(state)
        for i in range(n):
            ly = self.layers[i]
            pre = self.conf.preprocessors[i]
            if pre is not None:
                x = pre(x)
            kwargs = {}
            if getattr(ly, "USES_MASK", False):
                kwargs["mask"] = mask
            x, s = ly.apply(
                params[f"layer_{i}"], state[f"layer_{i}"], x,
                training=training, rng=keys[i], compute_dtype=compute_dtype,
                **kwargs)
            new_state[f"layer_{i}"] = s
        return x, new_state

    def _forward_infer(self, params, state, x, mask=None):
        y, _ = self._forward_layers(params, state, x, False, None, mask=mask)
        return y

    def _regularization_score(self, params):
        from deeplearning4j_tpu.utils.trees import get_path
        reg = 0.0
        for i, ly in enumerate(self.layers):
            l1 = ly.l1 or 0.0
            l2 = ly.l2 or 0.0
            if not (l1 or l2):
                continue
            for name in ly.regularized_param_names():
                w = get_path(params[f"layer_{i}"], name)
                if w is None:
                    continue
                if l1:
                    reg = reg + l1 * jnp.sum(jnp.abs(w))
                if l2:
                    # DL4J L2Regularization score: 0.5 * l2 * sum(w^2)
                    reg = reg + 0.5 * l2 * jnp.sum(jnp.square(w))
        return reg

    def _score_batch(self, params, state, batch, rng, training):
        """Mean per-example loss + regularization (DL4J ``score()``)."""
        x = batch["features"]
        labels = batch["labels"]
        lmask = batch.get("labels_mask")
        fmask = batch.get("features_mask")
        out_layer = self.layers[-1]
        if not isinstance(out_layer, BaseOutputLayerConf):
            raise ValueError("Last layer must be an output/loss layer for fit()")
        h, new_state = self._forward_layers(
            params, state, x, training, rng, upto=len(self.layers) - 1,
            mask=fmask)
        pre = self.conf.preprocessors[-1]
        if pre is not None:
            h = pre(h)
        z = out_layer.pre_output(
            params[f"layer_{len(self.layers) - 1}"], h,
            self._compute_dtype)
        # Distinct key for head sampling (e.g. VAE reparameterization):
        # `rng` itself already parented the per-layer dropout splits.
        head_rng = None if rng is None else jax.random.fold_in(rng, 0x5eed)
        scores = out_layer.per_example_score(
            labels, z, lmask, head_input=h, rng=head_rng,
            params=params[f"layer_{len(self.layers) - 1}"])
        if lmask is not None:
            denom = jnp.maximum(jnp.sum(lmask), 1.0)
            loss = jnp.sum(scores) / denom
        else:
            loss = jnp.mean(scores)
        return loss + self._regularization_score(params), new_state

    def _score_batch_infer(self, params, state, batch):
        loss, _ = self._score_batch(params, state, batch, None, False)
        return loss

    # ------------------------------------------------------------------
    # Training
    # ------------------------------------------------------------------
    def _trainable_mask(self):
        """0/1 mask pytree from conf.frozen_layers (persisted through
        save/load — the ONLY freezing mechanism, so it always
        survives serialization)."""
        frozen = set(getattr(self.conf, "frozen_layers", ()) or ())
        if not frozen:
            return None
        return {f"layer_{i}": jax.tree_util.tree_map(
                    lambda _: 0.0 if i in frozen else 1.0,
                    self.params_tree[f"layer_{i}"])
                for i in range(len(self.layers))}

    def _build_solver(self):
        if self._solver is not None:
            return
        from deeplearning4j_tpu.utils.trees import get_path, set_path
        decay_tree = jax.tree_util.tree_map(lambda _: 0.0, self.params_tree)
        any_decay = False
        for i, ly in enumerate(self.layers):
            wd = ly.weight_decay or 0.0
            if wd:
                any_decay = True
                for name in ly.regularized_param_names():
                    if get_path(decay_tree[f"layer_{i}"], name) is not None:
                        set_path(decay_tree[f"layer_{i}"], name, wd)
        self._solver = Solver(
            score_fn=self._score_batch,
            updater=self._updater,
            grad_normalization=self.conf.grad_normalization,
            grad_norm_threshold=self.conf.grad_norm_threshold,
            minimize=self.conf.global_conf.minimize,
            decay_tree=decay_tree if any_decay else None,
            trainable_tree=self._trainable_mask(),
        )
        if self.opt_state is None:
            self.opt_state = self._solver.init_opt_state(self.params_tree)

    @staticmethod
    def _batch_dict(ds: DataSet):
        b = {"features": jnp.asarray(ds.features),
             "labels": jnp.asarray(ds.labels)}
        if ds.labels_mask is not None:
            b["labels_mask"] = jnp.asarray(ds.labels_mask)
        if ds.features_mask is not None:
            b["features_mask"] = jnp.asarray(ds.features_mask)
        return b

    def fit(self, data: Union[DataSet, DataSetIterator], n_epochs: int = 1,
            async_prefetch: bool = True, resume: bool = False):
        """Train (DL4J ``fit(DataSetIterator, numEpochs)`` /
        ``fit(DataSet)``).  Wraps the iterator in async prefetch exactly as
        DL4J wraps in ``AsyncDataSetIterator``.  ``resume=True`` restores
        the newest checkpoint from an attached ``CheckpointListener``
        first (``n_epochs`` is then the TOTAL epoch target)."""
        self._check_init()
        self._build_solver()
        if isinstance(data, DataSet):
            # fit(DataSet) bypasses async prefetch (nothing to overlap),
            # like DL4J's fit(DataSet) vs fit(DataSetIterator).
            iterator: DataSetIterator = ListDataSetIterator([data])
            async_prefetch = False
        else:
            iterator = data
        wrapped = (AsyncDataSetIterator(iterator)
                   if async_prefetch and not isinstance(
                       iterator, AsyncDataSetIterator)
                   else iterator)

        return run_fit(self, wrapped, n_epochs, reset_target=iterator,
                       resume=resume)

    # ------------------------------------------------------------------
    # Recurrent state management (DL4J rnnTimeStep / tBPTT semantics)
    # ------------------------------------------------------------------
    def _has_rnn(self) -> bool:
        return any(getattr(ly, "IS_RNN", False) for ly in self.layers)

    @staticmethod
    def _tbptt_chunks(ds: DataSet, length: int):
        """Split a sequence DataSet along time into tBPTT segments
        (DL4J ``MultiLayerNetwork.doTruncatedBPTT``)."""
        from deeplearning4j_tpu.data.dataset import tbptt_segments
        return tbptt_segments(ds, length)

    def rnn_clear_previous_state(self):
        """Drop stored recurrent carries (DL4J ``rnnClearPreviousState``)."""
        from deeplearning4j_tpu.nn.conf.layers_recurrent import strip_rnn_carry
        self._rnn_state_map = None
        if self.state_tree is not None:
            self.state_tree = strip_rnn_carry(self.state_tree)

    def rnn_time_step(self, x, features_mask=None):
        """Streaming inference: run these timesteps continuing from the
        stored recurrent state, store the new state (DL4J ``rnnTimeStep``).
        ``x``: [b, t, f] (or [b, f] for a single step -> returns [b, out]).

        Like DL4J's ``stateMap``, the streaming carry lives in a SEPARATE
        map (not the model's state tree), so interleaved ``output``/
        ``score`` calls still start from zero state."""
        self._check_init()
        x = jnp.asarray(x)
        single = x.ndim == 2
        if single:
            x = x[:, None, :]
        if features_mask is not None:
            features_mask = jnp.asarray(features_mask)
        carry = getattr(self, "_rnn_state_map", None)
        state_in = dict(self.state_tree)
        if carry is not None:
            for lname, lcarry in carry.items():
                state_in[lname] = {**state_in[lname], **lcarry}
        y, new_state = self._rnn_step_jit(
            self.params_tree, state_in, x, features_mask)
        self._rnn_state_map = {
            lname: {k: v for k, v in lstate.items()
                    if k.startswith("rnn_")}
            for lname, lstate in new_state.items()}
        return y[:, -1] if single else y

    def _rnn_step_impl(self, params, state, x, mask):
        y, new_state = self._forward_layers(params, state, x, False, None,
                                            mask=mask)
        return y, new_state

    @property
    def _rnn_step_jit(self):
        if not hasattr(self, "_rnn_step_fn"):
            self._rnn_step_fn = jax.jit(self._rnn_step_impl)
        return self._rnn_step_fn

    # ------------------------------------------------------------------
    # Inference / scoring
    # ------------------------------------------------------------------
    def output(self, x, training: bool = False, features_mask=None):
        """Forward pass returning final-layer activations
        (DL4J ``output(INDArray[, featuresMask])``)."""
        self._check_init()
        x = jnp.asarray(x)
        if features_mask is not None:
            features_mask = jnp.asarray(features_mask)
        if training:
            y, _ = self._forward_layers(self.params_tree, self.state_tree, x,
                                        True, self._rng.next_key(),
                                        mask=features_mask)
            return y
        return self._output_fn(self.params_tree, self.state_tree, x,
                               features_mask)

    def feed_forward(self, x, training: bool = False) -> List[jnp.ndarray]:
        """All per-layer activations (DL4J ``feedForward``)."""
        self._check_init()
        x = jnp.asarray(x)
        acts = [x]
        compute_dtype = self._compute_dtype
        rng = self._rng.next_key() if training else None
        keys = (jax.random.split(rng, len(self.layers)) if rng is not None
                else [None] * len(self.layers))
        state = self.state_tree
        for i, ly in enumerate(self.layers):
            pre = self.conf.preprocessors[i]
            if pre is not None:
                x = pre(x)
            x, _ = ly.apply(self.params_tree[f"layer_{i}"],
                            state[f"layer_{i}"], x, training=training,
                            rng=keys[i], compute_dtype=compute_dtype)
            acts.append(x)
        return acts

    def score(self, ds: DataSet) -> float:
        """Loss on a dataset without updating (DL4J ``score(DataSet)``)."""
        self._check_init()
        return float(self._score_fn(self.params_tree, self.state_tree,
                                    self._batch_dict(ds)))

    def evaluate(self, iterator: DataSetIterator, top_n: int = 1) -> Evaluation:
        """(DL4J ``evaluate(DataSetIterator)``)."""
        self._check_init()
        ev = Evaluation(top_n=top_n)
        for ds in iterator:
            out = self.output(ds.features, features_mask=ds.features_mask)
            ev.eval(ds.labels, np.asarray(out), ds.labels_mask)
        iterator.reset()
        return ev

    def evaluate_regression(self, iterator) -> RegressionEvaluation:
        self._check_init()
        ev = RegressionEvaluation()
        for ds in iterator:
            ev.eval(ds.labels, np.asarray(self.output(ds.features)),
                    ds.labels_mask)
        iterator.reset()
        return ev

    def evaluate_roc(self, iterator, exact: bool = True) -> ROCMultiClass:
        self._check_init()
        roc = ROCMultiClass(exact=exact)
        for ds in iterator:
            roc.eval(ds.labels, np.asarray(self.output(ds.features)),
                     ds.labels_mask)
        iterator.reset()
        return roc

    # ------------------------------------------------------------------
    # Parameter access (DL4J flattened-vector parity views)
    # ------------------------------------------------------------------
    def _leaf_order(self):
        """((path...), leaf) pairs, layer-major then name-sorted (nested
        dicts — e.g. Bidirectional's {fwd, bwd} — walked depth-first)."""
        from deeplearning4j_tpu.utils.trees import iter_leaves
        for i in range(len(self.layers)):
            for path, leaf in iter_leaves(self.params_tree[f"layer_{i}"]):
                yield (f"layer_{i}",) + path, leaf

    def params(self) -> np.ndarray:
        """One flattened host vector, layer-major then name-sorted — the
        DL4J ``params()`` view (order: per layer W then b)."""
        self._check_init()
        parts = [np.asarray(leaf).reshape(-1)
                 for _, leaf in self._leaf_order()]
        return (np.concatenate(parts) if parts
                else np.zeros((0,), np.float32))

    def set_params(self, vector: np.ndarray):
        from deeplearning4j_tpu.utils.trees import deep_copy_dicts, set_path
        self._check_init()
        vector = np.asarray(vector)
        off = 0
        new = deep_copy_dicts(self.params_tree)
        for path, arr in self._leaf_order():
            size = int(np.prod(arr.shape)) if arr.shape else 1
            set_path(new, path, jnp.asarray(
                vector[off:off + size].reshape(arr.shape), arr.dtype))
            off += size
        if off != vector.size:
            raise ValueError(f"Expected {off} values, got {vector.size}")
        self.params_tree = new

    def num_params(self) -> int:
        self._check_init()
        return sum(int(np.prod(np.asarray(l).shape))
                   for l in jax.tree_util.tree_leaves(self.params_tree))

    # ------------------------------------------------------------------
    # Misc parity API
    # ------------------------------------------------------------------
    def set_listeners(self, *listeners: TrainingListener):
        self.listeners = list(listeners)

    def add_listeners(self, *listeners: TrainingListener):
        self.listeners.extend(listeners)

    def clone(self) -> "MultiLayerNetwork":
        import copy
        hook = self.__dict__.get("_param_sync_hook")
        if hook is not None:
            hook()
        m = MultiLayerNetwork(MultiLayerConfiguration.from_dict(
            self.conf.to_dict()))
        if self.params_tree is not None:
            m.params_tree = jax.tree_util.tree_map(lambda a: a,
                                                   self.params_tree)
            m.state_tree = copy.deepcopy(
                jax.tree_util.tree_map(lambda a: a, self.state_tree))
        m.iteration_count = self.iteration_count
        m.epoch_count = self.epoch_count
        return m

    def summary(self) -> str:
        """Layer table (DL4J ``summary()``)."""
        self._check_init()
        from deeplearning4j_tpu.utils.trees import iter_leaves
        rows = [f"{'idx':<4} {'name':<22} {'type':<24} {'#params':>10}"]
        total = 0
        for i, ly in enumerate(self.layers):
            lp = self.params_tree[f"layer_{i}"]
            n = sum(int(np.prod(np.asarray(a).shape))
                    for _, a in iter_leaves(lp))
            total += n
            rows.append(f"{i:<4} {(ly.name or f'layer_{i}'):<22} "
                        f"{type(ly).__name__:<24} {n:>10}")
        rows.append(f"Total params: {total}")
        return "\n".join(rows)

    def save(self, path, save_updater: bool = True):
        from deeplearning4j_tpu.utils.model_serializer import write_model
        write_model(self, path, save_updater=save_updater)

    @staticmethod
    def load(path, load_updater: bool = True) -> "MultiLayerNetwork":
        from deeplearning4j_tpu.utils.model_serializer import (
            restore_multi_layer_network)
        return restore_multi_layer_network(path, load_updater=load_updater)
