"""Model classes: sequential MultiLayerNetwork and DAG ComputationGraph.

TPU-native twin of ``org.deeplearning4j.nn.multilayer.MultiLayerNetwork``
and ``org.deeplearning4j.nn.graph.ComputationGraph``.  Same public training
semantics (fit/output/score/evaluate, listeners, serialization), but the
whole train iteration is one compiled XLA program instead of eager per-op
dispatch, and parameters are pytrees instead of one flattened vector with
per-layer views (a flattened view is still offered for parity).
"""

from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.models.transfer_learning import TransferLearning

__all__ = ["MultiLayerNetwork", "TransferLearning"]
