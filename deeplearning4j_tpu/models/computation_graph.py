"""ComputationGraph: the DAG model (multi-input / multi-output).

Parity with ``org.deeplearning4j.nn.graph.ComputationGraph`` and its conf
(``ComputationGraphConfiguration.GraphBuilder``): named vertices wired by
name, topological-order execution, implicit merge when a layer has several
inputs, multiple output layers whose losses sum.

TPU-first execution: DL4J walks ``GraphVertex[]`` eagerly twice per step
(doForward then doBackward, one JNI crossing per op).  Here the whole DAG
— every vertex, every loss head, ``jax.grad``, and the updater — traces to
ONE XLA program per training step; the topological walk happens once at
trace time, then exists only as fused HLO.
"""
from __future__ import annotations

import dataclasses
import json
import logging
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterator import (
    AsyncDataSetIterator, DataSetIterator, ListDataSetIterator)
from deeplearning4j_tpu.eval.classification import Evaluation
from deeplearning4j_tpu.eval.regression import RegressionEvaluation
from deeplearning4j_tpu.eval.roc import ROCMultiClass
from deeplearning4j_tpu.nn.conf.base import (
    BaseLayerConf, GlobalConf, layer_from_dict)
from deeplearning4j_tpu.nn.conf.graph_vertices import (
    BaseGraphVertex, MergeVertex, vertex_from_dict)
from deeplearning4j_tpu.nn.conf.inputs import InputType, Preprocessor, adapt
from deeplearning4j_tpu.nn.conf.layers_core import BaseOutputLayerConf
from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.optimize.solver import Solver
from deeplearning4j_tpu.optimize.fit_loop import run_fit
from deeplearning4j_tpu.optimize.updaters import updater_from_dict
from deeplearning4j_tpu.runtime.backend import backend
from deeplearning4j_tpu.runtime.dtype import canonical_dtype
from deeplearning4j_tpu.runtime.rng import RngKeyManager

log = logging.getLogger("deeplearning4j_tpu")


@dataclasses.dataclass
class VertexSpec:
    """One named DAG node: either a layer (with optional auto-inserted
    preprocessor — DL4J ``LayerVertex`` wraps layer + InputPreProcessor)
    or a combining GraphVertex."""

    layer: Optional[BaseLayerConf] = None
    vertex: Optional[BaseGraphVertex] = None
    preprocessor: Optional[Preprocessor] = None

    def to_dict(self):
        d: Dict[str, Any] = {}
        if self.layer is not None:
            d["layer"] = self.layer.to_dict()
        if self.vertex is not None:
            d["vertex"] = self.vertex.to_dict()
        if self.preprocessor is not None:
            d["preprocessor"] = self.preprocessor.to_dict()
        return d

    @staticmethod
    def from_dict(d):
        return VertexSpec(
            layer=layer_from_dict(d["layer"]) if d.get("layer") else None,
            vertex=vertex_from_dict(d["vertex"]) if d.get("vertex") else None,
            preprocessor=(Preprocessor.from_dict(d["preprocessor"])
                          if d.get("preprocessor") else None),
        )


def _topological_order(network_inputs: Sequence[str],
                       vertex_inputs: Dict[str, Sequence[str]]) -> List[str]:
    """Kahn's algorithm over vertex names (DL4J
    ``ComputationGraph.topologicalSortOrder``)."""
    produced = set(network_inputs)
    remaining = dict(vertex_inputs)
    order: List[str] = []
    while remaining:
        ready = [n for n, ins in remaining.items()
                 if all(i in produced for i in ins)]
        if not ready:
            unresolved = {n: [i for i in ins if i not in produced]
                          for n, ins in remaining.items()}
            raise ValueError(f"Graph has a cycle or missing inputs: {unresolved}")
        for n in sorted(ready):
            order.append(n)
            produced.add(n)
            del remaining[n]
    return order


class GraphBuilder:
    """Fluent DAG builder (DL4J
    ``ComputationGraphConfiguration.GraphBuilder``)."""

    def __init__(self, parent):
        self._parent = parent  # nn.conf.builder.Builder (global defaults)
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._vertices: Dict[str, VertexSpec] = {}
        self._vertex_inputs: Dict[str, List[str]] = {}
        self._input_types: Dict[str, InputType] = {}
        self._backprop_type: str = "standard"
        self._tbptt_fwd: Optional[int] = None
        self._tbptt_bwd: Optional[int] = None

    def add_inputs(self, *names: str) -> "GraphBuilder":
        for n in names:
            self._check_name(n)
            self._inputs.append(n)
        return self

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        """Positional, matching ``add_inputs`` order (DL4J setInputTypes)."""
        for name, it in zip(self._inputs, types):
            self._input_types[name] = it
        return self

    def _check_name(self, name: str):
        if name in self._vertices or name in self._inputs:
            raise ValueError(f"Duplicate vertex name {name!r}")
        return name

    def add_layer(self, name: str, layer: BaseLayerConf,
                  *inputs: str) -> "GraphBuilder":
        self._check_name(name)
        if layer.name is None:
            layer.name = name
        self._vertices[name] = VertexSpec(layer=layer)
        self._vertex_inputs[name] = list(inputs)
        return self

    def add_vertex(self, name: str, vertex: BaseGraphVertex,
                   *inputs: str) -> "GraphBuilder":
        self._check_name(name)
        self._vertices[name] = VertexSpec(vertex=vertex)
        self._vertex_inputs[name] = list(inputs)
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def backprop_type(self, kind: str, tbptt_fwd: int = None,
                      tbptt_bwd: int = None) -> "GraphBuilder":
        self._backprop_type = str(kind).lower()
        self._tbptt_fwd = tbptt_fwd
        self._tbptt_bwd = tbptt_bwd or tbptt_fwd
        return self

    def build(self) -> "ComputationGraphConfiguration":
        if not self._inputs:
            raise ValueError("add_inputs(...) required")
        if not self._outputs:
            raise ValueError("set_outputs(...) required")
        for name in self._outputs:
            if name not in self._vertices:
                raise ValueError(f"Output {name!r} is not a vertex")
        for name, ins in self._vertex_inputs.items():
            for i in ins:
                if i not in self._vertices and i not in self._inputs:
                    raise ValueError(f"Vertex {name!r} input {i!r} undefined")
            spec = self._vertices[name]
            if spec.vertex is not None:
                lo, hi = spec.vertex.n_inputs()
                if len(ins) < lo or (hi is not None and len(ins) > hi):
                    raise ValueError(
                        f"Vertex {name!r} ({type(spec.vertex).__name__}) "
                        f"accepts {lo}..{hi if hi is not None else 'N'} "
                        f"inputs, got {len(ins)}")
        g = self._parent._g
        for spec in self._vertices.values():
            if spec.layer is not None:
                spec.layer.resolve_defaults(g)

        order = _topological_order(self._inputs, self._vertex_inputs)

        # InputType propagation + preprocessor insertion + n_in auto-fill
        # (DL4J GraphBuilder#build with setInputTypes).  Skipped entirely
        # when no input types were given — then every layer must be fully
        # specified, as in DL4J without setInputTypes.
        if self._input_types:
            types: Dict[str, InputType] = dict(self._input_types)
            missing = [n for n in self._inputs if n not in types]
            if missing:
                raise ValueError(f"set_input_types missing for {missing}")
            for name in order:
                spec = self._vertices[name]
                in_types = [types[i] for i in self._vertex_inputs[name]]
                if spec.layer is not None:
                    it = (in_types[0] if len(in_types) == 1
                          else MergeVertex().infer_shapes(in_types))
                    ly = spec.layer
                    if "any" in ly.WANTED_KINDS or it.kind in ly.WANTED_KINDS:
                        adapted = it
                    else:
                        err = None
                        for kind in ly.WANTED_KINDS:
                            try:
                                spec.preprocessor, adapted = adapt(it, kind)
                                break
                            except ValueError as e:
                                err = e
                        else:
                            raise ValueError(f"Vertex {name!r}: {err}")
                    out_shape = ly.infer_shapes(adapted.shape)
                    out_kind = getattr(ly, "OUTPUT_KIND", None) or adapted.kind
                    types[name] = InputType(out_kind, tuple(out_shape))
                else:
                    types[name] = spec.vertex.infer_shapes(in_types)

        return ComputationGraphConfiguration(
            global_conf=g,
            network_inputs=list(self._inputs),
            network_outputs=list(self._outputs),
            vertices=self._vertices,
            vertex_inputs=self._vertex_inputs,
            input_types={k: v for k, v in self._input_types.items()},
            topological_order=order,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_bwd_length=self._tbptt_bwd,
            grad_normalization=self._parent.grad_normalization,
            grad_norm_threshold=self._parent.grad_norm_threshold,
        )


@dataclasses.dataclass
class ComputationGraphConfiguration:
    """Serializable DAG config (DL4J ``ComputationGraphConfiguration`` —
    the JSON inside every graph checkpoint)."""

    global_conf: GlobalConf
    network_inputs: List[str]
    network_outputs: List[str]
    vertices: Dict[str, VertexSpec]
    vertex_inputs: Dict[str, List[str]]
    input_types: Dict[str, InputType] = dataclasses.field(default_factory=dict)
    topological_order: List[str] = dataclasses.field(default_factory=list)
    backprop_type: str = "standard"
    tbptt_fwd_length: Optional[int] = None
    tbptt_bwd_length: Optional[int] = None
    grad_normalization: Optional[str] = None
    grad_norm_threshold: float = 1.0
    # layer-vertex names whose parameters never update (TransferLearning
    # / FrozenLayer); persisted so a restored fine-tune keeps its freeze
    frozen_layers: List[str] = dataclasses.field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "format": "deeplearning4j_tpu/ComputationGraphConfiguration/v1",
            "global_conf": dataclasses.asdict(self.global_conf),
            "network_inputs": self.network_inputs,
            "network_outputs": self.network_outputs,
            "vertices": {n: s.to_dict() for n, s in self.vertices.items()},
            "vertex_inputs": self.vertex_inputs,
            "input_types": {n: t.to_dict() for n, t in self.input_types.items()},
            "topological_order": self.topological_order,
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_bwd_length": self.tbptt_bwd_length,
            "grad_normalization": self.grad_normalization,
            "grad_norm_threshold": self.grad_norm_threshold,
            "frozen_layers": list(self.frozen_layers),
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ComputationGraphConfiguration":
        conf = ComputationGraphConfiguration(
            global_conf=GlobalConf(**d["global_conf"]),
            network_inputs=list(d["network_inputs"]),
            network_outputs=list(d["network_outputs"]),
            vertices={n: VertexSpec.from_dict(s)
                      for n, s in d["vertices"].items()},
            vertex_inputs={n: list(v) for n, v in d["vertex_inputs"].items()},
            input_types={n: InputType.from_dict(t)
                         for n, t in d.get("input_types", {}).items()},
            topological_order=list(d.get("topological_order", [])),
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length"),
            tbptt_bwd_length=d.get("tbptt_bwd_length"),
            grad_normalization=d.get("grad_normalization"),
            grad_norm_threshold=d.get("grad_norm_threshold", 1.0),
            frozen_layers=list(d.get("frozen_layers", [])),
        )
        if not conf.topological_order:
            conf.topological_order = _topological_order(
                conf.network_inputs, conf.vertex_inputs)
        return conf

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration.from_dict(json.loads(s))


class TrainState(NamedTuple):
    """Carried state of ``compiled_train_step`` (pytree)."""

    params: Any
    opt_state: Any
    model_state: Any
    step: jnp.ndarray


class ComputationGraph:
    """Runtime twin of the configuration (DL4J
    ``org.deeplearning4j.nn.graph.ComputationGraph``)."""

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.params_tree = None
        self.state_tree = None
        self.opt_state = None
        self.listeners: List[TrainingListener] = []
        self.iteration_count = 0
        self.epoch_count = 0
        self.last_batch_size = 0
        self._rng = RngKeyManager(conf.global_conf.seed)
        self._dtype = canonical_dtype(conf.global_conf.dtype)
        cd = getattr(conf.global_conf, "compute_dtype", None)
        self._compute_dtype = (canonical_dtype(cd) if cd
                               else backend().compute_dtype)
        self._updater = updater_from_dict(conf.global_conf.updater)
        self._solver: Optional[Solver] = None
        self._output_fn = jax.jit(self._forward_infer)
        self._score_fn = jax.jit(self._score_batch_infer)

    # ------------------------------------------------------------------
    def vertex_names(self) -> List[str]:
        return list(self.conf.topological_order)

    def _layer_vertices(self):
        for name in self.conf.topological_order:
            spec = self.conf.vertices[name]
            if spec.layer is not None:
                yield name, spec.layer

    @property
    def output_layers(self) -> List[BaseOutputLayerConf]:
        return [self.conf.vertices[n].layer for n in self.conf.network_outputs]

    # ------------------------------------------------------------------
    def init(self, seed: Optional[int] = None) -> "ComputationGraph":
        if seed is not None:
            self._rng.reset(seed)
        names = [n for n, _ in self._layer_vertices()]
        keys = self._rng.next_keys(len(names))
        params, states = {}, {}
        for name in self.conf.topological_order:
            params[name], states[name] = {}, {}
        for (name, ly), key in zip(self._layer_vertices(), keys):
            params[name], states[name] = ly.init(key, self._dtype)
        self.params_tree = params
        self.state_tree = states
        self.opt_state = None
        return self

    def _check_init(self):
        if self.params_tree is None:
            self.init()

    # ------------------------------------------------------------------
    # Pure forward (traced by XLA)
    # ------------------------------------------------------------------
    def _as_input_dict(self, x) -> Dict[str, Any]:
        if isinstance(x, dict):
            return x
        if isinstance(x, (list, tuple)):
            return dict(zip(self.conf.network_inputs, x))
        return {self.conf.network_inputs[0]: x}

    def _forward_all(self, params, state, inputs: Dict[str, Any], training,
                     rng, masks: Optional[Dict[str, Any]] = None,
                     stop_before_output: bool = False):
        """Topological walk; returns (activations dict, new_state, masks,
        head_inputs).  With ``stop_before_output=True``, ``head_inputs``
        maps each output-layer vertex to the activation FEEDING it (the
        training path computes loss from logits); ``acts`` still holds the
        real output activation whenever a downstream vertex consumes it,
        so consumers never see pre-output values."""
        acts: Dict[str, Any] = dict(inputs)
        act_masks: Dict[str, Any] = dict(masks or {})
        head_inputs: Dict[str, Any] = {}
        new_state = dict(state)
        layer_names = [n for n, _ in self._layer_vertices()]
        keys = (dict(zip(layer_names,
                         jax.random.split(rng, max(len(layer_names), 1))))
                if rng is not None else {})
        out_set = set(self.conf.network_outputs) if stop_before_output else set()
        consumed = {i for ins in self.conf.vertex_inputs.values() for i in ins}
        for name in self.conf.topological_order:
            spec = self.conf.vertices[name]
            xs = [acts[i] for i in self.conf.vertex_inputs[name]]
            in_masks = [m for i in self.conf.vertex_inputs[name]
                        if (m := act_masks.get(i)) is not None]
            # Combining vertices AND their input masks pointwise (DL4J
            # feedForwardMaskArrays: a timestep is valid only if valid in
            # every masked input).
            mask = None
            for m in in_masks:
                mask = m if mask is None else jnp.minimum(mask, m)
            if spec.layer is not None:
                x = xs[0] if len(xs) == 1 else MergeVertex().apply(xs)
                if spec.preprocessor is not None:
                    x = spec.preprocessor(x)
                if name in out_set:
                    head_inputs[name] = x
                    if name not in consumed:
                        acts[name] = x
                        continue
                    # fall through: a downstream vertex reads this output
                    # layer's real activation during training too
                ly = spec.layer
                kwargs = {"mask": mask} if getattr(ly, "USES_MASK", False) \
                    else {}
                y, s = ly.apply(params[name], state[name], x,
                                training=training, rng=keys.get(name),
                                compute_dtype=self._compute_dtype, **kwargs)
                new_state[name] = s
                acts[name] = y
            else:
                acts[name] = spec.vertex.apply(xs)
            if mask is not None:
                act_masks[name] = mask
        return acts, new_state, act_masks, head_inputs

    def _forward_infer(self, params, state, inputs, masks=None):
        """Inference forward; returns dict of output-vertex activations."""
        inputs = self._as_input_dict(inputs)
        acts, _, _, _ = self._forward_all(params, state, inputs, False, None,
                                          masks=masks)
        return {n: acts[n] for n in self.conf.network_outputs}

    def _regularization_score(self, params):
        from deeplearning4j_tpu.utils.trees import get_path
        reg = 0.0
        for name, ly in self._layer_vertices():
            l1 = ly.l1 or 0.0
            l2 = ly.l2 or 0.0
            if not (l1 or l2):
                continue
            for pname in ly.regularized_param_names():
                w = get_path(params[name], pname)
                if w is None:
                    continue
                if l1:
                    reg = reg + l1 * jnp.sum(jnp.abs(w))
                if l2:
                    reg = reg + 0.5 * l2 * jnp.sum(jnp.square(w))
        return reg

    def _score_batch(self, params, state, batch, rng, training):
        """Sum of per-output mean losses + regularization (DL4J
        ``ComputationGraph.score``: output-layer scores summed)."""
        inputs = self._as_input_dict(batch["features"])
        labels = batch["labels"]
        if not isinstance(labels, dict):
            labels = {self.conf.network_outputs[0]: labels}
        fmasks = batch.get("features_mask")
        if fmasks is not None and not isinstance(fmasks, dict):
            fmasks = {self.conf.network_inputs[0]: fmasks}
        lmasks = batch.get("labels_mask")
        if lmasks is None:
            lmasks = {}
        elif not isinstance(lmasks, dict):
            lmasks = {self.conf.network_outputs[0]: lmasks}
        acts, new_state, _, head_inputs = self._forward_all(
            params, state, inputs, training, rng, masks=fmasks,
            stop_before_output=True)
        loss = 0.0
        for head_i, name in enumerate(self.conf.network_outputs):
            out_layer = self.conf.vertices[name].layer
            if not isinstance(out_layer, BaseOutputLayerConf):
                raise ValueError(
                    f"Output vertex {name!r} must be an output/loss layer")
            z = out_layer.pre_output(params[name], head_inputs[name],
                                     self._compute_dtype)
            lmask = lmasks.get(name)
            head_rng = (None if rng is None
                        else jax.random.fold_in(rng, 0x5eed + head_i))
            scores = out_layer.per_example_score(
                labels[name], z, lmask, head_input=head_inputs[name],
                rng=head_rng, params=params[name])
            if lmask is not None:
                loss = loss + jnp.sum(scores) / jnp.maximum(jnp.sum(lmask), 1.0)
            else:
                loss = loss + jnp.mean(scores)
        return loss + self._regularization_score(params), new_state

    def _score_batch_infer(self, params, state, batch):
        loss, _ = self._score_batch(params, state, batch, None, False)
        return loss

    # ------------------------------------------------------------------
    # Training
    # ------------------------------------------------------------------
    def _build_solver(self, alloc_opt_state: bool = True):
        if self._solver is not None:
            return
        from deeplearning4j_tpu.utils.trees import get_path, set_path
        decay_tree = jax.tree_util.tree_map(lambda _: 0.0, self.params_tree)
        any_decay = False
        for name, ly in self._layer_vertices():
            wd = ly.weight_decay or 0.0
            if wd:
                any_decay = True
                for pname in ly.regularized_param_names():
                    if get_path(decay_tree[name], pname) is not None:
                        set_path(decay_tree[name], pname, wd)
        frozen = set(getattr(self.conf, "frozen_layers", ()) or ())
        trainable = None
        if frozen:
            trainable = {
                name: jax.tree_util.tree_map(
                    lambda _: 0.0 if name in frozen else 1.0, sub)
                for name, sub in self.params_tree.items()}
        self._solver = Solver(
            score_fn=self._score_batch,
            updater=self._updater,
            grad_normalization=self.conf.grad_normalization,
            grad_norm_threshold=self.conf.grad_norm_threshold,
            minimize=self.conf.global_conf.minimize,
            decay_tree=decay_tree if any_decay else None,
            trainable_tree=trainable,
        )
        if alloc_opt_state and self.opt_state is None:
            self.opt_state = self._solver.init_opt_state(self.params_tree)

    def _batch_dict(self, ds: Union[DataSet, MultiDataSet]):
        def named(v, names):
            """list/tuple → dict keyed positionally by input/output name."""
            if v is None:
                return None
            if isinstance(v, dict):
                return {k: jnp.asarray(a) for k, a in v.items()
                        if a is not None}
            if isinstance(v, (list, tuple)):
                return {n: jnp.asarray(a) for n, a in zip(names, v)
                        if a is not None}
            return jnp.asarray(v)

        ins = self.conf.network_inputs
        outs = self.conf.network_outputs
        b = {"features": named(ds.features, ins),
             "labels": named(ds.labels, outs)}
        fmask = getattr(ds, "features_mask",
                        getattr(ds, "features_masks", None))
        lmask = getattr(ds, "labels_mask", getattr(ds, "labels_masks", None))
        fmask = named(fmask, ins)
        lmask = named(lmask, outs)
        if fmask is not None and (not isinstance(fmask, dict) or fmask):
            b["features_mask"] = fmask
        if lmask is not None and (not isinstance(lmask, dict) or lmask):
            b["labels_mask"] = lmask
        return b

    def fit(self, data, n_epochs: int = 1, async_prefetch: bool = True,
            resume: bool = False):
        """Train on a DataSet / MultiDataSet / iterator (DL4J
        ``ComputationGraph.fit`` overloads).  ``resume=True`` restores
        the newest checkpoint from an attached ``CheckpointListener``
        first (``n_epochs`` is then the TOTAL epoch target)."""
        self._check_init()
        self._build_solver()
        if isinstance(data, (DataSet, MultiDataSet)):
            iterator: DataSetIterator = ListDataSetIterator([data])
            async_prefetch = False
        else:
            iterator = data
        wrapped = (AsyncDataSetIterator(iterator)
                   if async_prefetch and not isinstance(
                       iterator, AsyncDataSetIterator)
                   else iterator)

        return run_fit(self, wrapped, n_epochs, reset_target=iterator,
                       resume=resume)

    def compiled_train_step(self):
        """A reusable jitted full train step operating on a ``TrainState``
        — the benchmark/serving-loop entry (donated buffers, so params and
        optimizer state update in place in HBM)."""
        self._check_init()
        self._build_solver(alloc_opt_state=False)
        model = self

        class _Step:
            def init(self) -> TrainState:
                # COPIES of the model trees: the step donates its buffers,
                # so handing over the model's own arrays would leave the
                # model holding deleted HBM buffers after the first call.
                params = jax.tree_util.tree_map(jnp.copy, model.params_tree)
                mstate = jax.tree_util.tree_map(jnp.copy, model.state_tree)
                return TrainState(params,
                                  model._solver.init_opt_state(params),
                                  mstate,
                                  jnp.zeros((), jnp.int32))

            def __call__(self, st: TrainState, features, labels,
                         features_mask=None, labels_mask=None):
                batch = {"features": features, "labels": labels}
                if features_mask is not None:
                    batch["features_mask"] = features_mask
                if labels_mask is not None:
                    batch["labels_mask"] = labels_mask
                params, opt_state, mstate, loss = model._solver.step(
                    st.params, st.opt_state, st.model_state, st.step, batch,
                    model._rng.next_key())
                return TrainState(params, opt_state, mstate, st.step + 1), loss

        return _Step()

    @staticmethod
    def _tbptt_chunks(ds: Union[DataSet, MultiDataSet], length: int):
        from deeplearning4j_tpu.data.dataset import tbptt_segments
        return tbptt_segments(ds, length)

    # ------------------------------------------------------------------
    # Recurrent state (DL4J ComputationGraph.rnnTimeStep analogues)
    # ------------------------------------------------------------------
    def _has_rnn(self) -> bool:
        return any(getattr(ly, "IS_RNN", False)
                   for _, ly in self._layer_vertices())

    def rnn_clear_previous_state(self):
        from deeplearning4j_tpu.nn.conf.layers_recurrent import strip_rnn_carry
        if self.state_tree is not None:
            self.state_tree = strip_rnn_carry(self.state_tree)

    # ------------------------------------------------------------------
    # Inference / scoring
    # ------------------------------------------------------------------
    def output(self, *inputs, training: bool = False, features_mask=None):
        """Forward pass (DL4J ``ComputationGraph.output(INDArray...)``).
        Returns a single array for single-output nets, else a list in
        ``network_outputs`` order."""
        self._check_init()
        if len(inputs) == 1:
            x = inputs[0]
        else:
            x = list(inputs)
        ins = {k: jnp.asarray(v)
               for k, v in self._as_input_dict(x).items()}
        masks = None
        if features_mask is not None:
            masks = {k: jnp.asarray(v) for k, v in
                     self._as_input_dict(features_mask).items()}
        if training:
            acts, _, _, _ = self._forward_all(
                self.params_tree, self.state_tree, ins, True,
                self._rng.next_key(), masks=masks)
            outs = {n: acts[n] for n in self.conf.network_outputs}
        else:
            outs = self._output_fn(self.params_tree, self.state_tree, ins,
                                   masks)
        vals = [outs[n] for n in self.conf.network_outputs]
        return vals[0] if len(vals) == 1 else vals

    def feed_forward(self, inputs, training: bool = False) -> Dict[str, Any]:
        """All vertex activations by name (DL4J ``feedForward``)."""
        self._check_init()
        ins = {k: jnp.asarray(v)
               for k, v in self._as_input_dict(inputs).items()}
        rng = self._rng.next_key() if training else None
        acts, _, _, _ = self._forward_all(self.params_tree, self.state_tree,
                                          ins, training, rng)
        return acts

    def score(self, ds: Union[DataSet, MultiDataSet]) -> float:
        self._check_init()
        return float(self._score_fn(self.params_tree, self.state_tree,
                                    self._batch_dict(ds)))

    def evaluate(self, iterator: DataSetIterator, top_n: int = 1) -> Evaluation:
        """Single-output classification eval (DL4J ``evaluate``)."""
        self._check_init()
        ev = Evaluation(top_n=top_n)
        for ds in iterator:
            out = self.output(ds.features,
                              features_mask=ds.features_mask)
            ev.eval(ds.labels, np.asarray(out), ds.labels_mask)
        iterator.reset()
        return ev

    def evaluate_regression(self, iterator) -> RegressionEvaluation:
        self._check_init()
        ev = RegressionEvaluation()
        for ds in iterator:
            ev.eval(ds.labels, np.asarray(self.output(ds.features)),
                    ds.labels_mask)
        iterator.reset()
        return ev

    def evaluate_roc(self, iterator, exact: bool = True) -> ROCMultiClass:
        self._check_init()
        roc = ROCMultiClass(exact=exact)
        for ds in iterator:
            roc.eval(ds.labels, np.asarray(self.output(ds.features)),
                     ds.labels_mask)
        iterator.reset()
        return roc

    # ------------------------------------------------------------------
    # Parameter access
    # ------------------------------------------------------------------
    def _leaf_order(self):
        from deeplearning4j_tpu.utils.trees import iter_leaves
        for name in self.conf.topological_order:
            for path, leaf in iter_leaves(self.params_tree.get(name, {})):
                yield (name,) + path, leaf

    def params(self) -> np.ndarray:
        self._check_init()
        parts = [np.asarray(leaf).reshape(-1)
                 for _, leaf in self._leaf_order()]
        return (np.concatenate(parts) if parts
                else np.zeros((0,), np.float32))

    def set_params(self, vector: np.ndarray):
        from deeplearning4j_tpu.utils.trees import deep_copy_dicts, set_path
        self._check_init()
        vector = np.asarray(vector)
        off = 0
        new = deep_copy_dicts(self.params_tree)
        for path, arr in self._leaf_order():
            size = int(np.prod(arr.shape)) if arr.shape else 1
            set_path(new, path, jnp.asarray(
                vector[off:off + size].reshape(arr.shape), arr.dtype))
            off += size
        if off != vector.size:
            raise ValueError(f"Expected {off} values, got {vector.size}")
        self.params_tree = new

    def num_params(self) -> int:
        self._check_init()
        return sum(int(np.prod(np.asarray(l).shape))
                   for l in jax.tree_util.tree_leaves(self.params_tree))

    # ------------------------------------------------------------------
    def set_listeners(self, *listeners: TrainingListener):
        self.listeners = list(listeners)

    def add_listeners(self, *listeners: TrainingListener):
        self.listeners.extend(listeners)

    def clone(self) -> "ComputationGraph":
        m = ComputationGraph(ComputationGraphConfiguration.from_dict(
            self.conf.to_dict()))
        if self.params_tree is not None:
            m.params_tree = jax.tree_util.tree_map(lambda a: a,
                                                   self.params_tree)
            m.state_tree = jax.tree_util.tree_map(lambda a: a,
                                                  self.state_tree)
        m.iteration_count = self.iteration_count
        m.epoch_count = self.epoch_count
        return m

    def summary(self) -> str:
        from deeplearning4j_tpu.utils.trees import iter_leaves
        self._check_init()
        rows = [f"{'name':<28} {'type':<26} {'inputs':<30} {'#params':>10}"]
        total = 0
        for name in self.conf.topological_order:
            spec = self.conf.vertices[name]
            kind = (type(spec.layer).__name__ if spec.layer is not None
                    else type(spec.vertex).__name__)
            lp = self.params_tree.get(name, {})
            n = sum(int(np.prod(np.asarray(a).shape))
                    for _, a in iter_leaves(lp))
            total += n
            ins = ",".join(self.conf.vertex_inputs[name])
            rows.append(f"{name:<28} {kind:<26} {ins:<30} {n:>10}")
        rows.append(f"Total params: {total}")
        return "\n".join(rows)

    def save(self, path, save_updater: bool = True):
        from deeplearning4j_tpu.utils.model_serializer import write_model
        write_model(self, path, save_updater=save_updater)

    @staticmethod
    def load(path, load_updater: bool = True) -> "ComputationGraph":
        from deeplearning4j_tpu.utils.model_serializer import (
            restore_computation_graph)
        return restore_computation_graph(path, load_updater=load_updater)
