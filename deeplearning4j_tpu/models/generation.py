"""KV-cache incremental decoding — the transformer analogue of DL4J's
``rnnTimeStep`` (``MultiLayerNetwork.rnnTimeStep`` keeps per-layer
recurrent state between calls; here the state is each block's key/value
cache).

TPU-first design: generation is ONE jitted ``lax.scan`` over time with
static shapes — the KV caches are preallocated [n_layers, b, h,
max_len, dh] buffers written via ``lax.dynamic_update_slice``, the
prompt prefills in ONE batched causal forward (matmul-rate, not the
per-step params-bandwidth floor), and sampling scans one token per
tick — the whole decode is a single XLA program, no per-token Python
dispatch or retrace.  The homogeneous block params are stacked on a
leading [n_layers] axis and BOTH the prefill and the decode tick
``lax.scan`` over layers, so the program size is O(1) in depth instead
of inlining n_layers copies of the block body.

Concurrent serving over this machinery (many callers multiplexed onto
one decode tick, Orca-style continuous batching) lives in
``parallel/generation_server.py`` — ``_embed_token``/
``_block_decode_step`` accept per-row position VECTORS for exactly
that caller.

Works over any MultiLayerNetwork whose stack is
``EmbeddingSequenceLayer -> N x TransformerEncoderBlock(causal=True)
-> (Rnn)OutputLayer`` (e.g. ``zoo.Gpt``).  IMPORTED graphs (SameDiff
IR) are NOT decodable here yet: they fine-tune through
``fused_attention`` but have no cached-step form — a known gap (the
toy imported GPT is pre-LN, so it cannot be mapped onto the post-LN
zoo blocks either).
"""
from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.nn.conf.layers_core import OutputLayer
from deeplearning4j_tpu.nn.conf.layers_transformer import (
    EmbeddingSequenceLayer, TransformerEncoderBlock, _layer_norm)


# Decode telemetry: tokens are THE serving unit for a causal decoder;
# steps/s is the per-row tick rate the params-bandwidth roofline bounds
# (GENERATION_r05.json).  A generate() that retraces (new shape key)
# shows up as a latency outlier in generation_seconds, not a separate
# series — check _fn_cache hygiene when the histogram grows a tail.
_GEN_REQS = telemetry.counter(
    "generation_requests_total", "generate() calls")
_GEN_TOKENS = telemetry.counter(
    "generation_tokens_total", "new tokens emitted (rows x n_new)")
_GEN_RATE = telemetry.gauge(
    "generation_decode_steps_per_sec",
    "decode ticks/sec over the last generate() (per-row token rate)")
_GEN_TIME = telemetry.histogram(
    "generation_seconds",
    "wall time per generate() call incl. prefill, decode scan, host "
    "sync (first call per shape includes compile)")


def _embed_token(ly: EmbeddingSequenceLayer, params, tok, pos):
    """[b] int token -> [b, d].  ``pos`` is a scalar (one shared
    position, the offline decode scan) or a [b] int32 vector (per-row
    positions, the continuous-batching server's slots)."""
    y = jnp.take(params["W"], tok.astype(jnp.int32), axis=0)
    if ly.add_positional:
        if jnp.ndim(pos) == 0:
            y = y + jax.lax.dynamic_slice_in_dim(
                params["P"], pos, 1, axis=0)[0]
        else:
            y = y + jnp.take(params["P"], pos, axis=0)
    if ly.layer_norm:
        y = _layer_norm(y, params["g"], params["b"], ly.eps)
    return y


def _block_decode_step(ly: TransformerEncoderBlock, params, kcache,
                       vcache, x, pos):
    """One cached decoder step.  x: [b, d] new-token hidden; caches
    [b, h, L, dh]; writes position ``pos``, attends over <= pos.
    ``pos`` may be a scalar (whole batch at one position) or a [b]
    vector (per-row positions — slots in the generation server decode
    at independent depths inside ONE static-shape program).
    Returns (y [b, d], kcache, vcache)."""
    b, d = x.shape
    h, dh = ly.n_heads, d // ly.n_heads
    L = kcache.shape[2]
    cast = lambda w: w.astype(x.dtype)

    qkv = x @ cast(params["Wqkv"]) + cast(params["bqkv"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    split = lambda z: z.reshape(b, h, 1, dh)
    q, k, v = split(q), split(k), split(v)
    if jnp.ndim(pos) == 0:
        kcache = jax.lax.dynamic_update_slice(kcache, k, (0, 0, pos, 0))
        vcache = jax.lax.dynamic_update_slice(vcache, v, (0, 0, pos, 0))
        valid = (jnp.arange(L) <= pos)[None, None, None, :]
    else:
        write = jax.vmap(lambda c, n, p: jax.lax.dynamic_update_slice(
            c, n, (0, p, 0)))
        kcache = write(kcache, k, pos)
        vcache = write(vcache, v, pos)
        valid = (jnp.arange(L)[None, :]
                 <= pos[:, None])[:, None, None, :]

    scale = 1.0 / (dh ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kcache).astype(jnp.float32)
    s = s * scale
    s = jnp.where(valid, s, -1e9)
    p = jax.nn.softmax(s, axis=-1).astype(vcache.dtype)
    att = jnp.einsum("bhqk,bhkd->bhqd", p, vcache)
    att = att.transpose(0, 2, 1, 3).reshape(b, d)
    att = att @ cast(params["Wo"]) + cast(params["bo"])
    hdn = _layer_norm(x + att, params["ln1_g"], params["ln1_b"], ly.eps)

    from deeplearning4j_tpu.nn.activations import get_activation
    act = get_activation(ly.activation or "gelu")
    ffn = act(hdn @ cast(params["W1"]) + cast(params["b1"]))
    ffn = ffn @ cast(params["W2"]) + cast(params["b2"])
    y = _layer_norm(hdn + ffn, params["ln2_g"], params["ln2_b"], ly.eps)
    return y, kcache, vcache


def _block_decode_step_paged(ly: TransformerEncoderBlock, params,
                             kpool, vpool, x, pos, table, wblk, woff,
                             shard=None):
    """Paged-cache variant of ``_block_decode_step``: the slot's K/V
    live in pool blocks routed by a block table instead of a
    contiguous stripe.  x: [b, d] new-token hidden; ``kpool``/``vpool``
    [n_blocks, h, block_size, dh]; ``table`` [b, max_blocks] int32;
    the new K/V row lands at (``wblk``, ``woff``) per slot — the
    caller masks inactive slots to the scratch block 0 — and attention
    reads THROUGH the table (``kernels.paged_decode_attention``; the
    reference path mirrors the stripe step's f32-score/-1e9-mask math
    exactly, which is what byte parity with offline decode rests on).

    ``shard`` (a ``parallel.mesh.TpShardCtx``, or None = identity) is
    the mesh-sharded tick's parity contract: weights arrive with their
    OUTPUT columns sharded along ``tp`` (heads ride along when qkv
    splits), and ``shard.rep`` gathers the feature axis back to full
    replication at EXACTLY the points where the math reduces over it —
    before ``@ Wo``, both layer norms, and ``@ W2`` — so no device
    ever sums a partial feature axis.  Returns (y [b, d], kpool,
    vpool)."""
    from deeplearning4j_tpu.kernels import paged_decode_attention
    rep = shard.rep if shard is not None else (lambda t: t)
    b, d = x.shape
    h, dh = ly.n_heads, d // ly.n_heads
    cast = lambda w: w.astype(x.dtype)

    qkv = x @ cast(params["Wqkv"]) + cast(params["bqkv"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    split = lambda z: z.reshape(b, h, dh)
    q, k, v = split(q), split(k), split(v)
    kpool = kpool.at[wblk, :, woff, :].set(k)
    vpool = vpool.at[wblk, :, woff, :].set(v)

    att = paged_decode_attention(q, kpool, vpool, table, pos,
                                 scale=1.0 / (dh ** 0.5), shard=shard)
    att = rep(att.reshape(b, d))
    att = att @ cast(params["Wo"]) + cast(params["bo"])
    hdn = _layer_norm(rep(x + att), params["ln1_g"], params["ln1_b"],
                      ly.eps)

    from deeplearning4j_tpu.nn.activations import get_activation
    act = get_activation(ly.activation or "gelu")
    ffn = act(hdn @ cast(params["W1"]) + cast(params["b1"]))
    ffn = rep(ffn) @ cast(params["W2"]) + cast(params["b2"])
    y = _layer_norm(rep(hdn + ffn), params["ln2_g"], params["ln2_b"],
                    ly.eps)
    return y, kpool, vpool


def _block_verify_step_paged(ly: TransformerEncoderBlock, params,
                             kpool, vpool, x, table, wblk, woff, pos0,
                             shard=None):
    """W-token verification step for speculative decode: one block's
    forward over a chunk of W tokens per slot, K/V written through the
    block table at (``wblk``, ``woff``) [B, W] and attention read back
    through :func:`~deeplearning4j_tpu.kernels.paged_verify_attention`
    with query row j at position ``pos0 + j``.

    ``x`` is FLAT [B*W, d] — every matmul and layer norm here runs at
    the 2-D shapes that are row-bitwise-stable on the backends (the
    decode step's [b, d] @ W and a [B*W, d] @ W agree per row where a
    [B, W, d] batched contraction need not), and the attention unrolls
    per query row inside the kernel's reference path.  Together that
    makes this chunked step's outputs AND cache writes byte-identical
    to W sequential ``_block_decode_step_paged`` ticks — the invariant
    speculative greedy parity rests on.  ``shard`` replicates feature
    axes before their reductions exactly as in
    ``_block_decode_step_paged`` (the flat [B*W, d] rows keep their
    batch axis on ``data``)."""
    rep = shard.rep if shard is not None else (lambda t: t)
    BW, d = x.shape
    B, W = wblk.shape
    h, dh = ly.n_heads, d // ly.n_heads
    from deeplearning4j_tpu.kernels import paged_verify_attention
    cast = lambda w: w.astype(x.dtype)

    qkv = x @ cast(params["Wqkv"]) + cast(params["bqkv"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    split = lambda z: z.reshape(B, W, h, dh)
    q, k, v = split(q), split(k), split(v)
    kpool = kpool.at[wblk, :, woff, :].set(k)
    vpool = vpool.at[wblk, :, woff, :].set(v)

    att = paged_verify_attention(q, kpool, vpool, table, pos0,
                                 scale=1.0 / (dh ** 0.5), shard=shard)
    att = rep(att.reshape(BW, d))
    att = att @ cast(params["Wo"]) + cast(params["bo"])
    hdn = _layer_norm(rep(x + att), params["ln1_g"], params["ln1_b"],
                      ly.eps)

    from deeplearning4j_tpu.nn.activations import get_activation
    act = get_activation(ly.activation or "gelu")
    ffn = act(hdn @ cast(params["W1"]) + cast(params["b1"]))
    ffn = rep(ffn) @ cast(params["W2"]) + cast(params["b2"])
    y = _layer_norm(rep(hdn + ffn), params["ln2_g"], params["ln2_b"],
                    ly.eps)
    return y, kpool, vpool


def _embed_prompt(ly: EmbeddingSequenceLayer, params, ids):
    """[b, t0] int prompt -> [b, t0, d] (positions 0..t0-1)."""
    y = jnp.take(params["W"], ids.astype(jnp.int32), axis=0)
    if ly.add_positional:
        y = y + params["P"][: ids.shape[1]][None]
    if ly.layer_norm:
        y = _layer_norm(y, params["g"], params["b"], ly.eps)
    return y


def _block_prefill(ly: TransformerEncoderBlock, params, x, shard=None):
    """Whole-prompt causal forward for one block: x [b, t, d] ->
    (y [b, t, d], k [b, h, t, dh], v) — ONE batched pass instead of t
    cached single-token steps, so prefill runs at matmul rate instead
    of the per-step params-bandwidth floor.  Same math (f32 scores,
    -1e9 mask) as ``_block_decode_step``.  ``shard`` replicates the
    feature axis before its reductions (mesh-sharded admissions; the
    returned K/V rows stay head-sharded for the pool scatter)."""
    rep = shard.rep if shard is not None else (lambda t: t)
    b, t, d = x.shape
    h, dh = ly.n_heads, d // ly.n_heads
    cast = lambda w: w.astype(x.dtype)
    qkv = x @ cast(params["Wqkv"]) + cast(params["bqkv"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    split = lambda z: z.reshape(b, t, h, dh).transpose(0, 2, 1, 3)
    q, k, v = split(q), split(k), split(v)
    scale = 1.0 / (dh ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    rows = jnp.arange(t)[:, None]
    cols = jnp.arange(t)[None, :]
    s = jnp.where((cols <= rows)[None, None], s, -1e9)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    att = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    att = rep(att.transpose(0, 2, 1, 3).reshape(b, t, d))
    att = att @ cast(params["Wo"]) + cast(params["bo"])
    hdn = _layer_norm(rep(x + att), params["ln1_g"], params["ln1_b"],
                      ly.eps)
    from deeplearning4j_tpu.nn.activations import get_activation
    act = get_activation(ly.activation or "gelu")
    ffn = act(hdn @ cast(params["W1"]) + cast(params["b1"]))
    ffn = rep(ffn) @ cast(params["W2"]) + cast(params["b2"])
    y = _layer_norm(rep(hdn + ffn), params["ln2_g"], params["ln2_b"],
                    ly.eps)
    return y, k, v


def _block_prefill_chunked(ly: TransformerEncoderBlock, params, x,
                           pk, pv, p0, shard=None):
    """Chunked (suffix) causal forward for one block: the query rows
    are the UNCACHED prompt suffix at global positions p0..p0+s-1 and
    the key set is [cached prefix K/V ; suffix K/V].  x: [b, s, d];
    ``pk``/``pv``: [b, h, P, dh] gathered prefix rows (valid cols
    < ``p0`` — the pad tail up to P is masked).  Same f32-score /
    -1e9-mask / f32-softmax math as ``_block_prefill``; masked columns
    contribute EXACT zeros to the softmax, so the suffix rows come out
    byte-identical to the full-prompt prefill's — the prefix-cache hit
    path's parity contract.  Returns (y, k, v) with k/v the SUFFIX
    rows only.  ``shard`` replicates feature axes before their
    reductions (the gathered prefix K/V arrive head-sharded from the
    mesh-sharded pool and concatenate exactly)."""
    rep = shard.rep if shard is not None else (lambda t: t)
    b, s_len, d = x.shape
    h, dh = ly.n_heads, d // ly.n_heads
    cast = lambda w: w.astype(x.dtype)
    qkv = x @ cast(params["Wqkv"]) + cast(params["bqkv"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    split = lambda z: z.reshape(b, s_len, h, dh).transpose(0, 2, 1, 3)
    q, k, v = split(q), split(k), split(v)
    P = pk.shape[2]
    kk = jnp.concatenate([pk, k], axis=2)       # [b, h, P+s, dh]
    vv = jnp.concatenate([pv, v], axis=2)
    scale = 1.0 / (dh ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kk).astype(jnp.float32) * scale
    cols = jnp.arange(P + s_len)
    col_g = jnp.where(cols < P, cols, p0 + cols - P)   # global key pos
    col_ok = jnp.where(cols < P, cols < p0, True)      # prefix pad out
    rows_g = p0 + jnp.arange(s_len)
    mask = col_ok[None, :] & (col_g[None, :] <= rows_g[:, None])
    s = jnp.where(mask[None, None], s, -1e9)
    p = jax.nn.softmax(s, axis=-1).astype(vv.dtype)
    att = jnp.einsum("bhqk,bhkd->bhqd", p, vv)
    att = rep(att.transpose(0, 2, 1, 3).reshape(b, s_len, d))
    att = att @ cast(params["Wo"]) + cast(params["bo"])
    hdn = _layer_norm(rep(x + att), params["ln1_g"], params["ln1_b"],
                      ly.eps)
    from deeplearning4j_tpu.nn.activations import get_activation
    act = get_activation(ly.activation or "gelu")
    ffn = act(hdn @ cast(params["W1"]) + cast(params["b1"]))
    ffn = rep(ffn) @ cast(params["W2"]) + cast(params["b2"])
    y = _layer_norm(rep(hdn + ffn), params["ln2_g"], params["ln2_b"],
                    ly.eps)
    return y, k, v


def _filter_logits_rows(logits, top_k, top_p):
    """Per-row variant of ``_filter_logits`` for the generation
    server's vectorized sampler: ``top_k`` is a [b] int32 VECTOR (one
    k per slot; k == vocab disables filtering for that row — the
    minimum logit becomes the threshold and nothing is below it) and
    ``top_p`` is a [b] float32 VECTOR (one nucleus mass per slot;
    p >= 1 disables the cut for that row), so requests with different
    top-k/top-p settings ride one traced program."""
    V = logits.shape[-1]
    srt = jnp.sort(logits, axis=-1)              # ascending
    kth = jnp.take_along_axis(srt, (V - top_k)[:, None], axis=-1)
    logits = jnp.where(logits < kth, -jnp.inf, logits)
    srt_d = jnp.sort(logits, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(srt_d, axis=-1)
    csum = jnp.cumsum(probs, axis=-1)
    # drop tokens whose preceding cumulative mass already covers p
    # (the top token always survives); the p >= 1 guard keeps "off"
    # rows EXACTLY unfiltered even when float cumsum rounds past 1
    cut = ((csum - probs) >= top_p[:, None]) & (top_p[:, None] < 1.0)
    srt_d = jnp.where(cut, jnp.inf, srt_d)
    thresh = jnp.min(srt_d, axis=-1, keepdims=True)
    return jnp.where(logits < thresh, -jnp.inf, logits)


def _filtered_logprobs_rows(logits, temp, top_k, top_p):
    """Log-probabilities of each row's ACTUAL sampling distribution:
    temperature-scale (rows with temp <= 0 are greedy — scaled by 1 so
    the row stays finite; callers mask them out), top-k/top-p filter
    via ``_filter_logits_rows``, then log-softmax (-inf survives for
    filtered-out tokens).  This is the density the speculative
    accept/residual math needs on BOTH sides of the rejection test —
    the draft's proposal distribution and the target's verify
    distribution must be the post-filter ones, or the committed stream
    drifts from what direct sampling would produce."""
    safe = jnp.where(temp > 0.0, temp, 1.0)
    lg = _filter_logits_rows(logits / safe[:, None], top_k, top_p)
    return jax.nn.log_softmax(lg, axis=-1)


def _filter_logits(logits, top_k, top_p):
    """Nucleus/top-k filtering on [b, V] logits (already
    temperature-scaled): outside-the-set entries go to -inf."""
    if top_k is not None:
        kth = jnp.sort(logits, axis=-1)[:, -int(top_k)][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        srt = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(srt, axis=-1)
        csum = jnp.cumsum(probs, axis=-1)
        # drop tokens whose preceding cumulative mass already covers p
        # (the top token always survives)
        cut = (csum - probs) >= float(top_p)
        srt = jnp.where(cut, jnp.inf, srt)
        thresh = jnp.min(srt, axis=-1, keepdims=True)
        logits = jnp.where(logits < thresh, -jnp.inf, logits)
    return logits


class TransformerGenerator:
    """Greedy / temperature / top-k / nucleus sampling with KV caches
    over a decoder MLN.  The prompt is prefilled in ONE batched causal
    forward (matmul-rate), then decode scans one token at a time.

    >>> gen = TransformerGenerator(net)
    >>> out = gen.generate(prompt_ids, n_new=64)      # [b, t0+64]
    >>> out = gen.generate(prompt_ids, n_new=64, temperature=0.8,
    ...                    top_k=40, top_p=0.95)
    """

    def __init__(self, net, compute_dtype: Optional[str] = None):
        layers = list(net.layers)
        if not isinstance(layers[0], EmbeddingSequenceLayer):
            raise ValueError("generator expects EmbeddingSequenceLayer "
                             f"first, got {type(layers[0]).__name__}")
        if not all(isinstance(l, TransformerEncoderBlock)
                   for l in layers[1:-1]):
            raise ValueError("generator expects a pure "
                             "TransformerEncoderBlock stack")
        for l in layers[1:-1]:
            if not l.causal:
                raise ValueError("generation requires causal=True blocks")
        import dataclasses
        ref = dataclasses.asdict(layers[1])
        if any(dataclasses.asdict(l) != ref for l in layers[2:-1]):
            # the decode tick stacks the block params on a leading axis
            # and lax.scans over layers (ONE traced block body instead
            # of n_layers inlined copies) — that stack needs
            # conf-identical blocks.  Every in-tree decoder (zoo.Gpt)
            # is homogeneous.
            raise ValueError("generator requires conf-identical "
                             "TransformerEncoderBlocks (the decode "
                             "tick scans stacked block params)")
        self.net = net
        self.emb = layers[0]
        self.blocks = layers[1:-1]
        self.head = layers[-1]
        if not isinstance(self.head, OutputLayer):
            # RnnOutputLayer subclasses OutputLayer: any W/b softmax
            # head over the final hidden state decodes
            raise ValueError("generator expects an (Rnn)OutputLayer "
                             f"head, got {type(self.head).__name__}")
        self.compute_dtype = (jnp.dtype(compute_dtype)
                              if compute_dtype else jnp.float32)
        self._fn_cache = {}

    def _params(self):
        self.net._check_init()   # fires any lazy _param_sync_hook
        pt = self.net.params_tree
        n = len(self.net.layers)
        return (pt["layer_0"],
                [pt[f"layer_{i}"] for i in range(1, n - 1)],
                pt[f"layer_{n - 1}"])

    @staticmethod
    def _stack_blocks(blk_ps):
        """List of per-block param dicts -> one dict with a leading
        [n_layers] axis on every leaf — the layout ``_step``'s
        layer-scan consumes.  Inside jit the stack is a compile-time
        concatenate; the scan body then references ONE block's worth of
        program, so the decode tick's XLA program size stays O(1) in
        depth instead of inlining n_layers copies."""
        return jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *blk_ps)

    def _step(self, emb_p, blk_stack, head_p, kc, vc, tok, pos):
        """One decode tick.  ``blk_stack`` is ``_stack_blocks`` output;
        ``kc``/``vc`` are [n_layers, b, h, L, dh]; ``pos`` is a scalar
        (offline scan) or [b] vector (server slots).  Returns
        (logits [b, V], kc, vc)."""
        x = _embed_token(self.emb, emb_p, tok, pos)
        x = x.astype(self.compute_dtype)
        ly = self.blocks[0]          # conf-identical (checked in init)

        def body(h, layer):
            p, kc_l, vc_l = layer
            h, kc_l, vc_l = _block_decode_step(ly, p, kc_l, vc_l, h, pos)
            return h, (kc_l, vc_l)

        x, (kc, vc) = jax.lax.scan(body, x, (blk_stack, kc, vc))
        logits = (x.astype(jnp.float32) @ head_p["W"] + head_p["b"])
        return logits, kc, vc

    def _step_paged(self, emb_p, blk_stack, head_p, kc, vc, tok, pos,
                    table, wblk, woff, shard=None):
        """Paged-pool decode tick: ``kc``/``vc`` are the global block
        pools [n_layers, n_blocks, h, block_size, dh], ``table``
        [b, max_blocks] the per-slot block tables, and the new row
        lands at (``wblk``, ``woff``) per slot.  Same layer-scan
        structure as ``_step``; attention routes through
        ``kernels.paged_decode_attention``.  ``shard`` (TpShardCtx)
        turns this into the mesh-sharded tick: embeds replicate, block
        math shards heads/columns along ``tp`` with explicit
        replication before feature reductions, and the logits gather
        so the sampler's argmax/sort runs on the full vocab row —
        byte-identical to the unsharded program by construction."""
        x = _embed_token(self.emb, emb_p, tok, pos)
        x = x.astype(self.compute_dtype)
        if shard is not None:
            x = shard.rep(x)
        ly = self.blocks[0]          # conf-identical (checked in init)

        def body(h, layer):
            p, kc_l, vc_l = layer
            h, kc_l, vc_l = _block_decode_step_paged(
                ly, p, kc_l, vc_l, h, pos, table, wblk, woff,
                shard=shard)
            return h, (kc_l, vc_l)

        x, (kc, vc) = jax.lax.scan(body, x, (blk_stack, kc, vc))
        logits = (x.astype(jnp.float32) @ head_p["W"] + head_p["b"])
        if shard is not None:
            logits = shard.rep(logits)
        return logits, kc, vc

    def _verify_rows_paged(self, emb_p, blk_stack, head_p, kc, vc,
                           toks, pos0, epos, table, wblk, woff,
                           shard=None):
        """Speculative verification forward: ONE batched pass over a
        chunk of W tokens per slot — ``toks`` [B, W] (the anchor + the
        draft's proposals, inactive rows masked to 0), ``pos0`` [B]
        the chunk's base position per slot, ``epos`` [B, W] the embed
        positions (masked rows clamped to 0 so the positional take
        never reads out of bounds — the PR 2 NaN class), ``wblk`` /
        ``woff`` [B, W] the per-token write targets through the
        slot's block table (masked rows at the scratch block 0).

        Returns (logits [B, W, V], kc, vc): logits at EVERY chunk
        position — G_j is the target's distribution after consuming
        tokens 0..j, which is both the acceptance judge and the held
        logits the round hands forward.  Flat-row matmuls + the
        per-row attention contract (``_block_verify_step_paged``)
        make logits AND cache writes bitwise equal to W sequential
        ``_step_paged`` ticks."""
        B, W = toks.shape
        ly = self.blocks[0]
        flat_tok = toks.reshape(B * W).astype(jnp.int32)
        y = jnp.take(emb_p["W"], flat_tok, axis=0)
        if self.emb.add_positional:
            y = y + jnp.take(emb_p["P"], epos.reshape(B * W), axis=0)
        if self.emb.layer_norm:
            y = _layer_norm(y, emb_p["g"], emb_p["b"], self.emb.eps)
        x = y.astype(self.compute_dtype)
        if shard is not None:
            x = shard.rep(x)

        def body(h, layer):
            p, kc_l, vc_l = layer
            h, kc_l, vc_l = _block_verify_step_paged(
                ly, p, kc_l, vc_l, h, table, wblk, woff, pos0,
                shard=shard)
            return h, (kc_l, vc_l)

        x, (kc, vc) = jax.lax.scan(body, x, (blk_stack, kc, vc))
        logits = (x.astype(jnp.float32) @ head_p["W"] + head_p["b"])
        if shard is not None:
            logits = shard.rep(logits)
        return logits.reshape(B, W, -1), kc, vc

    def _prefill_rows_chunked(self, emb_p, blk_stack, head_p, suffix,
                              pk, pv, p0, last_ix, shard=None):
        """Chunked-prefill counterpart of ``_prefill_rows`` for
        prefix-cache HITS: ``suffix`` [b, s] are the uncached prompt
        tokens at global positions p0..p0+s-1 (pad tail beyond the
        real suffix), ``pk``/``pv`` [n_layers, b, h, P, dh] the cached
        prefix K/V gathered out of the block pool (valid cols < p0).
        Returns (logits [b, V] at local row ``last_ix`` = t0-p0-1, ks,
        vs [n_layers, b, h, s, dh]) — the SUFFIX rows only, for the
        caller to scatter into fresh blocks.  Prefill runs only on the
        suffix: the prefix's compute is the work the cache saves."""
        cd = self.compute_dtype
        ly = self.blocks[0]
        pos = p0 + jnp.arange(suffix.shape[1])
        y = jnp.take(emb_p["W"], suffix.astype(jnp.int32), axis=0)
        if self.emb.add_positional:
            # same rows _embed_prompt's [:t] slice reads; take clamps
            # the pad tail (finite garbage, masked before any read)
            y = y + jnp.take(emb_p["P"], pos, axis=0)
        if self.emb.layer_norm:
            y = _layer_norm(y, emb_p["g"], emb_p["b"], self.emb.eps)
        x = y.astype(cd)
        if shard is not None:
            x = shard.rep(x)

        def body(hdn, layer):
            p, pk_l, pv_l = layer
            hdn, k, v = _block_prefill_chunked(ly, p, hdn, pk_l, pv_l,
                                               p0, shard=shard)
            return hdn, (k.astype(cd), v.astype(cd))

        x, (ks, vs) = jax.lax.scan(body, x, (blk_stack, pk, pv))
        last = jax.lax.dynamic_slice_in_dim(x, last_ix, 1, axis=1)[:, 0]
        logits = last.astype(jnp.float32) @ head_p["W"] + head_p["b"]
        if shard is not None:
            logits = shard.rep(logits)
        return logits, ks, vs

    def generate(self, prompt_ids, n_new: int, temperature: float = 0.0,
                 seed: int = 0, max_len: Optional[int] = None,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None):
        """[b, t0] int prompt -> [b, t0 + n_new].  temperature == 0 is
        greedy argmax; > 0 samples logits/temperature, optionally
        filtered to the top-k tokens and/or the top-p nucleus."""
        prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
        b, t0 = prompt_ids.shape
        total = t0 + n_new
        L = max_len or total
        if L < total:
            raise ValueError(f"max_len {L} < prompt+new {total}")
        if self.emb.add_positional and L > self.emb.max_len:
            # past the table, dynamic_slice would silently clamp and
            # every later position would reuse the LAST positional row
            raise ValueError(
                f"generation length {L} exceeds the model's positional "
                f"table ({self.emb.max_len} rows); re-configure "
                "EmbeddingSequenceLayer.max_len or shorten the request")
        if (top_k is not None or top_p is not None) and temperature <= 0:
            raise ValueError("top_k/top_p need temperature > 0 "
                             "(greedy ignores the filtered tail)")
        if top_k is not None:
            # ADVICE r5: JAX clamps out-of-range sort indices, so
            # top_k=0 / top_k>vocab would SILENTLY disable filtering
            # (kth becomes the min logit); top_k is static per jit key,
            # so a plain Python check catches it here.
            vocab = int(np.shape(self._params()[2]["W"])[-1])
            if not 1 <= int(top_k) <= vocab:
                raise ValueError(
                    f"top_k={top_k} out of range [1, {vocab}] "
                    "(vocab size)")
        key = (b, t0, n_new, L, float(temperature), top_k,
               None if top_p is None else float(top_p))
        if key not in self._fn_cache:
            self._fn_cache[key] = jax.jit(
                lambda e, bl, h, ids, k: self._generate_scan(
                    e, bl, h, ids, k, t0, n_new, L, temperature,
                    top_k, top_p))
        emb_p, blk_ps, head_p = self._params()
        ids = jnp.concatenate(
            [prompt_ids, jnp.zeros((b, n_new), jnp.int32)], axis=1)
        t_start = time.perf_counter()
        with telemetry.span("generate", batch=b, prompt=t0, new=n_new):
            out = np.asarray(self._fn_cache[key](
                emb_p, blk_ps, head_p, ids, jax.random.PRNGKey(seed)))
        dt = time.perf_counter() - t_start
        _GEN_REQS.inc()
        _GEN_TOKENS.inc(b * n_new)
        _GEN_TIME.observe(dt)
        if dt > 0:
            _GEN_RATE.set(n_new / dt)
        return out

    def _prefill_rows(self, emb_p, blk_stack, head_p, prompt, t0=None,
                      shard=None):
        """Batched prompt pass scanned over the stacked block params.
        Returns (logits [b, V], ks, vs [n_layers, b, h, t, dh]) — the
        raw per-layer K/V rows, for the caller to place (offline decode
        zero-pads to L; the generation server scatters into a slot's
        cache stripe).  ``t0`` picks the logits position for prompts
        PADDED past their real length (causal masking makes position
        t0-1 independent of the pad tail); default is the last column.
        THE prefill numerics both decode paths share — byte-identical
        greedy parity between them depends on exactly this."""
        cd = self.compute_dtype
        ly = self.blocks[0]
        x = _embed_prompt(self.emb, emb_p, prompt)
        x = x.astype(cd)
        if shard is not None:
            x = shard.rep(x)

        def body(hdn, p):
            hdn, k, v = _block_prefill(ly, p, hdn, shard=shard)
            return hdn, (k.astype(cd), v.astype(cd))

        x, (ks, vs) = jax.lax.scan(body, x, blk_stack)
        if t0 is None:
            last = x[:, -1]
        else:
            last = jax.lax.dynamic_slice_in_dim(x, t0 - 1, 1,
                                                axis=1)[:, 0]
        logits = last.astype(jnp.float32) @ head_p["W"] + head_p["b"]
        if shard is not None:
            logits = shard.rep(logits)
        return logits, ks, vs

    def _prefill(self, emb_p, blk_stack, head_p, prompt, L):
        """``_prefill_rows`` + zero-padded caches out to length L,
        stacked [n_layers, b, h, L, dh] — ``_step``'s layout."""
        b = prompt.shape[0]
        h = self.blocks[0].n_heads
        dh = self.emb.n_out // h
        n_layers = len(self.blocks)
        cd = self.compute_dtype
        logits, ks, vs = self._prefill_rows(emb_p, blk_stack, head_p,
                                            prompt)
        kc = jnp.zeros((n_layers, b, h, L, dh), cd)
        vc = jnp.zeros((n_layers, b, h, L, dh), cd)
        kc = jax.lax.dynamic_update_slice(kc, ks, (0, 0, 0, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, vs, (0, 0, 0, 0, 0))
        return logits, kc, vc

    def _generate_scan(self, emb_p, blk_ps, head_p, ids, rng_key,
                       t0, n_new, L, temperature, top_k=None,
                       top_p=None):
        if self.compute_dtype != jnp.float32:
            # cast the full parameter set ONCE inside the program: the
            # decode scan re-reads every parameter each tick, and
            # streaming f32-stored weights costs 2x the bytes of the
            # bf16 math actually performed (measured 840 -> 969
            # steps/s on zoo.Gpt; the tick also carries per-op
            # overheads the byte halving cannot remove)
            cast = lambda t: jax.tree_util.tree_map(
                lambda a: (a.astype(self.compute_dtype)
                           if jnp.issubdtype(a.dtype, jnp.floating)
                           else a), t)
            emb_p, blk_ps, head_p = cast(emb_p), cast(blk_ps), \
                cast(head_p)
        blk_stack = self._stack_blocks(blk_ps)
        prompt = ids[:, :t0]
        logits0, kc, vc = self._prefill(emb_p, blk_stack, head_p,
                                        prompt, L)

        def sample(logits, key):
            if temperature > 0.0:
                key, sub = jax.random.split(key)
                lg = _filter_logits(logits / temperature, top_k, top_p)
                nxt = jax.random.categorical(sub, lg, axis=-1)
            else:
                nxt = jnp.argmax(logits, axis=-1)
            return nxt.astype(jnp.int32), key

        def body(carry, pos):
            # sample the token AT pos from the previous logits, write
            # it, embed it, advance the caches
            ids, kc, vc, key, logits = carry
            nxt, key = sample(logits, key)
            ids = jax.lax.dynamic_update_slice(ids, nxt[:, None],
                                               (0, pos))
            logits, kc, vc = self._step(emb_p, blk_stack, head_p,
                                        kc, vc, nxt, pos)
            return (ids, kc, vc, key, logits), None

        (ids, _, _, _, _), _ = jax.lax.scan(
            body, (ids, kc, vc, rng_key, logits0),
            t0 + jnp.arange(n_new))
        return ids
