"""KV-cache incremental decoding — the transformer analogue of DL4J's
``rnnTimeStep`` (``MultiLayerNetwork.rnnTimeStep`` keeps per-layer
recurrent state between calls; here the state is each block's key/value
cache).

TPU-first design: generation is ONE jitted ``lax.scan`` over time with
static shapes — the KV caches are preallocated [b, h, max_len, dh]
buffers written via ``lax.dynamic_update_slice``, the prompt prefills
in ONE batched causal forward (matmul-rate, not the per-step
params-bandwidth floor), and sampling scans one token per tick — the
whole decode is a single XLA program, no per-token Python dispatch or
retrace.

Works over any MultiLayerNetwork whose stack is
``EmbeddingSequenceLayer -> N x TransformerEncoderBlock(causal=True)
-> (Rnn)OutputLayer`` (e.g. ``zoo.Gpt``).  IMPORTED graphs (SameDiff
IR) are NOT decodable here yet: they fine-tune through
``fused_attention`` but have no cached-step form — a known gap (the
toy imported GPT is pre-LN, so it cannot be mapped onto the post-LN
zoo blocks either).
"""
from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.nn.conf.layers_core import OutputLayer
from deeplearning4j_tpu.nn.conf.layers_transformer import (
    EmbeddingSequenceLayer, TransformerEncoderBlock, _layer_norm)


# Decode telemetry: tokens are THE serving unit for a causal decoder;
# steps/s is the per-row tick rate the params-bandwidth roofline bounds
# (GENERATION_r05.json).  A generate() that retraces (new shape key)
# shows up as a latency outlier in generation_seconds, not a separate
# series — check _fn_cache hygiene when the histogram grows a tail.
_GEN_REQS = telemetry.counter(
    "generation_requests_total", "generate() calls")
_GEN_TOKENS = telemetry.counter(
    "generation_tokens_total", "new tokens emitted (rows x n_new)")
_GEN_RATE = telemetry.gauge(
    "generation_decode_steps_per_sec",
    "decode ticks/sec over the last generate() (per-row token rate)")
_GEN_TIME = telemetry.histogram(
    "generation_seconds",
    "wall time per generate() call incl. prefill, decode scan, host "
    "sync (first call per shape includes compile)")


def _embed_token(ly: EmbeddingSequenceLayer, params, tok, pos):
    """[b] int token at scalar position -> [b, d]."""
    y = jnp.take(params["W"], tok.astype(jnp.int32), axis=0)
    if ly.add_positional:
        y = y + jax.lax.dynamic_slice_in_dim(
            params["P"], pos, 1, axis=0)[0]
    if ly.layer_norm:
        y = _layer_norm(y, params["g"], params["b"], ly.eps)
    return y


def _block_decode_step(ly: TransformerEncoderBlock, params, kcache,
                       vcache, x, pos):
    """One cached decoder step.  x: [b, d] new-token hidden; caches
    [b, h, L, dh]; writes position ``pos``, attends over <= pos.
    Returns (y [b, d], kcache, vcache)."""
    b, d = x.shape
    h, dh = ly.n_heads, d // ly.n_heads
    L = kcache.shape[2]
    cast = lambda w: w.astype(x.dtype)

    qkv = x @ cast(params["Wqkv"]) + cast(params["bqkv"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    split = lambda z: z.reshape(b, h, 1, dh)
    q, k, v = split(q), split(k), split(v)
    kcache = jax.lax.dynamic_update_slice(kcache, k, (0, 0, pos, 0))
    vcache = jax.lax.dynamic_update_slice(vcache, v, (0, 0, pos, 0))

    scale = 1.0 / (dh ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kcache).astype(jnp.float32)
    s = s * scale
    valid = jnp.arange(L) <= pos                      # causal: <= pos
    s = jnp.where(valid[None, None, None, :], s, -1e9)
    p = jax.nn.softmax(s, axis=-1).astype(vcache.dtype)
    att = jnp.einsum("bhqk,bhkd->bhqd", p, vcache)
    att = att.transpose(0, 2, 1, 3).reshape(b, d)
    att = att @ cast(params["Wo"]) + cast(params["bo"])
    hdn = _layer_norm(x + att, params["ln1_g"], params["ln1_b"], ly.eps)

    from deeplearning4j_tpu.nn.activations import get_activation
    act = get_activation(ly.activation or "gelu")
    ffn = act(hdn @ cast(params["W1"]) + cast(params["b1"]))
    ffn = ffn @ cast(params["W2"]) + cast(params["b2"])
    y = _layer_norm(hdn + ffn, params["ln2_g"], params["ln2_b"], ly.eps)
    return y, kcache, vcache


def _embed_prompt(ly: EmbeddingSequenceLayer, params, ids):
    """[b, t0] int prompt -> [b, t0, d] (positions 0..t0-1)."""
    y = jnp.take(params["W"], ids.astype(jnp.int32), axis=0)
    if ly.add_positional:
        y = y + params["P"][: ids.shape[1]][None]
    if ly.layer_norm:
        y = _layer_norm(y, params["g"], params["b"], ly.eps)
    return y


def _block_prefill(ly: TransformerEncoderBlock, params, x):
    """Whole-prompt causal forward for one block: x [b, t, d] ->
    (y [b, t, d], k [b, h, t, dh], v) — ONE batched pass instead of t
    cached single-token steps, so prefill runs at matmul rate instead
    of the per-step params-bandwidth floor.  Same math (f32 scores,
    -1e9 mask) as ``_block_decode_step``."""
    b, t, d = x.shape
    h, dh = ly.n_heads, d // ly.n_heads
    cast = lambda w: w.astype(x.dtype)
    qkv = x @ cast(params["Wqkv"]) + cast(params["bqkv"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    split = lambda z: z.reshape(b, t, h, dh).transpose(0, 2, 1, 3)
    q, k, v = split(q), split(k), split(v)
    scale = 1.0 / (dh ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    rows = jnp.arange(t)[:, None]
    cols = jnp.arange(t)[None, :]
    s = jnp.where((cols <= rows)[None, None], s, -1e9)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    att = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    att = att.transpose(0, 2, 1, 3).reshape(b, t, d)
    att = att @ cast(params["Wo"]) + cast(params["bo"])
    hdn = _layer_norm(x + att, params["ln1_g"], params["ln1_b"], ly.eps)
    from deeplearning4j_tpu.nn.activations import get_activation
    act = get_activation(ly.activation or "gelu")
    ffn = act(hdn @ cast(params["W1"]) + cast(params["b1"]))
    ffn = ffn @ cast(params["W2"]) + cast(params["b2"])
    y = _layer_norm(hdn + ffn, params["ln2_g"], params["ln2_b"], ly.eps)
    return y, k, v


def _filter_logits(logits, top_k, top_p):
    """Nucleus/top-k filtering on [b, V] logits (already
    temperature-scaled): outside-the-set entries go to -inf."""
    if top_k is not None:
        kth = jnp.sort(logits, axis=-1)[:, -int(top_k)][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        srt = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(srt, axis=-1)
        csum = jnp.cumsum(probs, axis=-1)
        # drop tokens whose preceding cumulative mass already covers p
        # (the top token always survives)
        cut = (csum - probs) >= float(top_p)
        srt = jnp.where(cut, jnp.inf, srt)
        thresh = jnp.min(srt, axis=-1, keepdims=True)
        logits = jnp.where(logits < thresh, -jnp.inf, logits)
    return logits


class TransformerGenerator:
    """Greedy / temperature / top-k / nucleus sampling with KV caches
    over a decoder MLN.  The prompt is prefilled in ONE batched causal
    forward (matmul-rate), then decode scans one token at a time.

    >>> gen = TransformerGenerator(net)
    >>> out = gen.generate(prompt_ids, n_new=64)      # [b, t0+64]
    >>> out = gen.generate(prompt_ids, n_new=64, temperature=0.8,
    ...                    top_k=40, top_p=0.95)
    """

    def __init__(self, net, compute_dtype: Optional[str] = None):
        layers = list(net.layers)
        if not isinstance(layers[0], EmbeddingSequenceLayer):
            raise ValueError("generator expects EmbeddingSequenceLayer "
                             f"first, got {type(layers[0]).__name__}")
        if not all(isinstance(l, TransformerEncoderBlock)
                   for l in layers[1:-1]):
            raise ValueError("generator expects a pure "
                             "TransformerEncoderBlock stack")
        for l in layers[1:-1]:
            if not l.causal:
                raise ValueError("generation requires causal=True blocks")
        self.net = net
        self.emb = layers[0]
        self.blocks = layers[1:-1]
        self.head = layers[-1]
        if not isinstance(self.head, OutputLayer):
            # RnnOutputLayer subclasses OutputLayer: any W/b softmax
            # head over the final hidden state decodes
            raise ValueError("generator expects an (Rnn)OutputLayer "
                             f"head, got {type(self.head).__name__}")
        self.compute_dtype = (jnp.dtype(compute_dtype)
                              if compute_dtype else jnp.float32)
        self._fn_cache = {}

    def _params(self):
        pt = self.net.params_tree
        n = len(self.net.layers)
        return (pt["layer_0"],
                [pt[f"layer_{i}"] for i in range(1, n - 1)],
                pt[f"layer_{n - 1}"])

    def _step(self, emb_p, blk_ps, head_p, caches, tok, pos):
        x = _embed_token(self.emb, emb_p, tok, pos)
        x = x.astype(self.compute_dtype)
        new_caches = []
        for ly, p, (kc, vc) in zip(self.blocks, blk_ps, caches):
            x, kc, vc = _block_decode_step(ly, p, kc, vc, x, pos)
            new_caches.append((kc, vc))
        logits = (x.astype(jnp.float32) @ head_p["W"] + head_p["b"])
        return logits, new_caches

    def generate(self, prompt_ids, n_new: int, temperature: float = 0.0,
                 seed: int = 0, max_len: Optional[int] = None,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None):
        """[b, t0] int prompt -> [b, t0 + n_new].  temperature == 0 is
        greedy argmax; > 0 samples logits/temperature, optionally
        filtered to the top-k tokens and/or the top-p nucleus."""
        prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
        b, t0 = prompt_ids.shape
        total = t0 + n_new
        L = max_len or total
        if L < total:
            raise ValueError(f"max_len {L} < prompt+new {total}")
        if self.emb.add_positional and L > self.emb.max_len:
            # past the table, dynamic_slice would silently clamp and
            # every later position would reuse the LAST positional row
            raise ValueError(
                f"generation length {L} exceeds the model's positional "
                f"table ({self.emb.max_len} rows); re-configure "
                "EmbeddingSequenceLayer.max_len or shorten the request")
        if (top_k is not None or top_p is not None) and temperature <= 0:
            raise ValueError("top_k/top_p need temperature > 0 "
                             "(greedy ignores the filtered tail)")
        key = (b, t0, n_new, L, float(temperature), top_k,
               None if top_p is None else float(top_p))
        if key not in self._fn_cache:
            self._fn_cache[key] = jax.jit(
                lambda e, bl, h, ids, k: self._generate_scan(
                    e, bl, h, ids, k, t0, n_new, L, temperature,
                    top_k, top_p))
        emb_p, blk_ps, head_p = self._params()
        ids = jnp.concatenate(
            [prompt_ids, jnp.zeros((b, n_new), jnp.int32)], axis=1)
        t_start = time.perf_counter()
        with telemetry.span("generate", batch=b, prompt=t0, new=n_new):
            out = np.asarray(self._fn_cache[key](
                emb_p, blk_ps, head_p, ids, jax.random.PRNGKey(seed)))
        dt = time.perf_counter() - t_start
        _GEN_REQS.inc()
        _GEN_TOKENS.inc(b * n_new)
        _GEN_TIME.observe(dt)
        if dt > 0:
            _GEN_RATE.set(n_new / dt)
        return out

    def _prefill(self, emb_p, blk_ps, head_p, prompt, L):
        """Batched prompt pass: fill every block's KV cache for
        positions < t0 and return the last position's logits."""
        b, t0 = prompt.shape
        dh = self.emb.n_out // self.blocks[0].n_heads
        h = self.blocks[0].n_heads
        x = _embed_prompt(self.emb, emb_p, prompt)
        x = x.astype(self.compute_dtype)
        caches = []
        for ly, p in zip(self.blocks, blk_ps):
            x, k, v = _block_prefill(ly, p, x)
            kc = jnp.zeros((b, h, L, dh), self.compute_dtype)
            vc = jnp.zeros((b, h, L, dh), self.compute_dtype)
            kc = jax.lax.dynamic_update_slice(
                kc, k.astype(self.compute_dtype), (0, 0, 0, 0))
            vc = jax.lax.dynamic_update_slice(
                vc, v.astype(self.compute_dtype), (0, 0, 0, 0))
            caches.append((kc, vc))
        last = x[:, -1].astype(jnp.float32)
        logits = last @ head_p["W"] + head_p["b"]
        return logits, caches

    def _generate_scan(self, emb_p, blk_ps, head_p, ids, rng_key,
                       t0, n_new, L, temperature, top_k=None,
                       top_p=None):
        if self.compute_dtype != jnp.float32:
            # cast the full parameter set ONCE inside the program: the
            # decode scan re-reads every parameter each tick, and
            # streaming f32-stored weights costs 2x the bytes of the
            # bf16 math actually performed (measured 840 -> 969
            # steps/s on zoo.Gpt; the tick also carries per-op
            # overheads the byte halving cannot remove)
            cast = lambda t: jax.tree_util.tree_map(
                lambda a: (a.astype(self.compute_dtype)
                           if jnp.issubdtype(a.dtype, jnp.floating)
                           else a), t)
            emb_p, blk_ps, head_p = cast(emb_p), cast(blk_ps), \
                cast(head_p)
        prompt = ids[:, :t0]
        logits0, caches = self._prefill(emb_p, blk_ps, head_p, prompt,
                                        L)

        def sample(logits, key):
            if temperature > 0.0:
                key, sub = jax.random.split(key)
                lg = _filter_logits(logits / temperature, top_k, top_p)
                nxt = jax.random.categorical(sub, lg, axis=-1)
            else:
                nxt = jnp.argmax(logits, axis=-1)
            return nxt.astype(jnp.int32), key

        def body(carry, pos):
            # sample the token AT pos from the previous logits, write
            # it, embed it, advance the caches
            ids, caches, key, logits = carry
            nxt, key = sample(logits, key)
            ids = jax.lax.dynamic_update_slice(ids, nxt[:, None],
                                               (0, pos))
            logits, caches = self._step(emb_p, blk_ps, head_p, caches,
                                        nxt, pos)
            return (ids, caches, key, logits), None

        (ids, _, _, _), _ = jax.lax.scan(
            body, (ids, caches, rng_key, logits0),
            t0 + jnp.arange(n_new))
        return ids
