"""Keras model import.

Reference: ``deeplearning4j-modelimport
org.deeplearning4j.nn.modelimport.keras.KerasModelImport`` (~50k LoC of
per-layer ``KerasLayer`` mappings + weight copying over HDF5).  Here the
legacy ``.h5`` full-model format (the format DL4J consumed) is parsed
directly with h5py — config JSON → our layer confs, weight groups → our
param trees — with no keras runtime needed at import time.
"""
from deeplearning4j_tpu.keras_import.keras_import import KerasModelImport

__all__ = ["KerasModelImport"]
