"""Keras HDF5 → MultiLayerNetwork / ComputationGraph.

Scope (the layer set covering this repo's zoo, per VERDICT item 6):
InputLayer, Dense, Conv2D, DepthwiseConv2D, MaxPooling2D,
AveragePooling2D, GlobalAveragePooling2D, BatchNormalization, Flatten,
Dropout, Activation, ZeroPadding2D, Embedding, LSTM, Add, Concatenate.

Weight-layout facts used (verified against keras 3.13):
* Dense kernel [in, out] — identical to our ``DenseLayer`` "W".
* Conv2D kernel HWIO, channels_last — identical to our NHWC/HWIO stack.
* LSTM kernel [in, 4u], recurrent [u, 4u], bias [4u], gate order
  i, f, g(cell), o — identical to our fused LSTM layout.
* BatchNormalization: gamma, beta (params) + moving_mean, moving_variance
  (state).
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import h5py
import numpy as np

from deeplearning4j_tpu.models.computation_graph import ComputationGraph
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf.builder import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_vertices import (
    ElementWiseVertex, MergeVertex, PreprocessorVertex)
from deeplearning4j_tpu.nn.conf.inputs import InputType, Preprocessor
from deeplearning4j_tpu.nn.conf.layers_conv import (
    BatchNormalization, ConvolutionLayer, DepthwiseConvolution2D,
    GlobalPoolingLayer, SubsamplingLayer, ZeroPaddingLayer)
from deeplearning4j_tpu.nn.conf.layers_core import (
    ActivationLayer, DenseLayer, DropoutLayer, EmbeddingLayer, OutputLayer)
from deeplearning4j_tpu.nn.conf.layers_recurrent import (
    LSTM, LastTimeStep, RnnOutputLayer)

_ACTIVATIONS = {
    "linear": "identity", "relu": "relu", "relu6": "relu6",
    "sigmoid": "sigmoid", "tanh": "tanh", "softmax": "softmax",
    "softplus": "softplus", "softsign": "softsign", "elu": "elu",
    "selu": "selu", "gelu": "gelu", "swish": "swish",
    "hard_sigmoid": "hardsigmoid", "leaky_relu": "leakyrelu",
    "exponential": "exp",
}


def _act(name: Optional[str]) -> str:
    if not name:
        return "identity"
    out = _ACTIVATIONS.get(str(name).lower())
    if out is None:
        raise ValueError(f"Unsupported Keras activation {name!r}")
    return out


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


class KerasModelImport:
    """``KerasModelImport.importKerasSequentialModelAndWeights`` /
    ``importKerasModelAndWeights`` equivalents."""

    # ------------------------------------------------------------------
    @staticmethod
    def import_keras_model_and_weights(path: str):
        """Auto-detects Sequential vs Functional; returns
        MultiLayerNetwork or ComputationGraph with weights loaded."""
        with h5py.File(path, "r") as f:
            cfg = f.attrs.get("model_config")
            if cfg is None:
                raise ValueError(
                    f"{path!r} has no model_config attr — not a legacy "
                    "Keras full-model .h5 (Keras 3: save with "
                    "model.save('m.h5'))")
            d = json.loads(cfg)
            weights = KerasModelImport._read_weights(f["model_weights"])
        if d["class_name"] == "Sequential":
            return KerasModelImport._import_sequential(d["config"], weights)
        if d["class_name"] in ("Functional", "Model"):
            return KerasModelImport._import_functional(d["config"], weights)
        raise ValueError(f"Unsupported model class {d['class_name']!r}")

    # alias matching the DL4J static-method names
    import_keras_sequential_model_and_weights = \
        import_keras_model_and_weights

    # ------------------------------------------------------------------
    @staticmethod
    def _read_weights(grp) -> Dict[str, Dict[str, np.ndarray]]:
        """model_weights/<layer>/**/<leaf> → {layer: {leaf: array}}."""
        out: Dict[str, Dict[str, np.ndarray]] = {}
        for layer_name in grp:
            leaf: Dict[str, np.ndarray] = {}

            def visit(name, obj):
                if isinstance(obj, h5py.Dataset):
                    leaf[name.split("/")[-1].split(":")[0]] = np.asarray(obj)
            grp[layer_name].visititems(visit)
            if leaf:
                out[layer_name] = leaf
        return out

    # ------------------------------------------------------------------
    # Layer conversion
    # ------------------------------------------------------------------
    @staticmethod
    def _convert(cls_name: str, c: dict, is_last: bool):
        """One keras layer config → (our layer conf or None, params_map)
        where params_map maps our param name → keras leaf name."""
        name = c.get("name")
        if cls_name == "Dense":
            act = _act(c.get("activation"))
            if is_last:
                loss = "mcxent" if act == "softmax" else (
                    "xent" if act == "sigmoid" else "mse")
                ly = OutputLayer(n_out=c["units"], activation=act,
                                 loss=loss, has_bias=c.get("use_bias", True))
            else:
                ly = DenseLayer(n_out=c["units"], activation=act,
                                has_bias=c.get("use_bias", True))
            ly.name = name
            return ly, {"W": "kernel", "b": "bias"}
        if cls_name == "Conv2D":
            ly = ConvolutionLayer(
                n_out=c["filters"], kernel_size=_pair(c["kernel_size"]),
                stride=_pair(c.get("strides", 1)),
                dilation=_pair(c.get("dilation_rate", 1)),
                convolution_mode=("same" if c.get("padding") == "same"
                                  else "truncate"),
                activation=_act(c.get("activation")),
                has_bias=c.get("use_bias", True))
            ly.name = name
            return ly, {"W": "kernel", "b": "bias"}
        if cls_name == "DepthwiseConv2D":
            ly = DepthwiseConvolution2D(
                kernel_size=_pair(c["kernel_size"]),
                stride=_pair(c.get("strides", 1)),
                depth_multiplier=c.get("depth_multiplier", 1),
                convolution_mode=("same" if c.get("padding") == "same"
                                  else "truncate"),
                activation=_act(c.get("activation")),
                has_bias=c.get("use_bias", True))
            ly.name = name
            return ly, {"W": "depthwise_kernel", "b": "bias"}
        if cls_name in ("MaxPooling2D", "AveragePooling2D"):
            ly = SubsamplingLayer(
                kernel_size=_pair(c.get("pool_size", 2)),
                stride=_pair(c.get("strides") or c.get("pool_size", 2)),
                pooling_type="max" if cls_name.startswith("Max") else "avg",
                convolution_mode=("same" if c.get("padding") == "same"
                                  else "truncate"))
            ly.name = name
            return ly, {}
        if cls_name in ("GlobalAveragePooling2D", "GlobalMaxPooling2D"):
            ly = GlobalPoolingLayer(
                pooling_type="avg" if "Average" in cls_name else "max")
            ly.name = name
            return ly, {}
        if cls_name == "BatchNormalization":
            ly = BatchNormalization(eps=c.get("epsilon", 1e-3),
                                    decay=c.get("momentum", 0.99))
            ly.name = name
            return ly, {"gamma": "gamma", "beta": "beta",
                        "state:mean": "moving_mean",
                        "state:var": "moving_variance"}
        if cls_name == "Dropout":
            ly = DropoutLayer(rate=c.get("rate", 0.5))
            ly.name = name
            return ly, {}
        if cls_name == "Activation":
            ly = ActivationLayer(activation=_act(c.get("activation")))
            ly.name = name
            return ly, {}
        if cls_name == "ZeroPadding2D":
            pad = c.get("padding", 1)
            if isinstance(pad, (list, tuple)) and isinstance(
                    pad[0], (list, tuple)):
                pad = (pad[0][0], pad[0][1], pad[1][0], pad[1][1])
            ly = ZeroPaddingLayer(padding=pad)
            ly.name = name
            return ly, {}
        if cls_name == "Embedding":
            ly = EmbeddingLayer(n_in=c["input_dim"], n_out=c["output_dim"])
            ly.name = name
            return ly, {"W": "embeddings"}
        if cls_name == "LSTM":
            ly = LSTM(n_out=c["units"],
                      activation=_act(c.get("activation", "tanh")),
                      gate_activation=_act(
                          c.get("recurrent_activation", "sigmoid")))
            ly.name = name
            return ly, {"W": "kernel", "R": "recurrent_kernel", "b": "bias"}
        if cls_name == "Flatten":
            return None, {}  # our conv→ff preprocessor auto-inserts
        raise ValueError(
            f"Unsupported Keras layer {cls_name!r} ({name!r}) — extend "
            "deeplearning4j_tpu/keras_import/keras_import.py")

    @staticmethod
    def _input_type(batch_shape) -> InputType:
        dims = [d for d in batch_shape[1:]]
        if len(dims) == 3:
            return InputType.convolutional(dims[0], dims[1], dims[2])
        if len(dims) == 2:
            return InputType.recurrent(dims[1], dims[0])
        return InputType.feed_forward(dims[0])

    # ------------------------------------------------------------------
    @staticmethod
    def _import_sequential(cfg: dict, weights) -> MultiLayerNetwork:
        layers_cfg = cfg["layers"] if isinstance(cfg, dict) else cfg
        lb = NeuralNetConfiguration.builder().list()
        converted: List[Tuple[Any, Dict[str, str], str]] = []
        last_real = None
        for i, lc in enumerate(layers_cfg):
            if lc["class_name"] != "Flatten":
                last_real = i
        # A model ending Dense → Activation('softmax') must import as ONE
        # OutputLayer (activation folded in), not DenseLayer+ActivationLayer
        # — the latter leaves the network without a loss head and fails
        # later in fit() with a confusing error (advisor round 2).
        folded_act, skip_idx = None, None
        if layers_cfg and layers_cfg[-1]["class_name"] == "Activation":
            j = len(layers_cfg) - 2
            # Only Flatten may sit between (it is shape-only and never
            # emitted); a Dropout there changes training numerics, and a
            # Dense with its own non-linearity composes two activations
            # — both cases keep the un-folded import.
            while j >= 0 and layers_cfg[j]["class_name"] == "Flatten":
                j -= 1
            if j >= 0 and layers_cfg[j]["class_name"] == "Dense" and \
                    _act(layers_cfg[j]["config"].get("activation")) == \
                    "identity":
                skip_idx = len(layers_cfg) - 1
                last_real = j
                folded_act = layers_cfg[-1]["config"].get("activation")
        for i, lc in enumerate(layers_cfg):
            if i == skip_idx:
                continue
            cls, c = lc["class_name"], lc["config"]
            if i == last_real and folded_act is not None:
                c = dict(c, activation=folded_act)
            if cls == "InputLayer":
                shape = c.get("batch_shape") or c.get("batch_input_shape")
                lb.set_input_type(KerasModelImport._input_type(shape))
                continue
            if i == 0 and (c.get("batch_input_shape") is not None):
                lb.set_input_type(KerasModelImport._input_type(
                    c["batch_input_shape"]))
            ly, pmap = KerasModelImport._convert(cls, c, i == last_real)
            if ly is None:
                continue
            # keras LSTM with return_sequences=False: append LastTimeStep
            lb.layer(ly)
            converted.append((ly, pmap, c.get("name")))
            if cls == "LSTM" and not c.get("return_sequences", False):
                lb.layer(LastTimeStep())
                converted.append((LastTimeStep(), {}, None))
        model = MultiLayerNetwork(lb.build()).init()
        KerasModelImport._copy_weights_mln(model, converted, weights)
        return model

    @staticmethod
    def _copy_weights_mln(model, converted, weights):
        li = 0
        for ly, pmap, kname in converted:
            key = f"layer_{li}"
            li += 1
            if not pmap or kname not in weights:
                continue
            KerasModelImport._fill(model.params_tree[key],
                                   model.state_tree[key], pmap,
                                   weights[kname], kname)

    @staticmethod
    def _fill(params, state, pmap, w, kname):
        for ours, theirs in pmap.items():
            if theirs not in w:
                if ours == "b":
                    continue  # use_bias=False
                raise KeyError(
                    f"Layer {kname!r}: missing weight {theirs!r}; "
                    f"have {sorted(w)}")
            val = np.asarray(w[theirs])
            if ours.startswith("state:"):
                tgt = state
                ours = ours.split(":", 1)[1]
            else:
                tgt = params
            if tuple(tgt[ours].shape) != tuple(val.shape):
                raise ValueError(
                    f"Layer {kname!r} weight {ours}: shape "
                    f"{val.shape} != expected {tuple(tgt[ours].shape)}")
            tgt[ours] = val.astype(np.asarray(tgt[ours]).dtype)

    # ------------------------------------------------------------------
    @staticmethod
    def _import_functional(cfg: dict, weights) -> ComputationGraph:
        layers_cfg = cfg["layers"]

        def _refs(spec) -> List[str]:
            """'name' | ['name', n, t] | [['a',0,0], ['b',0,0]] — keras
            flattens single-output refs to one triple."""
            if isinstance(spec, str):
                return [spec]
            if (isinstance(spec, list) and spec
                    and isinstance(spec[0], str)):
                return [spec[0]]
            return [r for s in spec for r in _refs(s)]

        in_names = _refs(cfg.get("input_layers", []))
        out_names = _refs(cfg.get("output_layers", []))

        g = NeuralNetConfiguration.builder().graph()
        converted: Dict[str, Tuple[Any, Dict[str, str]]] = {}
        input_types = []
        for lc in layers_cfg:
            cls, c, name = lc["class_name"], lc["config"], lc["config"]["name"]
            inbound = lc.get("inbound_nodes", [])
            srcs = KerasModelImport._inbound_names(inbound)
            if cls == "InputLayer":
                g.add_inputs(name)
                shape = c.get("batch_shape") or c.get("batch_input_shape")
                input_types.append(KerasModelImport._input_type(shape))
                continue
            if cls == "Add":
                g.add_vertex(name, ElementWiseVertex("add"), *srcs)
                continue
            if cls in ("Concatenate", "Merge"):
                g.add_vertex(name, MergeVertex(), *srcs)
                continue
            is_out = name in out_names
            ly, pmap = KerasModelImport._convert(cls, c, is_out)
            if ly is None:  # Flatten -> explicit cnn_to_ff vertex
                g.add_vertex(name, PreprocessorVertex(
                    Preprocessor("cnn_to_ff")), *srcs)
                continue
            g.add_layer(name, ly, *srcs)
            converted[name] = (ly, pmap)
        g.set_input_types(*input_types)
        g.set_outputs(*out_names)
        model = ComputationGraph(g.build()).init()
        for name, (ly, pmap) in converted.items():
            if pmap and name in weights:
                KerasModelImport._fill(model.params_tree[name],
                                       model.state_tree[name], pmap,
                                       weights[name], name)
        return model

    @staticmethod
    def _inbound_names(inbound) -> List[str]:
        """Keras 3 inbound_nodes: [{'args': [{'class_name':
        '__keras_tensor__', 'config': {'keras_history': [name, ...]}}...]}]
        (legacy: [[[name, 0, 0, {}], ...]])."""
        names: List[str] = []

        def walk(x):
            if isinstance(x, dict):
                if x.get("class_name") == "__keras_tensor__":
                    names.append(x["config"]["keras_history"][0])
                else:
                    for v in x.values():
                        walk(v)
            elif isinstance(x, list):
                if (len(x) >= 3 and isinstance(x[0], str)
                        and isinstance(x[1], int)):
                    names.append(x[0])  # legacy [name, node, tensor, {}]
                else:
                    for v in x:
                        walk(v)
        walk(inbound)
        return names

