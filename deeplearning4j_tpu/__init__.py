"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A ground-up re-design of the capability set of deeplearning4j
(reference: yichencc/deeplearning4j) for TPU hardware:

* the libnd4j/JavaCPP native core is replaced by the PJRT runtime that jax
  already drives — arrays live in TPU HBM as jax Arrays;
* the SameDiff interpreter is replaced by traced, XLA-compiled programs
  (one compiled step per ``fit`` loop instead of one JNI crossing per op);
* ``MultiLayerNetwork``/``ComputationGraph`` keep their declarative,
  JSON-round-trippable configuration surface but build pure ``init/apply``
  functions over parameter pytrees;
* the cuDNN/oneDNN layer helpers are XLA lowerings — no helper seam exists;
* ParallelWrapper / SharedTrainingMaster / Aeron are replaced by a single
  sharded train step over a ``jax.sharding.Mesh`` (ICI/DCN collectives).

Reference parity citations in docstrings use the upstream monorepo layout
(e.g. ``deeplearning4j/deeplearning4j-nn/.../MultiLayerNetwork.java``); see
SURVEY.md for the full component inventory this package mirrors.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.nn.conf.builder import NeuralNetConfiguration
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.models.computation_graph import ComputationGraph
from deeplearning4j_tpu import native_io

__all__ = [
    "NeuralNetConfiguration",
    "MultiLayerNetwork",
    "ComputationGraph",
    "native_io",
    "__version__",
]
