"""Loss functions.

Parity with DL4J's ``LossFunctions.LossFunction`` zoo (reference:
``nd4j-api org.nd4j.linalg.lossfunctions.impl.{LossMCXENT,LossNegativeLogLikelihood,
LossMSE,LossL1,LossBinaryXENT,LossHinge,LossSquaredHinge,LossKLD,LossPoisson,
LossCosineProximity,LossMixtureDensity,…}``).

Semantics that matter for loss-curve parity with DL4J:

* every loss is averaged over the minibatch (DL4J ``computeScore`` divides
  by example count), and summed over output dimensions within an example;
* MCXENT expects the activation already applied (softmax output) — like
  DL4J, we fuse softmax+xent numerically when the output layer's activation
  is softmax, by computing from logits via log_softmax;
* per-example mask weights (label masks) multiply per-example scores.

Each entry maps name -> fn(labels, preds_or_logits, from_logits) returning
per-example scores of shape [batch].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_EPS = 1e-7


def _sum_features(x):
    # Sum across all non-batch axes: handles 2-D dense, 4-D conv, 3-D time.
    return jnp.sum(x, axis=tuple(range(1, x.ndim)))


def mcxent(labels, preds, logits=None):
    """Multi-class cross entropy. If `logits` given, computes via
    log_softmax for numerical stability (the fused softmax+MCXENT path that
    DL4J special-cases in ``LossMCXENT`` when paired with softmax)."""
    if logits is not None:
        logp = jax.nn.log_softmax(logits, axis=-1)
    else:
        logp = jnp.log(jnp.clip(preds, _EPS, 1.0))
    return -_sum_features(labels * logp)


def negativeloglikelihood(labels, preds, logits=None):
    # DL4J's NLL is MCXENT (it subclasses LossMCXENT with clipping).
    return mcxent(labels, preds, logits)


def binary_xent(labels, preds, logits=None):
    """XENT — sigmoid binary cross entropy (``LossBinaryXENT``)."""
    if logits is not None:
        # stable: max(z,0) - z*y + log(1+exp(-|z|))
        z = logits
        per = jnp.maximum(z, 0.0) - z * labels + jnp.log1p(jnp.exp(-jnp.abs(z)))
        return _sum_features(per)
    p = jnp.clip(preds, _EPS, 1.0 - _EPS)
    return -_sum_features(labels * jnp.log(p) + (1.0 - labels) * jnp.log1p(-p))


def _n_features(x):
    n = 1
    for s in x.shape[1:]:
        n *= s
    return n


def mse(labels, preds, logits=None):
    # DL4J LossMSE divides by the output count (LossL2 is the plain sum).
    return _sum_features(jnp.square(preds - labels)) / _n_features(labels)


def l1(labels, preds, logits=None):
    return _sum_features(jnp.abs(preds - labels))


def l2(labels, preds, logits=None):
    # DL4J LossL2 = sum of squares (MSE without the /n over outputs; in our
    # convention both sum over features, matching DL4J's per-output sums).
    return _sum_features(jnp.square(preds - labels))


def hinge(labels, preds, logits=None):
    # labels in {-1, +1} per DL4J LossHinge
    return _sum_features(jnp.maximum(0.0, 1.0 - labels * preds))


def squared_hinge(labels, preds, logits=None):
    return _sum_features(jnp.square(jnp.maximum(0.0, 1.0 - labels * preds)))


def kld(labels, preds, logits=None):
    y = jnp.clip(labels, _EPS, 1.0)
    p = jnp.clip(preds, _EPS, 1.0)
    return _sum_features(y * (jnp.log(y) - jnp.log(p)))


def poisson(labels, preds, logits=None):
    p = jnp.clip(preds, _EPS, None)
    return _sum_features(p - labels * jnp.log(p))


def cosine_proximity(labels, preds, logits=None):
    yn = labels / (jnp.linalg.norm(labels, axis=-1, keepdims=True) + _EPS)
    pn = preds / (jnp.linalg.norm(preds, axis=-1, keepdims=True) + _EPS)
    return -_sum_features(yn * pn)


def mape(labels, preds, logits=None):
    return _sum_features(
        100.0 * jnp.abs((labels - preds) / jnp.clip(jnp.abs(labels), _EPS, None))
    )


def msle(labels, preds, logits=None):
    return _sum_features(
        jnp.square(jnp.log1p(jnp.clip(preds, -1 + _EPS, None))
                   - jnp.log1p(jnp.clip(labels, -1 + _EPS, None)))
    )


def sparse_mcxent(labels, preds, logits=None):
    """SPARSE_MCXENT — integer class labels of shape [batch]."""
    if logits is not None:
        logp = jax.nn.log_softmax(logits, axis=-1)
    else:
        logp = jnp.log(jnp.clip(preds, _EPS, 1.0))
    labels = labels.astype(jnp.int32)
    if labels.ndim == logp.ndim:  # [batch,1]
        labels = labels.reshape(labels.shape[:-1])
    return -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]


LOSSES = {
    "mcxent": mcxent,
    "negativeloglikelihood": negativeloglikelihood,
    "sparse_mcxent": sparse_mcxent,
    "xent": binary_xent,
    "mse": mse,
    "squared_loss": mse,
    "l1": l1,
    "mae": l1,
    "l2": l2,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "kl_divergence": kld,
    "reconstruction_crossentropy": binary_xent,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "mean_absolute_percentage_error": mape,
    "mean_squared_logarithmic_error": msle,
}

# Losses that can consume raw logits when fused with these final activations.
FUSED_ACTIVATIONS = {
    "mcxent": "softmax",
    "negativeloglikelihood": "softmax",
    "sparse_mcxent": "softmax",
    "xent": "sigmoid",
}


def get_loss(name: str):
    fn = LOSSES.get(str(name).lower())
    if fn is None:
        raise ValueError(f"Unknown loss {name!r}; available: {sorted(LOSSES)}")
    return fn
