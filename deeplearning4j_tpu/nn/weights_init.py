"""Weight initialization schemes.

Parity with DL4J's ``WeightInit`` enum + ``WeightInitUtil`` (reference:
``deeplearning4j-nn org.deeplearning4j.nn.weights.WeightInit`` /
``WeightInitUtil.initWeights``).  DL4J semantics preserved where they are
load-bearing for loss-curve parity:

* XAVIER        — N(0, 2/(fanIn+fanOut))        (DL4J's Glorot-normal)
* XAVIER_UNIFORM— U(±sqrt(6/(fanIn+fanOut)))
* RELU          — N(0, 2/fanIn)                  (He)
* RELU_UNIFORM  — U(±sqrt(6/fanIn))
* LECUN_NORMAL  — N(0, 1/fanIn)
* SIGMOID_UNIFORM — U(±4*sqrt(6/(fanIn+fanOut)))
* NORMAL        — N(0, 1/sqrt(fanIn))  (DL4J "NORMAL" is fan-in scaled)
* UNIFORM       — U(±1/sqrt(fanIn))    (legacy DL4J default)
* ZERO / ONES / IDENTITY / DISTRIBUTION(custom)
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def init_weights(
    key,
    shape,
    fan_in: float,
    fan_out: float,
    scheme: str = "xavier",
    dtype=jnp.float32,
    distribution=None,
):
    """Sample a weight tensor per DL4J ``WeightInitUtil.initWeights``.

    `shape` is the full kernel shape; fan_in/fan_out are computed by the
    layer (for conv: fan_in = C_in * kH * kW, matching DL4J).
    """
    s = str(scheme).lower() if scheme is not None else "xavier"
    if s == "zero":
        return jnp.zeros(shape, dtype)
    if s == "ones":
        return jnp.ones(shape, dtype)
    if s == "identity":
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("IDENTITY init requires a square 2-D shape")
        return jnp.eye(shape[0], dtype=dtype)
    if s == "distribution":
        if distribution is None:
            raise ValueError("DISTRIBUTION init requires a `distribution` spec")
        return _sample_distribution(key, shape, distribution, dtype)

    # Lazy samplers: only the one the scheme needs is executed.
    def normal():
        return jax.random.normal(key, shape, dtype)

    def uniform():
        return jax.random.uniform(key, shape, dtype, -1.0, 1.0)

    if s == "xavier":
        return normal() * math.sqrt(2.0 / (fan_in + fan_out))
    if s == "xavier_uniform":
        return uniform() * math.sqrt(6.0 / (fan_in + fan_out))
    if s == "xavier_fan_in":
        return normal() / math.sqrt(fan_in)
    if s == "relu":
        return normal() * math.sqrt(2.0 / fan_in)
    if s == "relu_uniform":
        return uniform() * math.sqrt(6.0 / fan_in)
    if s == "lecun_normal":
        return normal() / math.sqrt(fan_in)
    if s == "lecun_uniform":
        return uniform() * math.sqrt(3.0 / fan_in)
    if s == "sigmoid_uniform":
        return uniform() * 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
    if s == "normal":
        return normal() / math.sqrt(fan_in)
    if s == "uniform":
        a = 1.0 / math.sqrt(fan_in)
        return uniform() * a
    if s == "var_scaling_normal_fan_avg":
        return normal() * math.sqrt(2.0 / (fan_in + fan_out))
    raise ValueError(f"Unknown weight init scheme {scheme!r}")


def _sample_distribution(key, shape, dist, dtype):
    """`dist` is a dict like {"type": "normal", "mean": 0, "std": 1e-2} —
    the analogue of DL4J ``org.deeplearning4j.nn.conf.distribution.*``."""
    t = dist.get("type", "normal").lower()
    if t == "normal" or t == "gaussian":
        return dist.get("mean", 0.0) + jax.random.normal(key, shape, dtype) * dist.get(
            "std", 1.0
        )
    if t == "uniform":
        return jax.random.uniform(
            key, shape, dtype, dist.get("lower", -1.0), dist.get("upper", 1.0)
        )
    if t == "truncated_normal":
        return dist.get("mean", 0.0) + jax.random.truncated_normal(
            key, -2.0, 2.0, shape, dtype
        ) * dist.get("std", 1.0)
    if t == "orthogonal":
        return dist.get("gain", 1.0) * jax.random.orthogonal(key, shape[0], shape=()) \
            if len(shape) == 1 else dist.get("gain", 1.0) * jax.random.orthogonal(
                key, max(shape), shape=())[: shape[0], : shape[1]].astype(dtype)
    raise ValueError(f"Unknown distribution type {t!r}")
