"""Activation functions.

Parity with DL4J's ``Activation`` enum (reference:
``nd4j-api org.nd4j.linalg.activations.Activation`` — CUBE, ELU, HARDSIGMOID,
HARDTANH, IDENTITY, LEAKYRELU, RATIONALTANH, RELU, RELU6, RRELU, SELU,
SIGMOID, SOFTMAX, SOFTPLUS, SOFTSIGN, SWISH, TANH, THRESHOLDEDRELU, GELU,
MISH).  All are pure jnp functions so XLA fuses them into the surrounding
matmul/conv — the fusion DL4J needed cuDNN activation descriptors for.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_E = 1e-7


def _rational_tanh(x):
    # tanh approximation from DL4J's RATIONALTANH (Anguita et al.)
    a = 1.7159
    y = a * _rational_core((2.0 / 3.0) * x)
    return jnp.clip(y, -a, a)


def _rational_core(x):
    ax = jnp.abs(x)
    return jnp.sign(x) * (1.0 - 1.0 / (1.0 + ax + x * x + 1.41645 * x**4))


ACTIVATIONS = {
    "identity": lambda x: x,
    "relu": jax.nn.relu,
    "relu6": lambda x: jnp.clip(x, 0.0, 6.0),
    "leakyrelu": lambda x: jax.nn.leaky_relu(x, 0.01),
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "celu": jax.nn.celu,
    "gelu": jax.nn.gelu,
    "sigmoid": jax.nn.sigmoid,
    "hardsigmoid": jax.nn.hard_sigmoid,
    "hardtanh": lambda x: jnp.clip(x, -1.0, 1.0),
    "tanh": jnp.tanh,
    "rationaltanh": _rational_tanh,
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
    "logsoftmax": lambda x: jax.nn.log_softmax(x, axis=-1),
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "swish": jax.nn.swish,
    "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    "cube": lambda x: x**3,
    "thresholdedrelu": lambda x: jnp.where(x > 1.0, x, 0.0),
}


def get_activation(name: str):
    """Look up an activation by DL4J enum name (case-insensitive)."""
    fn = ACTIVATIONS.get(str(name).lower())
    if fn is None:
        raise ValueError(
            f"Unknown activation {name!r}; available: {sorted(ACTIVATIONS)}"
        )
    return fn
