"""Neural-network building blocks: activations, initializers, losses, layers.

TPU-native twin of ``deeplearning4j/deeplearning4j-nn`` — but where DL4J
splits each layer into a conf class + an eager runtime class +
backend-specific helpers (cuDNN/oneDNN), here a layer is one dataclass
config that owns pure ``init``/``apply`` functions lowered through XLA.
"""
