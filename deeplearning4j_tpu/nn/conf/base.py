"""Layer-config base classes and serialization registry.

Replaces DL4J's Jackson-polymorphic layer conf hierarchy (reference:
``org.deeplearning4j.nn.conf.layers.Layer`` + ``@JsonTypeInfo`` subtype
registry).  A layer here is ONE dataclass that carries:

* hyperparameters (serialized to/from JSON via ``to_dict``/``from_dict``),
* ``infer_shapes(input_shape)`` — InputType propagation (DL4J
  ``Layer.getOutputType`` + ``setNIn``),
* ``init(key, dtype) -> (params, state)`` — parameter pytree construction
  (DL4J ``ParamInitializer``),
* ``apply(params, state, x, training, rng, compute_dtype) -> (y, state)`` —
  the pure forward, traced and compiled by XLA.  Backward is ``jax.grad`` —
  there is no ``backpropGradient`` twin to hand-write.

Shape convention: batch-major; images are NHWC (TPU-native), sequences are
[batch, time, features] — time-major conversion happens at the scan, not in
user-facing shapes.  (DL4J uses NCHW / [b, f, t]; the data pipeline adapts.)
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp

_LAYER_REGISTRY: Dict[str, type] = {}


def register_layer(cls):
    """Class decorator: register for polymorphic JSON round-trip."""
    _LAYER_REGISTRY[cls.__name__] = cls
    return cls


def _populate_registry():
    """Import every layers_* module in this package so @register_layer
    runs — needed when a process deserializes a checkpoint without
    having imported the package surface (e.g. only
    utils.model_serializer).  Discovered, not hardcoded, so new layer
    modules are covered automatically."""
    import importlib
    import pkgutil

    import deeplearning4j_tpu.nn.conf as conf_pkg
    for info in pkgutil.iter_modules(conf_pkg.__path__):
        if info.name.startswith("layers"):
            importlib.import_module(
                f"deeplearning4j_tpu.nn.conf.{info.name}")


def layer_from_dict(d: Dict[str, Any]) -> "BaseLayerConf":
    d = dict(d)
    type_name = d.pop("type")
    cls = _LAYER_REGISTRY.get(type_name)
    if cls is None:
        _populate_registry()
        cls = _LAYER_REGISTRY.get(type_name)
    if cls is None:
        raise ValueError(f"Unknown layer type {type_name!r} in config")
    field_names = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in d.items() if k in field_names})


@dataclasses.dataclass
class BaseLayerConf:
    """Common hyperparameters every DL4J ``BaseLayer`` carries.

    ``None`` means "inherit from the global NeuralNetConfiguration" — the
    builder resolves these before the model is built (DL4J does the same
    via ``NeuralNetConfiguration.Builder`` global defaults).
    """

    # Input kinds this layer consumes, in preference order; the builder
    # auto-inserts reshape preprocessors (DL4J InputPreProcessor insertion).
    WANTED_KINDS = ("any",)

    name: Optional[str] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    weight_distribution: Optional[dict] = None
    bias_init: float = 0.0
    l1: Optional[float] = None
    l2: Optional[float] = None
    weight_decay: Optional[float] = None
    dropout: Optional[float] = None  # DROP probability (DL4J stores keep)
    updater: Optional[dict] = None   # per-layer updater override
    learning_rate_mult: float = 1.0  # analogue of per-layer lr override

    # ---- serialization ----
    def to_dict(self) -> Dict[str, Any]:
        d = {"type": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v is not None and v != f.default:
                d[f.name] = v
        return d

    # ---- to be overridden ----
    def infer_shapes(self, input_shape: Tuple[int, ...]) -> Tuple[int, ...]:
        """Propagate the (batch-free) input shape; fill in n_in if unset."""
        return input_shape

    def has_params(self) -> bool:
        return False

    def init(self, key, dtype=jnp.float32):
        """Return (params, state) pytrees (both possibly empty dicts)."""
        return {}, {}

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        raise NotImplementedError

    # weight-carrying params that regularization applies to (not biases)
    def regularized_param_names(self):
        return ("W",) if self.has_params() else ()

    def resolve_defaults(self, global_conf: "GlobalConf"):
        """Fill None fields from global conf (builder-time)."""
        if self.activation is None:
            self.activation = global_conf.activation
        if self.weight_init is None:
            self.weight_init = global_conf.weight_init
        if self.weight_distribution is None:
            self.weight_distribution = global_conf.weight_distribution
        if self.l1 is None:
            self.l1 = global_conf.l1
        if self.l2 is None:
            self.l2 = global_conf.l2
        if self.weight_decay is None:
            self.weight_decay = global_conf.weight_decay
        if self.dropout is None:
            self.dropout = global_conf.dropout
        # Fail at BUILD time on an unknown activation name, not at first
        # forward (DL4J's enum gives the same eager guarantee).
        if self.activation is not None:
            from deeplearning4j_tpu.nn.activations import get_activation
            get_activation(self.activation)


@dataclasses.dataclass
class GlobalConf:
    """Global defaults layers inherit (DL4J builder's top-level settings)."""

    seed: int = 0
    activation: str = "identity"
    weight_init: str = "xavier"
    weight_distribution: Optional[dict] = None
    l1: float = 0.0
    l2: float = 0.0
    weight_decay: float = 0.0
    dropout: float = 0.0
    updater: Optional[dict] = None
    dtype: str = "float32"
    # Matmul/conv compute dtype; None = backend default.  "bfloat16" with
    # f32 params is the TPU-native training recipe (full-rate MXU).
    compute_dtype: Optional[str] = None
    minimize: bool = True
