"""Declarative, JSON-round-trippable network configuration.

TPU-native twin of ``org.deeplearning4j.nn.conf`` (NeuralNetConfiguration
builder -> MultiLayerConfiguration JSON).  Unlike DL4J — where a conf class
is paired with a separate eager runtime Layer class and optional
cuDNN/oneDNN helpers — here each layer config directly owns pure
``init``/``apply`` functions that XLA compiles; there is no helper seam.
"""

from deeplearning4j_tpu.nn.conf.base import BaseLayerConf, layer_from_dict, register_layer
from deeplearning4j_tpu.nn.conf.builder import (
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)

__all__ = [
    "BaseLayerConf",
    "layer_from_dict",
    "register_layer",
    "NeuralNetConfiguration",
    "MultiLayerConfiguration",
]
from deeplearning4j_tpu.nn.conf import layers_objdetect  # noqa: F401  (registry)
