"""Fluent network-configuration builder.

Parity with ``org.deeplearning4j.nn.conf.NeuralNetConfiguration.Builder`` →
``ListBuilder`` → ``MultiLayerConfiguration`` (Jackson JSON round-trip is
replaced by plain dict/json of dataclasses).  The build step resolves
global-default inheritance, propagates InputType shapes (auto-filling
``n_in`` and inserting reshape preprocessors), exactly as DL4J's
``MultiLayerConfiguration.Builder#build`` does.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu.nn.conf.base import BaseLayerConf, GlobalConf, layer_from_dict
from deeplearning4j_tpu.nn.conf.inputs import InputType, Preprocessor, adapt


class NeuralNetConfiguration:
    """Entry point: ``NeuralNetConfiguration.builder()`` (DL4J idiom)."""

    @staticmethod
    def builder() -> "Builder":
        return Builder()


class Builder:
    def __init__(self):
        self._g = GlobalConf()
        self.grad_normalization: Optional[str] = None
        self.grad_norm_threshold: float = 1.0

    # -- fluent global defaults (names follow DL4J's builder methods) --
    def seed(self, s: int) -> "Builder":
        self._g.seed = int(s)
        return self

    def activation(self, a: str) -> "Builder":
        self._g.activation = a
        return self

    def weight_init(self, w: str, distribution: Optional[dict] = None) -> "Builder":
        self._g.weight_init = str(w).lower()
        self._g.weight_distribution = distribution
        return self

    def updater(self, u) -> "Builder":
        # `u` is an updater dataclass from optimize.updaters (or its dict)
        self._g.updater = u.to_dict() if hasattr(u, "to_dict") else dict(u)
        return self

    def l1(self, v: float) -> "Builder":
        self._g.l1 = float(v)
        return self

    def l2(self, v: float) -> "Builder":
        self._g.l2 = float(v)
        return self

    def weight_decay(self, v: float) -> "Builder":
        self._g.weight_decay = float(v)
        return self

    def dropout(self, rate: float) -> "Builder":
        self._g.dropout = float(rate)
        return self

    def dtype(self, d: str) -> "Builder":
        self._g.dtype = str(d)
        return self

    def compute_dtype(self, d: str) -> "Builder":
        """Matmul/conv compute dtype ('bfloat16' feeds the MXU at full
        rate; params stay in ``dtype``)."""
        self._g.compute_dtype = str(d)
        return self

    def minimize(self, m: bool = True) -> "Builder":
        self._g.minimize = bool(m)
        return self

    def gradient_normalization(self, kind: str, threshold: float = 1.0) -> "Builder":
        """DL4J ``GradientNormalization``: 'clip_l2_per_layer',
        'clip_element_wise_absolute_value', 'renormalize_l2_per_layer',
        'clip_l2_per_param_type', or 'clip_global_norm' (TPU-era extra)."""
        self.grad_normalization = str(kind).lower()
        self.grad_norm_threshold = float(threshold)
        return self

    def list(self) -> "ListBuilder":
        return ListBuilder(self)

    def graph(self):
        from deeplearning4j_tpu.models.computation_graph import GraphBuilder
        return GraphBuilder(self)


class ListBuilder:
    """Sequential-stack builder (DL4J ``NeuralNetConfiguration.ListBuilder``)."""

    def __init__(self, parent: Builder):
        self._parent = parent
        self._layers: List[BaseLayerConf] = []
        self._input_type: Optional[InputType] = None
        self._backprop_type: str = "standard"
        self._tbptt_fwd: Optional[int] = None
        self._tbptt_bwd: Optional[int] = None

    def layer(self, conf: BaseLayerConf) -> "ListBuilder":
        self._layers.append(conf)
        return self

    def set_input_type(self, it: InputType) -> "ListBuilder":
        self._input_type = it
        return self

    def backprop_type(self, kind: str, tbptt_fwd: int = None,
                      tbptt_bwd: int = None) -> "ListBuilder":
        """'standard' | 'truncated_bptt' (DL4J BackpropType + tBPTT lengths)."""
        self._backprop_type = str(kind).lower()
        self._tbptt_fwd = tbptt_fwd
        self._tbptt_bwd = tbptt_bwd or tbptt_fwd
        return self

    def build(self) -> "MultiLayerConfiguration":
        if not self._layers:
            raise ValueError("No layers configured")
        g = self._parent._g
        for ly in self._layers:
            ly.resolve_defaults(g)

        # Shape propagation + preprocessor insertion.
        preprocessors: List[Optional[Preprocessor]] = [None] * len(self._layers)
        it = self._input_type
        if it is None:
            first = self._layers[0]
            n_in = getattr(first, "n_in", None)
            if n_in is None:
                raise ValueError(
                    "Either set_input_type(...) or n_in on the first layer is required"
                )
            it = InputType.feed_forward(n_in)
        input_type = it
        for i, ly in enumerate(self._layers):
            pre = None
            err = None
            # Direct match first: a layer that natively consumes the current
            # kind gets NO preprocessor, regardless of preference order
            # (e.g. Dense handles [b,t,f] natively — never fold time).
            if "any" in ly.WANTED_KINDS or it.kind in ly.WANTED_KINDS:
                adapted = it
            else:
                for kind in ly.WANTED_KINDS:
                    try:
                        pre, adapted = adapt(it, kind)
                        break
                    except ValueError as e:
                        err = e
                else:
                    raise ValueError(f"Layer {i} ({type(ly).__name__}): {err}")
            preprocessors[i] = pre
            out_shape = ly.infer_shapes(adapted.shape)
            out_kind = getattr(ly, "OUTPUT_KIND", None) or adapted.kind
            it = InputType(out_kind, tuple(out_shape))

        return MultiLayerConfiguration(
            global_conf=g,
            layers=self._layers,
            preprocessors=preprocessors,
            input_type=input_type,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_bwd_length=self._tbptt_bwd,
            grad_normalization=self._parent.grad_normalization,
            grad_norm_threshold=self._parent.grad_norm_threshold,
        )


@dataclasses.dataclass
class MultiLayerConfiguration:
    """The serializable model IR (DL4J ``MultiLayerConfiguration`` — the
    JSON stored inside every ModelSerializer checkpoint)."""

    global_conf: GlobalConf
    layers: List[BaseLayerConf]
    preprocessors: List[Optional[Preprocessor]]
    input_type: Optional[InputType] = None
    backprop_type: str = "standard"
    tbptt_fwd_length: Optional[int] = None
    tbptt_bwd_length: Optional[int] = None
    grad_normalization: Optional[str] = None
    grad_norm_threshold: float = 1.0
    # layer indices whose parameters never update (TransferLearning /
    # FrozenLayer); persisted so a restored fine-tune keeps its freeze
    frozen_layers: List[int] = dataclasses.field(default_factory=list)

    # ---- JSON round-trip (DL4J MultiLayerConfiguration.toJson/fromJson) ----
    def to_dict(self) -> Dict[str, Any]:
        return {
            "format": "deeplearning4j_tpu/MultiLayerConfiguration/v1",
            "global_conf": dataclasses.asdict(self.global_conf),
            "layers": [ly.to_dict() for ly in self.layers],
            "preprocessors": [p.to_dict() if p else None for p in self.preprocessors],
            "input_type": self.input_type.to_dict() if self.input_type else None,
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_bwd_length": self.tbptt_bwd_length,
            "grad_normalization": self.grad_normalization,
            "grad_norm_threshold": self.grad_norm_threshold,
            "frozen_layers": list(self.frozen_layers),
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "MultiLayerConfiguration":
        g = GlobalConf(**d["global_conf"])
        layers = [layer_from_dict(ld) for ld in d["layers"]]
        pres = [Preprocessor.from_dict(p) if p else None for p in d["preprocessors"]]
        it = InputType.from_dict(d["input_type"]) if d.get("input_type") else None
        return MultiLayerConfiguration(
            global_conf=g, layers=layers, preprocessors=pres, input_type=it,
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length"),
            tbptt_bwd_length=d.get("tbptt_bwd_length"),
            grad_normalization=d.get("grad_normalization"),
            grad_norm_threshold=d.get("grad_norm_threshold", 1.0),
            frozen_layers=list(d.get("frozen_layers", [])),
        )

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_dict(json.loads(s))
