"""Transformer layers — the flagship TPU model family.

The reference has no native transformer blocks (its BERT story is
TF-import only — ``samediff-import-tensorflow`` [UNVERIFIED]); these
layers are the framework-native equivalent, built so the whole encoder
stack compiles to one XLA program with the Pallas flash-attention
kernel in the hot path (``kernels/flash_attention.py``).

``EmbeddingSequenceLayer`` extends DL4J's
``org.deeplearning4j.nn.conf.layers.EmbeddingSequenceLayer``
[UNVERIFIED] (ids -> vectors) with learned positional embeddings and
embedding layer-norm, i.e. a BERT input block.

``TransformerEncoderBlock`` is one post-LN encoder layer (attention +
FFN, residuals, layer norms) — matmul-dominated, bf16-friendly, the
shape the MXU wants.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.base import BaseLayerConf, register_layer
from deeplearning4j_tpu.nn.conf.layers_core import apply_dropout
from deeplearning4j_tpu.nn.weights_init import init_weights


def _layer_norm(x, gamma, beta, eps=1e-12):
    """LN at >=f32 (bf16 variance is numerically unsafe; f64 stays f64
    for the gradient-check harness), output in x dtype."""
    ct = jnp.promote_types(x.dtype, jnp.float32)
    xf = x.astype(ct)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * gamma.astype(ct) + beta.astype(ct)).astype(x.dtype)


@register_layer
@dataclasses.dataclass
class EmbeddingSequenceLayer(BaseLayerConf):
    """[b, t] int ids -> [b, t, n_out] vectors: word embedding +
    (optional) learned positional embedding + (optional) layer norm —
    the BERT input block in one layer."""

    n_in: Optional[int] = None       # vocabulary size
    n_out: Optional[int] = None      # embedding dim
    max_len: int = 512               # positional table length
    add_positional: bool = True
    layer_norm: bool = True
    eps: float = 1e-12

    WANTED_KINDS = ("any",)
    OUTPUT_KIND = "rnn"

    def infer_shapes(self, input_shape):
        t = input_shape[0] if input_shape else self.max_len
        return (t, self.n_out)

    def has_params(self):
        return True

    def init(self, key, dtype=jnp.float32):
        kw, kp = jax.random.split(key)
        params = {"W": init_weights(kw, (self.n_in, self.n_out), self.n_in,
                                    self.n_out, self.weight_init, dtype,
                                    self.weight_distribution)}
        if self.add_positional:
            params["P"] = 0.02 * jax.random.normal(
                kp, (self.max_len, self.n_out), dtype)
        if self.layer_norm:
            params["g"] = jnp.ones((self.n_out,), dtype)
            params["b"] = jnp.zeros((self.n_out,), dtype)
        return params, {}

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 3 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        w = params["W"]
        if compute_dtype is not None:
            w = w.astype(compute_dtype)
        y = jnp.take(w, idx, axis=0)               # [b, t, d]
        if self.add_positional:
            t = y.shape[1]
            y = y + params["P"][:t].astype(y.dtype)[None]
        if self.layer_norm:
            y = _layer_norm(y, params["g"], params["b"], self.eps)
        return apply_dropout(y, self.dropout, training, rng), state


@register_layer
@dataclasses.dataclass
class TransformerEncoderBlock(BaseLayerConf):
    """One post-LN transformer encoder layer over [b, t, d]:

        h = LN(x + Wo·FlashAttention(Wq x, Wk x, Wv x))
        y = LN(h + W2·act(W1 h))

    Attention runs through ``kernels.attention`` — the Pallas flash
    kernel on TPU (O(t) memory, causal/mask-aware) with an XLA einsum
    fallback; a [b, t] sequence mask becomes the kernel's additive
    key-position bias.  With ``compute_dtype=bfloat16`` every matmul is
    full-rate MXU; layer norms and softmax stay f32."""

    n_heads: int = 8
    d_ff: Optional[int] = None       # default 4*d
    n_in: Optional[int] = None
    n_out: Optional[int] = None
    causal: bool = False
    eps: float = 1e-12
    use_flash: bool = True

    WANTED_KINDS = ("rnn",)
    USES_MASK = True

    def infer_shapes(self, input_shape):
        t, f = input_shape
        self.n_in = int(f)
        self.n_out = int(f)
        if self.d_ff is None:
            self.d_ff = 4 * self.n_in
        if self.n_in % self.n_heads:
            raise ValueError(
                f"d_model {self.n_in} must divide by n_heads {self.n_heads}")
        return (t, self.n_out)

    def has_params(self):
        return True

    def init(self, key, dtype=jnp.float32):
        d, ff = self.n_in, self.d_ff
        ks = jax.random.split(key, 6)
        mk = lambda k, shape: init_weights(k, shape, shape[0], shape[-1],
                                           self.weight_init, dtype,
                                           self.weight_distribution)
        params = {
            "Wqkv": mk(ks[0], (d, 3 * d)),   # fused qkv projection
            "bqkv": jnp.zeros((3 * d,), dtype),
            "Wo": mk(ks[1], (d, d)), "bo": jnp.zeros((d,), dtype),
            "ln1_g": jnp.ones((d,), dtype), "ln1_b": jnp.zeros((d,), dtype),
            "W1": mk(ks[2], (d, ff)), "b1": jnp.zeros((ff,), dtype),
            "W2": mk(ks[3], (ff, d)), "b2": jnp.zeros((d,), dtype),
            "ln2_g": jnp.ones((d,), dtype), "ln2_b": jnp.zeros((d,), dtype),
        }
        return params, {}

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None, mask=None):
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        cast = lambda w: w.astype(x.dtype)
        r1 = r2 = None
        if rng is not None:
            r1, r2 = jax.random.split(rng)
        b, t, d = x.shape
        h, dh = self.n_heads, d // self.n_heads

        from deeplearning4j_tpu.kernels import (
            attention, mask_to_bias, xla_attention)
        qkv = x @ cast(params["Wqkv"]) + cast(params["bqkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        bias = mask_to_bias(mask)
        if self.use_flash:
            # [b, t, h, dh] straight into the kernel's bthd layout —
            # the [b, h, t, dh] transpose pair cost ~22 ms/step on
            # zoo.Gpt (fwd+bwd, r5 profile) and is gone entirely
            split = lambda z: z.reshape(b, t, h, dh)
            att = attention(split(q), split(k), split(v), bias=bias,
                            causal=self.causal, layout="bthd")
        else:
            split = lambda z: z.reshape(b, t, h, dh).transpose(
                0, 2, 1, 3)
            att = xla_attention(split(q), split(k), split(v),
                                bias=bias, causal=self.causal)
            att = att.transpose(0, 2, 1, 3)
        att = att.reshape(b, t, d)
        att = att @ cast(params["Wo"]) + cast(params["bo"])
        att = apply_dropout(att, self.dropout, training, r1)
        hdn = _layer_norm(x + att, params["ln1_g"], params["ln1_b"],
                          self.eps)

        act = get_activation(self.activation or "gelu")
        ffn = act(hdn @ cast(params["W1"]) + cast(params["b1"]))
        ffn = ffn @ cast(params["W2"]) + cast(params["b2"])
        ffn = apply_dropout(ffn, self.dropout, training, r2)
        y = _layer_norm(hdn + ffn, params["ln2_g"], params["ln2_b"],
                        self.eps)
        return y, state
