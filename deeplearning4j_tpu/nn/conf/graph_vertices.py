"""Graph vertices: the non-layer nodes of a ComputationGraph.

Parity with ``org.deeplearning4j.nn.conf.graph.*`` (``MergeVertex``,
``ElementWiseVertex``, ``SubsetVertex``, ``ScaleVertex``, ``ShiftVertex``,
``StackVertex``, ``UnstackVertex``, ``L2NormalizeVertex``, ``ReshapeVertex``,
``PreprocessorVertex``).  DL4J pairs each conf class with a runtime
``GraphVertex`` twin holding ``doForward``/``doBackward``; here a vertex is
a single pure function — backward is ``jax.grad``.

Shape convention matches the layer confs: batch-major, NHWC images,
[batch, time, features] sequences (DL4J is NCHW / [b, f, t]).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType, Preprocessor

_VERTEX_REGISTRY: Dict[str, type] = {}


def register_vertex(cls):
    _VERTEX_REGISTRY[cls.__name__] = cls
    return cls


def vertex_from_dict(d: Dict[str, Any]) -> "BaseGraphVertex":
    d = dict(d)
    type_name = d.pop("type")
    cls = _VERTEX_REGISTRY.get(type_name)
    if cls is None:
        raise ValueError(f"Unknown vertex type {type_name!r} in config")
    field_names = {f.name for f in dataclasses.fields(cls)}
    kwargs = {k: v for k, v in d.items() if k in field_names}
    if "preprocessor" in kwargs and isinstance(kwargs["preprocessor"], dict):
        kwargs["preprocessor"] = Preprocessor.from_dict(kwargs["preprocessor"])
    return cls(**kwargs)


@dataclasses.dataclass
class BaseGraphVertex:
    """A parameterless DAG node combining/reshaping one or more inputs."""

    def n_inputs(self) -> Tuple[int, Optional[int]]:
        """(min, max) accepted fan-in; max None = unbounded."""
        return (1, 1)

    def infer_shapes(self, input_types: List[InputType]) -> InputType:
        return input_types[0]

    def apply(self, inputs: List[jnp.ndarray]) -> jnp.ndarray:
        raise NotImplementedError

    def to_dict(self) -> Dict[str, Any]:
        d = {"type": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v is not None and v != f.default:
                d[f.name] = v.to_dict() if hasattr(v, "to_dict") else v
        return d


@register_vertex
@dataclasses.dataclass
class MergeVertex(BaseGraphVertex):
    """Concatenate along the feature axis (last axis here; DL4J
    ``MergeVertex`` concatenates dim 1 of NCHW / [b, f, t] — same semantic
    axis)."""

    def n_inputs(self):
        return (1, None)

    def infer_shapes(self, input_types):
        kinds = {it.kind for it in input_types}
        if len(kinds) != 1:
            raise ValueError(f"MergeVertex inputs must share a kind, got {kinds}")
        first = input_types[0]
        feat = sum(it.shape[-1] for it in input_types)
        return InputType(first.kind, first.shape[:-1] + (feat,))

    def apply(self, inputs):
        return inputs[0] if len(inputs) == 1 else jnp.concatenate(inputs, -1)


@register_vertex
@dataclasses.dataclass
class ElementWiseVertex(BaseGraphVertex):
    """Pointwise combine — the residual-add vertex of ResNet
    (``ElementWiseVertex.Op.{Add,Subtract,Product,Average,Max}``)."""

    op: str = "add"

    def n_inputs(self):
        return (1, None) if self.op in ("add", "average", "product", "max") \
            else (2, 2)

    def infer_shapes(self, input_types):
        shapes = {it.shape for it in input_types}
        if len(shapes) != 1:
            raise ValueError(
                f"ElementWiseVertex inputs must share a shape, got {shapes}")
        return input_types[0]

    def apply(self, inputs):
        op = self.op
        acc = inputs[0]
        for x in inputs[1:]:
            if op in ("add", "average"):
                acc = acc + x
            elif op == "subtract":
                acc = acc - x
            elif op == "product":
                acc = acc * x
            elif op == "max":
                acc = jnp.maximum(acc, x)
            else:
                raise ValueError(f"Unknown ElementWiseVertex op {op!r}")
        if op == "average":
            acc = acc / len(inputs)
        return acc


@register_vertex
@dataclasses.dataclass
class SubsetVertex(BaseGraphVertex):
    """Feature-axis slice [from, to] INCLUSIVE (DL4J ``SubsetVertex``)."""

    from_index: int = 0
    to_index: int = 0

    def infer_shapes(self, input_types):
        it = input_types[0]
        n = self.to_index - self.from_index + 1
        return InputType(it.kind, it.shape[:-1] + (n,))

    def apply(self, inputs):
        return inputs[0][..., self.from_index:self.to_index + 1]


@register_vertex
@dataclasses.dataclass
class ScaleVertex(BaseGraphVertex):
    scale_factor: float = 1.0

    def apply(self, inputs):
        return inputs[0] * self.scale_factor


@register_vertex
@dataclasses.dataclass
class ShiftVertex(BaseGraphVertex):
    shift_factor: float = 0.0

    def apply(self, inputs):
        return inputs[0] + self.shift_factor


@register_vertex
@dataclasses.dataclass
class StackVertex(BaseGraphVertex):
    """Stack along the BATCH axis (DL4J ``StackVertex`` — used for shared
    weights over multiple inputs; unstack splits back)."""

    def n_inputs(self):
        return (1, None)

    def infer_shapes(self, input_types):
        return input_types[0]

    def apply(self, inputs):
        return jnp.concatenate(inputs, 0)


@register_vertex
@dataclasses.dataclass
class UnstackVertex(BaseGraphVertex):
    """Take batch-slab ``from_index`` of ``stack_size`` equal slabs
    (DL4J ``UnstackVertex``)."""

    from_index: int = 0
    stack_size: int = 1

    def apply(self, inputs):
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_index * step:(self.from_index + 1) * step]


@register_vertex
@dataclasses.dataclass
class L2NormalizeVertex(BaseGraphVertex):
    """Normalize each example to unit L2 norm over all non-batch axes
    (DL4J ``L2NormalizeVertex``)."""

    eps: float = 1e-8

    def apply(self, inputs):
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True))
        return x / (n + self.eps)


@register_vertex
@dataclasses.dataclass
class ReshapeVertex(BaseGraphVertex):
    """Reshape to ``new_shape`` (batch-free; batch dim preserved).  DL4J's
    ``ReshapeVertex`` takes the full shape with a mandatory -1 batch; here
    the batch axis is implicit."""

    new_shape: Sequence[int] = ()
    kind: str = "ff"  # InputType kind of the result

    def infer_shapes(self, input_types):
        return InputType(self.kind, tuple(self.new_shape))

    def apply(self, inputs):
        x = inputs[0]
        return x.reshape((x.shape[0],) + tuple(self.new_shape))


@register_vertex
@dataclasses.dataclass
class PreprocessorVertex(BaseGraphVertex):
    """Wrap an InputPreProcessor as a standalone vertex
    (DL4J ``PreprocessorVertex``)."""

    preprocessor: Optional[Preprocessor] = None

    def infer_shapes(self, input_types):
        it = input_types[0]
        name = self.preprocessor.name
        if name == "cnn_to_ff":
            return InputType("ff", (it.flat_size(),))
        if name == "ff_to_cnn":
            return InputType("cnn", tuple(self.preprocessor.spec))
        if name == "rnn_to_ff":
            return InputType("ff", (it.shape[-1],))
        if name == "ff_to_rnn":
            (t,) = self.preprocessor.spec
            return InputType("rnn", (t, it.shape[-1]))
        if name == "cnn_to_rnn":
            h, w, c = it.shape
            return InputType("rnn", (w, h * c))
        return it

    def apply(self, inputs):
        return self.preprocessor(inputs[0])
