"""Remaining DL4J layer types (VERDICT round-1 item 8).

Parity targets (``org.deeplearning4j.nn.conf.layers.**``):
``PReLULayer``, ``ElementWiseMultiplicationLayer``,
``LocallyConnected1D``/``LocallyConnected2D``, ``SelfAttentionLayer`` /
``LearnedSelfAttentionLayer``, ``Convolution3D`` / ``Subsampling3D``,
``CenterLossOutputLayer``, ``variational.VariationalAutoencoder``.

TPU notes: locally-connected layers extract patches with
``lax.conv_general_dilated_patches`` and contract with one einsum (no
per-position loop); attention is batched einsum softmax einsum — the MXU
path (a Pallas flash kernel can swap in later without touching configs);
3-D conv uses XLA's NDHWC lowering directly.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.base import BaseLayerConf, register_layer
from deeplearning4j_tpu.nn.conf.layers_conv import _pair
from deeplearning4j_tpu.nn.conf.layers_core import (
    BaseOutputLayerConf, DenseLayer, apply_dropout)
from deeplearning4j_tpu.nn.weights_init import init_weights


def _triple(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (list(v) + [v[-1]] * 3)[:3])
    return (int(v),) * 3


# ---------------------------------------------------------------------------
@register_layer
@dataclasses.dataclass
class PReLULayer(BaseLayerConf):
    """Parametric ReLU (``PReLULayer``): one learned alpha per input
    element, optionally shared over axes (DL4J ``sharedAxes``, 1-indexed
    over non-batch dims as upstream)."""

    input_shape: Optional[Sequence[int]] = None  # inferred
    shared_axes: Optional[Sequence[int]] = None

    WANTED_KINDS = ("ff", "cnn", "rnn")

    def infer_shapes(self, input_shape):
        shape = list(input_shape)
        for ax in (self.shared_axes or ()):
            shape[int(ax) - 1] = 1  # DL4J sharedAxes are 1-indexed
        for i, d in enumerate(shape):
            if d is None:
                raise ValueError(
                    "PReLULayer needs every non-shared input dim fixed; "
                    f"dim {i + 1} is dynamic — add it to shared_axes or "
                    "use a fixed InputType (e.g. recurrent(size, "
                    "timesteps))")
        # Dynamic dims are legal only on shared axes (alpha dim 1 there).
        self.input_shape = tuple(
            int(d) if d is not None else None for d in input_shape)
        self._alpha_shape = tuple(int(d) for d in shape)
        return input_shape

    def has_params(self):
        return True

    def init(self, key, dtype=jnp.float32):
        return {"alpha": jnp.zeros(self._alpha_shape, dtype)}, {}

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        a = params["alpha"].astype(x.dtype)
        y = jnp.maximum(x, 0) + a * jnp.minimum(x, 0)
        return apply_dropout(y, self.dropout, training, rng), state


# ---------------------------------------------------------------------------
@register_layer
@dataclasses.dataclass
class ElementWiseMultiplicationLayer(BaseLayerConf):
    """y = act(x * w + b) with learned per-feature w, b
    (``ElementWiseMultiplicationLayer``); n_out == n_in."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None

    WANTED_KINDS = ("ff",)

    def infer_shapes(self, input_shape):
        f = int(input_shape[-1])
        if self.n_out is not None and self.n_out != f:
            # DL4J validates nIn == nOut and fails fast.
            raise ValueError(
                f"ElementWiseMultiplicationLayer requires n_out == n_in "
                f"(got n_out={self.n_out}, input width {f})")
        self.n_in = self.n_out = f
        return input_shape

    def has_params(self):
        return True

    def init(self, key, dtype=jnp.float32):
        return {"w": jnp.ones((self.n_in,), dtype),
                "b": jnp.zeros((self.n_in,), dtype)}, {}

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        y = x * params["w"].astype(x.dtype) + params["b"].astype(x.dtype)
        y = get_activation(self.activation or "identity")(y)
        return apply_dropout(y, self.dropout, training, rng), state


# ---------------------------------------------------------------------------
@register_layer
@dataclasses.dataclass
class LocallyConnected2D(BaseLayerConf):
    """Unshared 2-D convolution (``LocallyConnected2D``): a separate
    kernel per output position.  Patches come from one
    ``conv_general_dilated_patches`` call; the per-position contraction is
    a single einsum the MXU batches over positions."""

    kernel_size: Sequence[int] = (2, 2)
    stride: Sequence[int] = (1, 1)
    convolution_mode: str = "truncate"  # or 'same'
    n_in: Optional[int] = None
    n_out: Optional[int] = None
    has_bias: bool = True
    _out_hw: Optional[Tuple[int, int]] = None

    WANTED_KINDS = ("cnn",)

    def _padding(self):
        return "SAME" if self.convolution_mode == "same" else "VALID"

    def infer_shapes(self, input_shape):
        h, w, c = (int(d) for d in input_shape)
        self.n_in = c
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        if self.convolution_mode == "same":
            oh, ow = -(-h // sh), -(-w // sw)
        else:
            oh, ow = (h - kh) // sh + 1, (w - kw) // sw + 1
        self._out_hw = (oh, ow)
        return (oh, ow, self.n_out)

    def has_params(self):
        return True

    def init(self, key, dtype=jnp.float32):
        kh, kw = _pair(self.kernel_size)
        oh, ow = self._out_hw
        fan_in = self.n_in * kh * kw
        w = init_weights(key, (oh, ow, kh * kw * self.n_in, self.n_out),
                         fan_in, self.n_out, self.weight_init, dtype,
                         self.weight_distribution)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.full((oh, ow, self.n_out), self.bias_init,
                                   dtype)
        return params, {}

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        w = params["W"]
        if compute_dtype is not None:
            x, w = x.astype(compute_dtype), w.astype(compute_dtype)
        patches = lax.conv_general_dilated_patches(
            x, _pair(self.kernel_size), _pair(self.stride), self._padding(),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        # conv_general_dilated_patches emits the patch feature dim as
        # C*kh*kw with the INPUT CHANNEL major (spatial offsets minor);
        # W's [oh, ow, kh*kw*cin, cout] dim 2 uses the same order.  Any
        # future weight importer for locally-connected layers must
        # permute into this layout.
        y = jnp.einsum("bhwk,hwko->bhwo", patches, w)
        if self.has_bias:
            y = y + params["b"].astype(y.dtype)
        y = get_activation(self.activation or "identity")(y)
        return apply_dropout(y, self.dropout, training, rng), state


@register_layer
@dataclasses.dataclass
class LocallyConnected1D(BaseLayerConf):
    """Unshared 1-D convolution over [b, t, f] (``LocallyConnected1D``)."""

    kernel_size: int = 2
    stride: int = 1
    convolution_mode: str = "truncate"
    n_in: Optional[int] = None
    n_out: Optional[int] = None
    has_bias: bool = True
    _out_t: Optional[int] = None

    WANTED_KINDS = ("rnn",)
    IS_RNN = False

    def infer_shapes(self, input_shape):
        t, f = input_shape
        self.n_in = int(f)
        k, s = int(self.kernel_size), int(self.stride)
        if self.convolution_mode == "same":
            ot = -(-int(t) // s) if t is not None else None
        else:
            ot = (int(t) - k) // s + 1 if t is not None else None
        self._out_t = ot
        return (ot, self.n_out)

    def has_params(self):
        return True

    def init(self, key, dtype=jnp.float32):
        k = int(self.kernel_size)
        ot = self._out_t
        if ot is None:
            raise ValueError(
                "LocallyConnected1D needs a fixed sequence length "
                "(InputType.recurrent(size, timesteps))")
        fan_in = self.n_in * k
        w = init_weights(key, (ot, k * self.n_in, self.n_out), fan_in,
                         self.n_out, self.weight_init, dtype,
                         self.weight_distribution)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.full((ot, self.n_out), self.bias_init, dtype)
        return params, {}

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        w = params["W"]
        if compute_dtype is not None:
            x, w = x.astype(compute_dtype), w.astype(compute_dtype)
        pad = "SAME" if self.convolution_mode == "same" else "VALID"
        patches = lax.conv_general_dilated_patches(
            x, (int(self.kernel_size),), (int(self.stride),), pad,
            dimension_numbers=("NTC", "TIO", "NTC"))
        y = jnp.einsum("btk,tko->bto", patches, w)
        if self.has_bias:
            y = y + params["b"].astype(y.dtype)
        y = get_activation(self.activation or "identity")(y)
        return apply_dropout(y, self.dropout, training, rng), state


# ---------------------------------------------------------------------------
@register_layer
@dataclasses.dataclass
class SelfAttentionLayer(BaseLayerConf):
    """Multi-head dot-product self-attention over [b, t, f]
    (``SelfAttentionLayer``): n_heads x head_size projections, optional
    output projection (``projectInput``), feature-mask aware."""

    n_heads: int = 1
    head_size: Optional[int] = None
    project_input: bool = True
    n_in: Optional[int] = None
    n_out: Optional[int] = None
    # Route the unmasked path through the Pallas flash kernel (TPU; CPU
    # uses its interpret mode).  Falls back to the einsum path whenever
    # a mask is present or the sequence doesn't tile.
    use_flash: bool = False

    WANTED_KINDS = ("rnn",)
    USES_MASK = True

    def infer_shapes(self, input_shape):
        t, f = input_shape
        self.n_in = int(f)
        if self.head_size is None:
            self.head_size = self.n_in // self.n_heads
        d = self.n_heads * self.head_size
        if not self.project_input and d != self.n_in:
            # DL4J SelfAttentionLayer validates exactly this.
            raise ValueError(
                f"projectInput=false requires n_heads*head_size == n_in "
                f"({self.n_heads}x{self.head_size} != {self.n_in})")
        if self.n_out is None:
            self.n_out = d if self.project_input else self.n_in
        return (t, self.n_out)

    def has_params(self):
        return True

    def init(self, key, dtype=jnp.float32):
        d = self.n_heads * self.head_size
        ks = jax.random.split(key, 4)
        mk = lambda k, shape: init_weights(k, shape, shape[0], shape[-1],
                                           self.weight_init, dtype,
                                           self.weight_distribution)
        params = {"Wq": mk(ks[0], (self.n_in, d)),
                  "Wk": mk(ks[1], (self.n_in, d)),
                  "Wv": mk(ks[2], (self.n_in, d))}
        if self.project_input:
            params["Wo"] = mk(ks[3], (d, self.n_out))
        return params, {}

    def _attend(self, q, k, v, mask):
        h, s = self.n_heads, self.head_size
        b, t, _ = q.shape
        split = lambda z: z.reshape(b, -1, h, s).transpose(0, 2, 1, 3)
        q, k, v = split(q), split(k), split(v)
        # The fused-attention entry routes to the Pallas flash kernel
        # when the shape permits (auto-tuned blocks) and falls back to
        # the XLA einsum path otherwise; a [b, t] sequence mask becomes
        # the kernel's additive key-position bias.
        if self.use_flash and q.shape[2] == k.shape[2]:
            from deeplearning4j_tpu.kernels import attention, mask_to_bias
            bias = mask_to_bias(mask)
            if jax.default_backend() == "tpu" and q.dtype == jnp.float32:
                # f32 operands run the MXU at 1/8 rate (see the
                # kernel header): use_flash on TPU implies bf16
                # attention math, the TPU-native training choice.
                q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
            out = attention(q, k, v, bias=bias)
            return out.transpose(0, 2, 1, 3).reshape(b, -1, h * s)
        logits = jnp.einsum("bhqs,bhks->bhqk", q, k) / jnp.sqrt(
            jnp.asarray(s, q.dtype))
        if mask is not None:
            neg = jnp.asarray(-1e9, logits.dtype)
            logits = jnp.where(mask[:, None, None, :] > 0, logits, neg)
        att = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhqk,bhks->bhqs", att, v)
        return out.transpose(0, 2, 1, 3).reshape(b, -1, h * s)

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None, mask=None):
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        cast = lambda w: w.astype(x.dtype)
        q = x @ cast(params["Wq"])
        k = x @ cast(params["Wk"])
        v = x @ cast(params["Wv"])
        y = self._attend(q, k, v, mask)
        if self.project_input:
            y = y @ cast(params["Wo"])
        return apply_dropout(y, self.dropout, training, rng), state


@register_layer
@dataclasses.dataclass
class LearnedSelfAttentionLayer(SelfAttentionLayer):
    """Attention with LEARNED queries (``LearnedSelfAttentionLayer``):
    n_queries fixed query vectors attend over the sequence; output is
    [b, n_queries, n_out] regardless of input length."""

    n_queries: int = 1

    def infer_shapes(self, input_shape):
        t, f = input_shape
        super().infer_shapes((t, f))
        return (self.n_queries, self.n_out)

    def init(self, key, dtype=jnp.float32):
        kq, rest = jax.random.split(key)
        params, state = super().init(rest, dtype)
        del params["Wq"]
        d = self.n_heads * self.head_size
        params["Q"] = init_weights(kq, (self.n_queries, d), d, d,
                                   self.weight_init, dtype,
                                   self.weight_distribution)
        return params, state

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None, mask=None):
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        cast = lambda w: w.astype(x.dtype)
        b = x.shape[0]
        q = jnp.broadcast_to(cast(params["Q"])[None],
                             (b,) + params["Q"].shape)
        k = x @ cast(params["Wk"])
        v = x @ cast(params["Wv"])
        y = self._attend(q, k, v, mask)
        if self.project_input:
            y = y @ cast(params["Wo"])
        return apply_dropout(y, self.dropout, training, rng), state


# ---------------------------------------------------------------------------
@register_layer
@dataclasses.dataclass
class Convolution3D(BaseLayerConf):
    """3-D convolution over [b, d, h, w, c] (``Convolution3D``, NDHWC —
    DL4J's NDHWC option; XLA lowers this natively)."""

    kernel_size: Sequence[int] = (2, 2, 2)
    stride: Sequence[int] = (1, 1, 1)
    convolution_mode: str = "truncate"
    n_in: Optional[int] = None
    n_out: Optional[int] = None
    has_bias: bool = True

    WANTED_KINDS = ("cnn3d",)

    def _padding(self):
        return "SAME" if self.convolution_mode == "same" else "VALID"

    def infer_shapes(self, input_shape):
        d, h, w, c = (int(v) for v in input_shape)
        self.n_in = c
        kd, kh, kw = _triple(self.kernel_size)
        sd, sh, sw = _triple(self.stride)
        if self.convolution_mode == "same":
            od, oh, ow = -(-d // sd), -(-h // sh), -(-w // sw)
        else:
            od, oh, ow = ((d - kd) // sd + 1, (h - kh) // sh + 1,
                          (w - kw) // sw + 1)
        return (od, oh, ow, self.n_out)

    def has_params(self):
        return True

    def init(self, key, dtype=jnp.float32):
        kd, kh, kw = _triple(self.kernel_size)
        fan_in = self.n_in * kd * kh * kw
        w = init_weights(key, (kd, kh, kw, self.n_in, self.n_out), fan_in,
                         self.n_out, self.weight_init, dtype,
                         self.weight_distribution)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        w = params["W"]
        if compute_dtype is not None:
            x, w = x.astype(compute_dtype), w.astype(compute_dtype)
        z = lax.conv_general_dilated(
            x, w, window_strides=_triple(self.stride),
            padding=self._padding(),
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.has_bias:
            z = z + params["b"].astype(z.dtype)
        y = get_activation(self.activation or "identity")(z)
        return apply_dropout(y, self.dropout, training, rng), state


@register_layer
@dataclasses.dataclass
class Subsampling3DLayer(BaseLayerConf):
    """3-D max/avg pooling (``Subsampling3DLayer``)."""

    kernel_size: Sequence[int] = (2, 2, 2)
    stride: Sequence[int] = (2, 2, 2)
    pooling_type: str = "max"

    WANTED_KINDS = ("cnn3d",)

    def infer_shapes(self, input_shape):
        d, h, w, c = (int(v) for v in input_shape)
        kd, kh, kw = _triple(self.kernel_size)
        sd, sh, sw = _triple(self.stride)
        return ((d - kd) // sd + 1, (h - kh) // sh + 1, (w - kw) // sw + 1,
                c)

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        k = (1,) + _triple(self.kernel_size) + (1,)
        s = (1,) + _triple(self.stride) + (1,)
        if self.pooling_type == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, k, s, "VALID")
        elif self.pooling_type == "avg":
            tot = lax.reduce_window(x, 0.0, lax.add, k, s, "VALID")
            y = tot / float(math.prod(_triple(self.kernel_size)))
        else:
            raise ValueError(f"pooling_type {self.pooling_type!r}")
        return y, state


# ---------------------------------------------------------------------------
@register_layer
@dataclasses.dataclass
class CenterLossOutputLayer(BaseOutputLayerConf, DenseLayer):
    """Softmax head + center loss (``CenterLossOutputLayer``):
    L = CE + (lambda/2)·||f − c_y||².  Deviation from DL4J noted: centers
    are PARAMETERS optimized by the configured updater via the gradient
    of the center term (DL4J hand-applies an `alpha` moving average inside
    backprop); same fixed point, and the gradient-check harness covers
    the whole loss including the centers."""

    alpha: float = 0.05  # kept for config parity / serialization
    lambda_: float = 2e-4

    def init(self, key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        params, state = DenseLayer.init(self, k1, dtype)
        params["centers"] = jnp.zeros((self.n_out, self.n_in), dtype)
        return params, state

    def regularized_param_names(self):
        return ("W",)

    def center_score(self, params, features, labels):
        """(lambda/2)·||f − c_y||² per example; labels one-hot [b, C]."""
        centers_y = labels.astype(features.dtype) @ params["centers"].astype(
            features.dtype)
        return 0.5 * self.lambda_ * jnp.sum(
            jnp.square(features - centers_y), axis=-1)
    def per_example_score(self, labels, z, mask=None, head_input=None,
                          rng=None, params=None):
        ce = super().per_example_score(labels, z, mask)
        if head_input is None or params is None:
            return ce
        center = self.center_score(params, self.promote_head(head_input),
                                   labels)
        if mask is not None:
            center = center * mask.reshape(center.shape[0])
        return ce + center


# ---------------------------------------------------------------------------
@register_layer
@dataclasses.dataclass
class VariationalAutoencoder(BaseOutputLayerConf):
    """``variational.VariationalAutoencoder``: encoder MLP → (mu, logvar)
    → reparameterized z → decoder MLP → reconstruction distribution;
    trained on -ELBO with ``fit(DataSet(x, x))`` (DL4J trains it as the
    unsupervised pretrain layer).  ``apply`` returns the posterior MEAN
    (the embedding DL4J's activate() exposes).

    ``reconstruction_distribution``: 'gaussian' (loss over mean+logvar
    outputs) or 'bernoulli' (logits + binary CE).
    """

    n_in: Optional[int] = None
    n_out: Optional[int] = None  # latent size n_z
    encoder_layer_sizes: Sequence[int] = (16,)
    decoder_layer_sizes: Sequence[int] = (16,)
    reconstruction_distribution: str = "gaussian"
    num_samples: int = 1

    WANTED_KINDS = ("ff",)

    def infer_shapes(self, input_shape):
        self.n_in = int(input_shape[-1])
        return (self.n_out,)

    def has_params(self):
        return True

    def _stack_sizes(self):
        enc = [self.n_in, *self.encoder_layer_sizes]
        dec = [self.n_out, *self.decoder_layer_sizes]
        recon_out = (2 * self.n_in
                     if self.reconstruction_distribution == "gaussian"
                     else self.n_in)
        return enc, dec, recon_out

    def init(self, key, dtype=jnp.float32):
        enc, dec, recon_out = self._stack_sizes()
        n_mats = (len(enc) - 1) + 2 + (len(dec) - 1) + 1
        ks = list(jax.random.split(key, n_mats))
        params = {}

        def dense(name, n_in, n_out):
            k = ks.pop(0)
            params[f"{name}_W"] = init_weights(
                k, (n_in, n_out), n_in, n_out, self.weight_init, dtype,
                self.weight_distribution)
            params[f"{name}_b"] = jnp.zeros((n_out,), dtype)

        for i in range(len(enc) - 1):
            dense(f"enc{i}", enc[i], enc[i + 1])
        dense("mu", enc[-1], self.n_out)
        dense("logvar", enc[-1], self.n_out)
        for i in range(len(dec) - 1):
            dense(f"dec{i}", dec[i], dec[i + 1])
        dense("recon", dec[-1], recon_out)
        return params, {}

    def _dense(self, params, name, x, act="relu"):
        y = x @ params[f"{name}_W"].astype(x.dtype) + \
            params[f"{name}_b"].astype(x.dtype)
        return get_activation(act)(y)

    def _encode(self, params, x):
        h = x
        for i in range(len(self.encoder_layer_sizes)):
            h = self._dense(params, f"enc{i}", h,
                            self.activation or "relu")
        mu = self._dense(params, "mu", h, "identity")
        logvar = self._dense(params, "logvar", h, "identity")
        return mu, logvar

    def _decode(self, params, z):
        h = z
        for i in range(len(self.decoder_layer_sizes)):
            h = self._dense(params, f"dec{i}", h,
                            self.activation or "relu")
        return self._dense(params, "recon", h, "identity")

    def pre_output(self, params, x, compute_dtype=None):
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        return x

    def per_example_score(self, labels, z, mask=None, head_input=None,
                          rng=None, params=None):
        """-ELBO per example.  ``z`` is the raw feature batch (see
        pre_output); ``labels`` is the reconstruction target (DataSet(x,
        x) — DL4J ignores labels entirely and reconstructs the features;
        accepting a distinct target is a superset)."""
        if params is None:
            raise ValueError(
                "VariationalAutoencoder scoring needs the layer params "
                "(the model passes params= automatically)")
        x = self.promote_head(z)
        target = self.promote_head(labels) if labels is not None else x
        mu, logvar = self._encode(params, x)
        n_s = max(int(self.num_samples), 1)
        if rng is not None and self.num_samples > 0:
            # DL4J numSamples: Monte-Carlo average of the reconstruction
            # term over n_s reparameterized draws.
            eps = jax.random.normal(rng, (n_s,) + mu.shape, mu.dtype)
        else:
            eps = jnp.zeros((1,) + mu.shape, mu.dtype)  # mean-field path

        def recon_nll(e):
            zs = mu + e * jnp.exp(0.5 * logvar)
            out = self._decode(params, zs)
            if self.reconstruction_distribution == "gaussian":
                r_mu, r_logvar = jnp.split(out, 2, axis=-1)
                return 0.5 * jnp.sum(
                    r_logvar + jnp.square(target - r_mu) / jnp.exp(r_logvar)
                    + jnp.log(2 * jnp.pi), axis=-1)
            if self.reconstruction_distribution == "bernoulli":
                # softplus form: stable for large |logit| (exp(-out)
                # overflows f32 past ~88)
                return jnp.sum(jax.nn.softplus(out) - out * target,
                               axis=-1)
            raise ValueError(self.reconstruction_distribution)

        nll = jnp.mean(jax.vmap(recon_nll)(eps), axis=0)
        kl = -0.5 * jnp.sum(1 + logvar - jnp.square(mu) - jnp.exp(logvar),
                            axis=-1)
        score = nll + kl
        if mask is not None:
            score = score * mask.reshape(score.shape[0])
        return score

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        mu, _ = self._encode(params, x)
        return self.promote_head(mu), state

    def reconstruct(self, params, x):
        """Encoder mean → decoder output (DL4J ``reconstructionOutput``)."""
        mu, _ = self._encode(params, jnp.asarray(x))
        out = self._decode(params, mu)
        if self.reconstruction_distribution == "gaussian":
            return jnp.split(out, 2, axis=-1)[0]
        return jax.nn.sigmoid(out)
