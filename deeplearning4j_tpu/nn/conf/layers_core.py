"""Feed-forward layer configs: Dense, Output, Activation, Dropout, Embedding.

Parity targets (reference paths, upstream layout):
* ``org.deeplearning4j.nn.conf.layers.DenseLayer`` + runtime
  ``org.deeplearning4j.nn.layers.feedforward.dense.DenseLayer``
* ``org.deeplearning4j.nn.conf.layers.OutputLayer`` + runtime
  ``org.deeplearning4j.nn.layers.BaseOutputLayer`` (loss integration)
* ``EmbeddingLayer`` / ``EmbeddingSequenceLayer``
* ``ActivationLayer``, ``DropoutLayer``, ``LossLayer``

Each DL4J runtime class hand-writes ``activate`` + ``backpropGradient``;
here only the forward exists (jax.grad supplies the backward), and XLA fuses
bias+activation into the matmul — the work DL4J delegated to cuDNN.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.base import BaseLayerConf, register_layer
from deeplearning4j_tpu.nn.losses import FUSED_ACTIVATIONS, get_loss
from deeplearning4j_tpu.nn.weights_init import init_weights


def apply_dropout(x, rate: float, training: bool, rng):
    """Inverted dropout.  DL4J's ``dropOut(p)`` takes a RETAIN probability
    (``org.deeplearning4j.nn.conf.dropout.Dropout``); our configs store the
    DROP rate (pythonic); conversion happens in the compat shims."""
    if not training or not rate or rng is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


@register_layer
@dataclasses.dataclass
class DenseLayer(BaseLayerConf):
    """Fully connected layer: y = act(x @ W + b)."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    has_bias: bool = True

    # Dense applies over the last axis, so it natively consumes flat [b, f]
    # and sequence [b, t, f] inputs (XLA batches the matmul); conv inputs
    # are flattened by an auto-inserted preprocessor.
    WANTED_KINDS = ("ff", "rnn")

    def infer_shapes(self, input_shape):
        if self.n_in is None:
            self.n_in = int(input_shape[-1])
        return tuple(input_shape[:-1]) + (self.n_out,)

    def has_params(self):
        return True

    def init(self, key, dtype=jnp.float32):
        w = init_weights(
            key, (self.n_in, self.n_out), self.n_in, self.n_out,
            self.weight_init, dtype, self.weight_distribution,
        )
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}

    def pre_output(self, params, x, compute_dtype=None):
        w = params["W"]
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
            w = w.astype(compute_dtype)
        z = x @ w
        if self.has_bias:
            z = z + params["b"].astype(z.dtype)
        # Activations STAY in compute dtype (bf16 on TPU): casting back up
        # per layer doubles HBM traffic for every downstream elementwise op.
        # Loss heads promote to >=f32 (see per_example_score).
        return z

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        z = self.pre_output(params, x, compute_dtype)
        y = get_activation(self.activation or "identity")(z)
        y = apply_dropout(y, self.dropout, training, rng)
        return y, state


@register_layer
@dataclasses.dataclass
class ActivationLayer(BaseLayerConf):
    """Standalone activation (``org.deeplearning4j.nn.conf.layers.ActivationLayer``)."""

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        return get_activation(self.activation or "identity")(x), state


@register_layer
@dataclasses.dataclass
class DropoutLayer(BaseLayerConf):
    """Standalone dropout (``DropoutLayer``); `rate` is the drop probability."""

    rate: float = 0.5

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        return apply_dropout(x, self.rate, training, rng), state


@register_layer
@dataclasses.dataclass
class EmbeddingLayer(BaseLayerConf):
    """Index -> vector lookup (``EmbeddingLayer``): input [batch] or
    [batch,1] of int ids, output [batch, n_out].  On TPU this is a gather —
    one-hot matmul is used for tiny vocabularies where MXU beats gather."""

    n_in: Optional[int] = None  # vocabulary size
    n_out: Optional[int] = None
    has_bias: bool = False

    def infer_shapes(self, input_shape):
        return (self.n_out,)

    def has_params(self):
        return True

    def init(self, key, dtype=jnp.float32):
        w = init_weights(key, (self.n_in, self.n_out), self.n_in, self.n_out,
                         self.weight_init, dtype, self.weight_distribution)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        idx = x.astype(jnp.int32)
        if idx.ndim >= 2 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        y = jnp.take(params["W"], idx, axis=0)
        if self.has_bias:
            y = y + params["b"]
        y = get_activation(self.activation or "identity")(y)
        return y, state


@dataclasses.dataclass
class BaseOutputLayerConf(BaseLayerConf):
    """Shared loss plumbing for Output/RnnOutput/Loss layers
    (``org.deeplearning4j.nn.layers.BaseOutputLayer``)."""

    loss: str = "mcxent"

    @staticmethod
    def promote_head(z):
        """Loss heads and user-facing head activations run at >=f32
        (bf16 softmax is numerically unsafe); f64 stays f64 for the
        gradient-check harness."""
        return z.astype(jnp.promote_types(z.dtype, jnp.float32))

    def per_example_score(self, labels, z, mask=None, head_input=None,
                          rng=None, params=None):
        """Per-example loss from PRE-activation z, fusing softmax/sigmoid
        into the loss when numerically profitable (LossMCXENT's fused path).

        Sequence outputs ([b, t, c]) are scored per timestep by folding
        time into the batch, so a label mask [b, t] (or [b, t, 1]) weights
        individual timesteps — DL4J's per-timestep masked scoring in
        ``BaseOutputLayer.computeScore`` with ``LossUtil`` masking.
        Mask shapes [b] and [b, 1] weight whole examples.
        """
        act = (self.activation or "identity").lower()
        loss_name = str(self.loss).lower()
        loss_fn = get_loss(loss_name)
        z = self.promote_head(z)

        seq = z.ndim == 3
        if seq:
            b, t = z.shape[0], z.shape[1]
            z2 = z.reshape(b * t, z.shape[-1])
            lab2 = (labels.reshape(b * t, labels.shape[-1])
                    if labels.ndim == 3 else labels.reshape(b * t))
        else:
            z2, lab2 = z, labels

        if FUSED_ACTIVATIONS.get(loss_name) == act:
            scores = loss_fn(lab2, None, logits=z2)
        else:
            scores = loss_fn(lab2, get_activation(act)(z2))

        if seq:
            scores = scores.reshape(b, t)
            if mask is not None:
                m = mask[..., 0] if mask.ndim == 3 else mask
                scores = scores * m
            scores = jnp.sum(scores, axis=1)
        elif mask is not None:
            scores = scores * mask.reshape(scores.shape[0])
        return scores


@register_layer
@dataclasses.dataclass
class OutputLayer(BaseOutputLayerConf, DenseLayer):
    """Dense + loss head (``org.deeplearning4j.nn.conf.layers.OutputLayer``)."""

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        z = self.promote_head(self.pre_output(params, x, compute_dtype))
        return get_activation(self.activation or "identity")(z), state


@register_layer
@dataclasses.dataclass
class LossLayer(BaseOutputLayerConf):
    """Loss without params (``org.deeplearning4j.nn.conf.layers.LossLayer``)."""

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        x = self.promote_head(x)
        return get_activation(self.activation or "identity")(x), state

    def pre_output(self, params, x, compute_dtype=None):
        return x
