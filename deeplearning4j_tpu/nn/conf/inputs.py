"""Input types and automatic shape preprocessors.

Parity with ``org.deeplearning4j.nn.conf.inputs.InputType`` (FF / recurrent /
convolutional) and the ``InputPreProcessor`` family
(``CnnToFeedForwardPreProcessor``, ``FeedForwardToCnnPreProcessor``,
``RnnToFeedForwardPreProcessor``, ``FeedForwardToRnnPreProcessor``,
``RnnToCnnPreProcessor``, ``CnnToRnnPreProcessor``).

DL4J stores images NCHW; this framework is NHWC end-to-end (the layout the
TPU conv lowering wants), so "convolutional(h, w, c)" here means a
[batch, h, w, c] tensor.  Recurrent data is [batch, time, features]
(DL4J uses [batch, features, time]; iterators adapt).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class InputType:
    """kind: 'ff' (features,), 'cnn' (h, w, c), 'rnn' (time, features).
    Shapes are batch-free; time may be None (dynamic — resolved per batch).
    """

    kind: str
    shape: Tuple[Optional[int], ...]

    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType("ff", (int(size),))

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnn", (int(height), int(width), int(channels)))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        # DL4J's convolutionalFlat: data arrives flattened, first layer conv
        return InputType("cnn_flat", (int(height), int(width), int(channels)))

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "InputType":
        return InputType("rnn", (timesteps, int(size)))

    @staticmethod
    def convolutional3d(depth: int, height: int, width: int,
                        channels: int) -> "InputType":
        """NDHWC volumetric input (DL4J InputType.convolutional3D)."""
        return InputType("cnn3d", (int(depth), int(height), int(width),
                                   int(channels)))

    def flat_size(self) -> int:
        n = 1
        for s in self.shape:
            if s is not None:
                n *= s
        return n

    def to_dict(self):
        return {"kind": self.kind, "shape": list(self.shape)}

    @staticmethod
    def from_dict(d):
        return InputType(d["kind"], tuple(d["shape"]))


# ---------------------------------------------------------------------------
# Preprocessors — pure reshape adapters auto-inserted between layer kinds.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Preprocessor:
    """name identifies the reshape; spec carries static dims it needs."""

    name: str
    spec: Tuple[int, ...] = ()

    def __call__(self, x):
        if self.name == "cnn_to_ff":          # [b,h,w,c] -> [b, h*w*c]
            return x.reshape(x.shape[0], -1)
        if self.name == "ff_to_cnn":          # [b, n] -> [b,h,w,c]
            h, w, c = self.spec
            return x.reshape(x.shape[0], h, w, c)
        if self.name == "rnn_to_ff":          # [b,t,f] -> [b*t, f]
            return x.reshape(-1, x.shape[-1])
        if self.name == "ff_to_rnn":          # [b*t, f] -> [b,t,f]
            (t,) = self.spec
            return x.reshape(-1, t, x.shape[-1])
        if self.name == "cnn_to_rnn":         # [b,h,w,c] -> [b, h*w, c]? DL4J: time=h*w? No:
            # DL4J CnnToRnn: [b,c,h,w] -> [b, c*h*w over time]? Actually maps
            # width as time: [b,h,w,c] -> [b, w, h*c]
            return x.transpose(0, 2, 1, 3).reshape(x.shape[0], x.shape[2], -1)
        if self.name == "cnn3d_to_ff":      # [b,d,h,w,c] -> [b, d*h*w*c]
            return x.reshape(x.shape[0], -1)
        if self.name == "identity":
            return x
        raise ValueError(f"Unknown preprocessor {self.name!r}")

    def to_dict(self):
        return {"name": self.name, "spec": list(self.spec)}

    @staticmethod
    def from_dict(d):
        return Preprocessor(d["name"], tuple(d.get("spec", ())))


def adapt(input_type: InputType, wanted_kind: str):
    """Return (preprocessor | None, new InputType) adapting `input_type` to
    the kind a layer wants ('ff'/'cnn'/'rnn'/'any').  Mirrors DL4J's
    automatic InputPreProcessor insertion in
    ``MultiLayerConfiguration.Builder#build``."""
    kind = input_type.kind
    if wanted_kind in ("any", kind):
        return None, input_type
    if kind == "cnn_flat" and wanted_kind == "cnn":
        h, w, c = input_type.shape
        return Preprocessor("ff_to_cnn", (h, w, c)), InputType("cnn", (h, w, c))
    if kind == "cnn_flat" and wanted_kind == "ff":
        return None, InputType("ff", (input_type.flat_size(),))
    if kind == "cnn" and wanted_kind == "ff":
        return Preprocessor("cnn_to_ff"), InputType("ff", (input_type.flat_size(),))
    if kind == "ff" and wanted_kind == "cnn":
        raise ValueError("ff->cnn requires explicit InputType.convolutional_flat")
    if kind == "cnn" and wanted_kind == "rnn":
        h, w, c = input_type.shape
        return Preprocessor("cnn_to_rnn"), InputType("rnn", (w, h * c))
    if kind == "cnn3d" and wanted_kind == "ff":
        return Preprocessor("cnn3d_to_ff"), InputType(
            "ff", (input_type.flat_size(),))
    if kind == "rnn" and wanted_kind == "ff":
        t, f = input_type.shape
        # Dense over every timestep: fold time into batch (DL4J
        # RnnToFeedForwardPreProcessor semantics); restored by ff_to_rnn.
        return Preprocessor("rnn_to_ff"), InputType("ff", (f,))
    raise ValueError(f"No preprocessor from {kind!r} to {wanted_kind!r}")
