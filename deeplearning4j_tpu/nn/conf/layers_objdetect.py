"""Object-detection layers
(``org.deeplearning4j.nn.layers.objdetect.Yolo2OutputLayer`` +
``org.deeplearning4j.nn.conf.layers.objdetect.Yolo2OutputLayer``).

Lives under nn/conf so the layer registry is populated by the standard
config imports — a TinyYOLO checkpoint restores in any process without
importing the zoo first.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.base import register_layer
from deeplearning4j_tpu.nn.conf.layers_core import BaseOutputLayerConf


@register_layer
@dataclasses.dataclass
class Yolo2OutputLayer(BaseOutputLayerConf):
    """Detection loss head over a [b, gh, gw, 5 + n_classes] feature map.

    lambda_coord / lambda_noobj follow the YOLO paper defaults DL4J
    exposes.  Predictions: sigmoid on objectness + cx/cy, raw w/h,
    softmax over classes.
    """

    n_classes: int = 20
    lambda_coord: float = 5.0
    lambda_noobj: float = 0.5

    WANTED_KINDS = ("cnn",)

    def infer_shapes(self, input_shape):
        h, w, c = input_shape
        want = 5 + self.n_classes
        if int(c) != want:
            raise ValueError(
                f"Yolo2OutputLayer needs {want} input channels "
                f"(5 + n_classes), got {c}")
        return input_shape

    def pre_output(self, params, x, compute_dtype=None):
        return x

    def per_example_score(self, labels, z, mask=None, head_input=None,
                          rng=None, params=None):
        z = self.promote_head(z)
        labels = self.promote_head(labels)
        obj_logit = z[..., 0]
        xy = jax.nn.sigmoid(z[..., 1:3])
        wh = z[..., 3:5]
        cls_logits = z[..., 5:]
        t_obj = labels[..., 0]
        t_xy = labels[..., 1:3]
        t_wh = labels[..., 3:5]
        t_cls = labels[..., 5:]

        coord = jnp.sum(jnp.square(xy - t_xy), -1) + \
            jnp.sum(jnp.square(wh - t_wh), -1)
        obj_p = jax.nn.sigmoid(obj_logit)
        conf_obj = jnp.square(1.0 - obj_p)
        conf_noobj = jnp.square(obj_p)
        cls_ce = -jnp.sum(t_cls * jax.nn.log_softmax(cls_logits, -1), -1)
        per_cell = (t_obj * (self.lambda_coord * coord + conf_obj + cls_ce)
                    + (1.0 - t_obj) * self.lambda_noobj * conf_noobj)
        score = jnp.sum(per_cell, axis=(1, 2))
        if mask is not None:
            score = score * mask.reshape(score.shape[0])
        return score

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        x = self.promote_head(x)
        out = jnp.concatenate(
            [jax.nn.sigmoid(x[..., :1]), jax.nn.sigmoid(x[..., 1:3]),
             x[..., 3:5], jax.nn.softmax(x[..., 5:], -1)], axis=-1)
        return out, state


