"""Convolutional layer configs: Conv2D/1D, Subsampling, BatchNorm, etc.

Parity targets (reference paths, upstream layout):
* ``org.deeplearning4j.nn.conf.layers.ConvolutionLayer`` + runtime
  ``org.deeplearning4j.nn.layers.convolution.ConvolutionLayer`` (and its
  cuDNN/oneDNN helper seam — replaced wholesale by XLA's conv lowering)
* ``SubsamplingLayer`` (MAX/AVG/SUM/PNORM pooling)
* ``BatchNormalization`` (+ ``CudnnBatchNormalizationHelper``)
* ``GlobalPoolingLayer``, ``Upsampling2D``, ``ZeroPaddingLayer``,
  ``DepthwiseConvolution2D``, ``SeparableConvolution2D``,
  ``Deconvolution2D``, ``LocalResponseNormalization``, ``Cropping2D``,
  ``SpaceToDepthLayer``

TPU-first notes: layout is NHWC with HWIO kernels — the layout XLA's TPU
conv emitter wants (DL4J is NCHW).  The conv itself is
``lax.conv_general_dilated``, which XLA tiles onto the MXU; bias + ReLU
fuse into it.  There is no helper indirection (no cuDNN algo selection, no
im2col fallback) — that whole seam from the reference does not exist here.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.base import BaseLayerConf, register_layer
from deeplearning4j_tpu.nn.conf.layers_core import (
    BaseOutputLayerConf, apply_dropout)
from deeplearning4j_tpu.nn.weights_init import init_weights


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1] if len(v) > 1 else v[0]))
    return (int(v), int(v))


def _conv_out(size: int, k: int, s: int, p: int, d: int, mode: str) -> int:
    """Output spatial size per DL4J ConvolutionUtils.getOutputSize;
    raises for ConvolutionMode.Strict when shapes don't divide exactly."""
    eff_k = (k - 1) * d + 1
    if mode == "same":
        return -(-size // s)  # ceil
    if mode == "strict" and (size + 2 * p - eff_k) % s:
        raise ValueError(
            f"ConvolutionMode.Strict: size {size} with kernel {k} stride "
            f"{s} pad {p} dilation {d} does not divide exactly")
    return (size + 2 * p - eff_k) // s + 1


def _tblr(spec) -> Tuple[int, int, int, int]:
    """Expand a (h, w) pair or explicit (top, bottom, left, right)."""
    p = list(spec)
    if len(p) == 2:
        return p[0], p[0], p[1], p[1]
    return p[0], p[1], p[2], p[3]


def _padding_config(mode: str, pad: Tuple[int, int]):
    """lax padding argument for a 2-D conv/pool."""
    if mode == "same":
        return "SAME"
    return [(pad[0], pad[0]), (pad[1], pad[1])]


@register_layer
@dataclasses.dataclass
class ConvolutionLayer(BaseLayerConf):
    """2-D convolution (``org.deeplearning4j.nn.conf.layers.ConvolutionLayer``).

    ``convolution_mode``: 'truncate' (DL4J default — floor division),
    'same', or 'strict' (shape must divide exactly).  Explicit ``padding``
    only applies to truncate/strict, as in DL4J.
    """

    kernel_size: Sequence[int] = (3, 3)
    stride: Sequence[int] = (1, 1)
    padding: Sequence[int] = (0, 0)
    dilation: Sequence[int] = (1, 1)
    convolution_mode: str = "truncate"
    n_in: Optional[int] = None   # input channels
    n_out: Optional[int] = None  # output channels
    has_bias: bool = True

    WANTED_KINDS = ("cnn",)

    def infer_shapes(self, input_shape):
        h, w, c = input_shape
        if self.n_in is None:
            self.n_in = int(c)
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        dh, dw = _pair(self.dilation)
        mode = self.convolution_mode
        oh = _conv_out(h, kh, sh, ph, dh, mode)
        ow = _conv_out(w, kw, sw, pw, dw, mode)
        return (oh, ow, self.n_out)

    def has_params(self):
        return True

    def init(self, key, dtype=jnp.float32):
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        # DL4J ConvolutionParamInitializer fan conventions:
        fan_in = self.n_in * kh * kw
        fan_out = self.n_out * kh * kw / float(sh * sw)
        w = init_weights(key, (kh, kw, self.n_in, self.n_out), fan_in,
                         fan_out, self.weight_init, dtype,
                         self.weight_distribution)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}

    def _conv(self, x, w):
        mode = self.convolution_mode
        pad = _padding_config("same" if mode == "same" else mode,
                              _pair(self.padding))
        return lax.conv_general_dilated(
            x, w, window_strides=_pair(self.stride), padding=pad,
            rhs_dilation=_pair(self.dilation),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        w = params["W"]
        if compute_dtype is not None:
            x, w = x.astype(compute_dtype), w.astype(compute_dtype)
        z = self._conv(x, w)
        if self.has_bias:
            z = z + params["b"].astype(z.dtype)
        y = get_activation(self.activation or "identity")(z)
        return apply_dropout(y, self.dropout, training, rng), state


@register_layer
@dataclasses.dataclass
class Deconvolution2D(ConvolutionLayer):
    """Transposed conv (``org.deeplearning4j.nn.conf.layers.Deconvolution2D``)."""

    def infer_shapes(self, input_shape):
        h, w, c = input_shape
        if self.n_in is None:
            self.n_in = int(c)
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        dh, dw = _pair(self.dilation)
        if self.convolution_mode == "same":
            oh, ow = h * sh, w * sw
        else:
            eff_kh, eff_kw = (kh - 1) * dh + 1, (kw - 1) * dw + 1
            oh = sh * (h - 1) + eff_kh - 2 * ph
            ow = sw * (w - 1) + eff_kw - 2 * pw
        return (oh, ow, self.n_out)

    def _conv(self, x, w):
        mode = self.convolution_mode
        if mode == "same":
            pad = "SAME"
        else:
            # lax.conv_transpose pads the dilated input directly; forward-
            # conv padding p maps to transpose padding (eff_k - 1 - p).
            kh, kw = _pair(self.kernel_size)
            dh, dw = _pair(self.dilation)
            ph, pw = _pair(self.padding)
            eff_kh, eff_kw = (kh - 1) * dh + 1, (kw - 1) * dw + 1
            pad = [(eff_kh - 1 - ph, eff_kh - 1 - ph),
                   (eff_kw - 1 - pw, eff_kw - 1 - pw)]
        return lax.conv_transpose(
            x, w, strides=_pair(self.stride), padding=pad,
            rhs_dilation=_pair(self.dilation),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


@register_layer
@dataclasses.dataclass
class DepthwiseConvolution2D(BaseLayerConf):
    """Per-channel conv (``DepthwiseConvolution2D``); output channels =
    n_in * depth_multiplier."""

    kernel_size: Sequence[int] = (3, 3)
    stride: Sequence[int] = (1, 1)
    padding: Sequence[int] = (0, 0)
    dilation: Sequence[int] = (1, 1)
    convolution_mode: str = "truncate"
    depth_multiplier: int = 1
    n_in: Optional[int] = None
    has_bias: bool = True

    WANTED_KINDS = ("cnn",)

    def infer_shapes(self, input_shape):
        h, w, c = input_shape
        if self.n_in is None:
            self.n_in = int(c)
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        dh, dw = _pair(self.dilation)
        oh = _conv_out(h, kh, sh, ph, dh, self.convolution_mode)
        ow = _conv_out(w, kw, sw, pw, dw, self.convolution_mode)
        return (oh, ow, self.n_in * self.depth_multiplier)

    def has_params(self):
        return True

    def init(self, key, dtype=jnp.float32):
        kh, kw = _pair(self.kernel_size)
        n_out = self.n_in * self.depth_multiplier
        fan_in, fan_out = kh * kw, kh * kw * self.depth_multiplier
        w = init_weights(key, (kh, kw, 1, n_out), fan_in, fan_out,
                         self.weight_init, dtype, self.weight_distribution)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.full((n_out,), self.bias_init, dtype)
        return params, {}

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        w = params["W"]
        if compute_dtype is not None:
            x, w = x.astype(compute_dtype), w.astype(compute_dtype)
        pad = _padding_config(
            "same" if self.convolution_mode == "same" else "truncate",
            _pair(self.padding))
        z = lax.conv_general_dilated(
            x, w, window_strides=_pair(self.stride), padding=pad,
            rhs_dilation=_pair(self.dilation),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.n_in)
        if self.has_bias:
            z = z + params["b"].astype(z.dtype)
        y = get_activation(self.activation or "identity")(z)
        return apply_dropout(y, self.dropout, training, rng), state


@register_layer
@dataclasses.dataclass
class SeparableConvolution2D(DepthwiseConvolution2D):
    """Depthwise + 1x1 pointwise (``SeparableConvolution2D``)."""

    n_out: Optional[int] = None

    def infer_shapes(self, input_shape):
        oh, ow, _ = super().infer_shapes(input_shape)
        return (oh, ow, self.n_out)

    def init(self, key, dtype=jnp.float32):
        k_dw, k_pw = jax.random.split(key)
        kh, kw = _pair(self.kernel_size)
        mid = self.n_in * self.depth_multiplier
        dw = init_weights(k_dw, (kh, kw, 1, mid), kh * kw,
                          kh * kw * self.depth_multiplier, self.weight_init,
                          dtype, self.weight_distribution)
        pw = init_weights(k_pw, (1, 1, mid, self.n_out), mid, self.n_out,
                          self.weight_init, dtype, self.weight_distribution)
        params = {"W": dw, "pW": pw}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}

    def regularized_param_names(self):
        return ("W", "pW")

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        w, pw = params["W"], params["pW"]
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
            w, pw = w.astype(compute_dtype), pw.astype(compute_dtype)
        pad = _padding_config(
            "same" if self.convolution_mode == "same" else "truncate",
            _pair(self.padding))
        z = lax.conv_general_dilated(
            x, w, window_strides=_pair(self.stride), padding=pad,
            rhs_dilation=_pair(self.dilation),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.n_in)
        z = lax.conv_general_dilated(
            z, pw, window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            z = z + params["b"].astype(z.dtype)
        y = get_activation(self.activation or "identity")(z)
        return apply_dropout(y, self.dropout, training, rng), state


@register_layer
@dataclasses.dataclass
class Convolution1DLayer(BaseLayerConf):
    """1-D conv over [batch, time, features]
    (``org.deeplearning4j.nn.conf.layers.Convolution1DLayer``)."""

    kernel_size: int = 3
    stride: int = 1
    padding: int = 0
    dilation: int = 1
    convolution_mode: str = "same"  # DL4J Conv1D default keeps length
    n_in: Optional[int] = None
    n_out: Optional[int] = None
    has_bias: bool = True

    WANTED_KINDS = ("rnn",)

    def infer_shapes(self, input_shape):
        t, f = input_shape
        if self.n_in is None:
            self.n_in = int(f)
        if t is None:
            return (None, self.n_out)
        if self.convolution_mode == "causal":
            ot = -(-t // self.stride)
        else:
            ot = _conv_out(t, self.kernel_size, self.stride, self.padding,
                           self.dilation, self.convolution_mode)
        return (ot, self.n_out)

    def has_params(self):
        return True

    def init(self, key, dtype=jnp.float32):
        k = int(self.kernel_size)
        fan_in = self.n_in * k
        fan_out = self.n_out * k / float(self.stride)
        w = init_weights(key, (k, self.n_in, self.n_out), fan_in, fan_out,
                         self.weight_init, dtype, self.weight_distribution)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        w = params["W"]
        if compute_dtype is not None:
            x, w = x.astype(compute_dtype), w.astype(compute_dtype)
        k, d = int(self.kernel_size), int(self.dilation)
        if self.convolution_mode == "same":
            pad = "SAME"
        elif self.convolution_mode == "causal":
            eff_k = (k - 1) * d + 1
            pad = [(eff_k - 1, 0)]
        else:
            pad = [(self.padding, self.padding)]
        z = lax.conv_general_dilated(
            x, w, window_strides=(self.stride,), padding=pad,
            rhs_dilation=(d,), dimension_numbers=("NTC", "TIO", "NTC"))
        if self.has_bias:
            z = z + params["b"].astype(z.dtype)
        y = get_activation(self.activation or "identity")(z)
        return apply_dropout(y, self.dropout, training, rng), state


@register_layer
@dataclasses.dataclass
class SubsamplingLayer(BaseLayerConf):
    """Pooling (``org.deeplearning4j.nn.conf.layers.SubsamplingLayer``).
    ``pooling_type``: 'max' | 'avg' | 'sum' | 'pnorm'."""

    kernel_size: Sequence[int] = (2, 2)
    stride: Sequence[int] = (2, 2)
    padding: Sequence[int] = (0, 0)
    dilation: Sequence[int] = (1, 1)
    convolution_mode: str = "truncate"
    pooling_type: str = "max"
    pnorm: int = 2

    WANTED_KINDS = ("cnn",)

    def infer_shapes(self, input_shape):
        h, w, c = input_shape
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        dh, dw = _pair(self.dilation)
        oh = _conv_out(h, kh, sh, ph, dh, self.convolution_mode)
        ow = _conv_out(w, kw, sw, pw, dw, self.convolution_mode)
        return (oh, ow, c)

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        dh, dw = _pair(self.dilation)
        window = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        dilation = (1, dh, dw, 1)
        if self.convolution_mode == "same":
            pad = "SAME"
        else:
            ph, pw = _pair(self.padding)
            pad = [(0, 0), (ph, ph), (pw, pw), (0, 0)]
        return pool2d(x, self.pooling_type, window, strides, pad, dilation,
                      self.pnorm), state


def pool2d(x, pooling_type, window, strides, pad, dilation=(1, 1, 1, 1),
           pnorm=2):
    """Shared reduce_window pooling (used by Subsampling and graph vertices).
    Average pooling divides by the ACTUAL window size at edges (DL4J
    behavior with padding excluded from the count)."""
    pt = str(pooling_type).lower()
    if pt == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides, pad,
                                 window_dilation=dilation)
    if pt in ("avg", "sum"):
        s = lax.reduce_window(x, 0.0, lax.add, window, strides, pad,
                              window_dilation=dilation)
        if pt == "sum":
            return s
        ones = jnp.ones(x.shape, x.dtype)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, pad,
                                   window_dilation=dilation)
        return s / counts
    if pt == "pnorm":
        p = float(pnorm)
        s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides,
                              pad, window_dilation=dilation)
        return s ** (1.0 / p)
    raise ValueError(f"Unknown pooling type {pooling_type!r}")


@register_layer
@dataclasses.dataclass
class BatchNormalization(BaseLayerConf):
    """Batch norm over the channel axis
    (``org.deeplearning4j.nn.conf.layers.BatchNormalization`` +
    ``CudnnBatchNormalizationHelper`` — on TPU the whole thing is a couple
    of fused XLA reductions; no helper).

    Running stats live in the layer STATE tree and are updated as a side
    output of the jitted step — the functional equivalent of DL4J mutating
    its global mean/var params with ``decay``.
    """

    n_out: Optional[int] = None  # channel count (inferred)
    decay: float = 0.9
    eps: float = 1e-5
    lock_gamma_beta: bool = False
    use_global_stats: bool = False  # DL4J useMinibatch=false analogue

    WANTED_KINDS = ("ff", "cnn", "rnn")

    def infer_shapes(self, input_shape):
        self.n_out = int(input_shape[-1])
        return input_shape

    def has_params(self):
        return not self.lock_gamma_beta

    def init(self, key, dtype=jnp.float32):
        c = self.n_out
        params = {} if self.lock_gamma_beta else {
            "gamma": jnp.ones((c,), dtype), "beta": jnp.zeros((c,), dtype)}
        state = {"mean": jnp.zeros((c,), jnp.float32),
                 "var": jnp.ones((c,), jnp.float32)}
        return params, state

    def regularized_param_names(self):
        return ()

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        axes = tuple(range(x.ndim - 1))
        # Statistics accumulate at >=f32 even when activations are bf16
        # (the convert fuses into the reduction); f64 inputs keep f64 so
        # gradient checks stay full-precision.
        stat_dtype = jnp.promote_types(x.dtype, jnp.float32)
        if training and not self.use_global_stats:
            xf = x.astype(stat_dtype)
            mean = jnp.mean(xf, axis=axes)
            # E[x^2]-E[x]^2: sibling reductions fuse into ONE pass over x.
            var = jnp.maximum(
                jnp.mean(jnp.square(xf), axis=axes) - jnp.square(mean), 0.0)
            d = self.decay
            new_state = {
                "mean": (d * state["mean"] + (1 - d) * mean).astype(jnp.float32),
                "var": (d * state["var"] + (1 - d) * var).astype(jnp.float32)}
        else:
            mean = state["mean"].astype(stat_dtype)
            var = state["var"].astype(stat_dtype)
            new_state = state
        # Fold (x-mean)*inv*gamma+beta into one FMA per element: scale and
        # offset are [C]-sized f32 vectors, the big tensor is touched once
        # in its own (bf16) dtype — the cuDNN-style fused BN on TPU terms.
        inv = lax.rsqrt(var + self.eps)
        if not self.lock_gamma_beta:
            scale = params["gamma"].astype(stat_dtype) * inv
            offset = params["beta"].astype(stat_dtype) - mean * scale
        else:
            scale = inv
            offset = -mean * inv
        y = x * scale.astype(x.dtype) + offset.astype(x.dtype)
        y = get_activation(self.activation or "identity")(y)
        return y, new_state


@register_layer
@dataclasses.dataclass
class GlobalPoolingLayer(BaseLayerConf):
    """Pool away all spatial/time dims (``GlobalPoolingLayer``): cnn
    [b,h,w,c] -> [b,c]; rnn [b,t,f] -> [b,f] honoring the feature mask
    exactly as DL4J's masked global pooling does."""

    pooling_type: str = "max"
    pnorm: int = 2
    collapse_dimensions: bool = True

    WANTED_KINDS = ("cnn", "rnn")
    USES_MASK = True

    @property
    def OUTPUT_KIND(self):
        # collapse_dimensions=False keeps size-1 spatial/time dims and the
        # input kind, as DL4J does.
        return "ff" if self.collapse_dimensions else None

    def infer_shapes(self, input_shape):
        if self.collapse_dimensions:
            return (input_shape[-1],)
        return tuple(1 for _ in input_shape[:-1]) + (input_shape[-1],)

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None, mask=None):
        axes = tuple(range(1, x.ndim - 1))
        keep = not self.collapse_dimensions
        pt = self.pooling_type.lower()
        if mask is not None and x.ndim == 3:
            m = (mask[..., 0] if mask.ndim == 3 else mask)[..., None]
            m = m.astype(x.dtype)
            n_valid = jnp.sum(m, axis=1, keepdims=keep)
            if pt == "max":
                # Fully-masked rows pool to 0, not -inf (avoids NaN grads).
                lo = jnp.finfo(x.dtype).min
                y = jnp.max(jnp.where(m > 0, x, lo), axis=1, keepdims=keep)
                y = jnp.where(n_valid > 0, y, 0.0)
                return y, state
            x = x * m
            if pt == "avg":
                return (jnp.sum(x, axis=1, keepdims=keep)
                        / jnp.maximum(n_valid, 1.0)), state
        if pt == "max":
            return jnp.max(x, axis=axes, keepdims=keep), state
        if pt == "avg":
            return jnp.mean(x, axis=axes, keepdims=keep), state
        if pt == "sum":
            return jnp.sum(x, axis=axes, keepdims=keep), state
        if pt == "pnorm":
            p = float(self.pnorm)
            return (jnp.sum(jnp.abs(x) ** p, axis=axes, keepdims=keep)
                    ** (1.0 / p)), state
        raise ValueError(f"Unknown pooling type {self.pooling_type!r}")


@register_layer
@dataclasses.dataclass
class ZeroPaddingLayer(BaseLayerConf):
    """Spatial zero padding (``ZeroPaddingLayer``); padding is
    (top, bottom, left, right) or a (h, w) pair."""

    padding: Sequence[int] = (1, 1)

    WANTED_KINDS = ("cnn",)

    def _tblr(self):
        return _tblr(self.padding)

    def infer_shapes(self, input_shape):
        h, w, c = input_shape
        t, b, l, r = self._tblr()
        return (h + t + b, w + l + r, c)

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        t, b, l, r = self._tblr()
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state


@register_layer
@dataclasses.dataclass
class Cropping2D(BaseLayerConf):
    """Spatial crop (``Cropping2D``): (top, bottom, left, right)."""

    cropping: Sequence[int] = (0, 0, 0, 0)

    WANTED_KINDS = ("cnn",)

    def _tblr(self):
        return _tblr(self.cropping)

    def infer_shapes(self, input_shape):
        h, w, c = input_shape
        t, b, l, r = self._tblr()
        return (h - t - b, w - l - r, c)

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        t, b, l, r = self._tblr()
        return x[:, t:x.shape[1] - b, l:x.shape[2] - r, :], state


@register_layer
@dataclasses.dataclass
class Upsampling2D(BaseLayerConf):
    """Nearest-neighbor upsample (``Upsampling2D``)."""

    size: Sequence[int] = (2, 2)

    WANTED_KINDS = ("cnn",)

    def infer_shapes(self, input_shape):
        h, w, c = input_shape
        sh, sw = _pair(self.size)
        return (h * sh, w * sw, c)

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        sh, sw = _pair(self.size)
        y = jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2)
        return y, state


@register_layer
@dataclasses.dataclass
class SpaceToDepthLayer(BaseLayerConf):
    """Rearrange spatial blocks into channels (``SpaceToDepthLayer``)."""

    block_size: int = 2

    WANTED_KINDS = ("cnn",)

    def infer_shapes(self, input_shape):
        h, w, c = input_shape
        b = self.block_size
        return (h // b, w // b, c * b * b)

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        n, h, w, c = x.shape
        b = self.block_size
        y = x.reshape(n, h // b, b, w // b, b, c)
        y = y.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // b, w // b,
                                                  c * b * b)
        return y, state


@register_layer
@dataclasses.dataclass
class LocalResponseNormalization(BaseLayerConf):
    """AlexNet-era LRN (``LocalResponseNormalization``); DL4J defaults
    k=2, n=5, alpha=1e-4, beta=0.75."""

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    WANTED_KINDS = ("cnn",)

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        half = self.n // 2
        sq = jnp.square(x)
        # Sum over a window of `n` adjacent channels via padded reduce.
        padded = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, half)))
        window = sum(padded[..., i:i + x.shape[-1]]
                     for i in range(2 * half + 1))
        return x / (self.k + self.alpha * window) ** self.beta, state


@register_layer
@dataclasses.dataclass
class CnnLossLayer(BaseOutputLayerConf):
    """Per-pixel loss over [b,h,w,c] (``CnnLossLayer``); the network's
    output plumbing calls ``per_example_score`` below."""

    WANTED_KINDS = ("cnn",)

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None):
        x = self.promote_head(x)
        return get_activation(self.activation or "identity")(x), state

    def pre_output(self, params, x, compute_dtype=None):
        return x

    def per_example_score(self, labels, z, mask=None, head_input=None,
                          rng=None, params=None):
        # Fold [b,h,w,c] to the sequence shape [b,h*w,c] and reuse the base
        # per-timestep masked scoring (one fused-loss dispatch to maintain).
        b, c = z.shape[0], z.shape[-1]
        z2 = z.reshape(b, -1, c)
        lab2 = labels.reshape(b, -1, labels.shape[-1])
        m2 = None if mask is None else mask.reshape(b, -1)
        return super().per_example_score(lab2, z2, m2)
