"""Recurrent layers: LSTM / GravesLSTM / GRU / SimpleRnn + wrappers.

Parity targets (upstream `deeplearning4j-nn`):
  ``org.deeplearning4j.nn.conf.layers.{LSTM,GravesLSTM,SimpleRnn,
  RnnOutputLayer,LastTimeStep}`` and ``...conf.layers.recurrent.Bidirectional``;
  runtime twins in ``org.deeplearning4j.nn.layers.recurrent.**`` (plus the
  cuDNN ``CudnnLSTMHelper`` this framework replaces with an XLA lowering).

TPU-first recurrence design (this is NOT how DL4J computes it):
* The input projection for ALL timesteps is hoisted out of the recurrence
  into one [b·t, n_in] x [n_in, 4h] matmul — a single large MXU op.
* Only the [b, h] x [h, 4h] recurrent matmul runs inside ``lax.scan`` —
  XLA compiles the scan to one fused while-loop on device (no per-timestep
  dispatch, unlike DL4J's per-step INDArray ops).
* Masked timesteps HOLD the carried state and zero the emitted activation
  (DL4J masking semantics), implemented with ``jnp.where`` inside the scan
  so the whole thing stays trace-able with static shapes.

Sequence layout is [batch, time, features]; the scan runs time-major
internally (transpose at the boundary — free inside XLA fusion).

State/carry convention: the recurrent carry (keys ``rnn_h``/``rnn_c``) is
stored in the layer's state tree ONLY when the model is carrying state
across calls (tBPTT chunks, ``rnn_time_step``).  The carry is batch-sized,
so models strip it between independent batches (``strip_rnn_carry``).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.base import BaseLayerConf, register_layer
from deeplearning4j_tpu.nn.conf.layers_core import (
    OutputLayer, apply_dropout)
from deeplearning4j_tpu.nn.weights_init import init_weights


def strip_rnn_carry(state_tree):
    """Drop batch-sized recurrent carries (keys 'rnn_*') from a state tree
    — called between independent batches so no state leaks across them."""
    def strip(node):
        if isinstance(node, dict):
            return {k: strip(v) for k, v in node.items()
                    if not k.startswith("rnn_")}
        return node
    return strip(state_tree)


class BaseRecurrentConf(BaseLayerConf):
    """Shared recurrent plumbing; subclasses define cell math."""

    IS_RNN = True
    USES_MASK = True
    WANTED_KINDS = ("rnn",)
    OUTPUT_KIND = "rnn"

    def infer_shapes(self, input_shape):
        t, f = input_shape
        if self.n_in is None:
            self.n_in = int(f)
        return (t, self.n_out)

    def has_params(self):
        return True

    def carry_init(self, batch: int, dtype):
        """Zero carry for a fresh sequence; dict of 'rnn_*' arrays."""
        raise NotImplementedError

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None, mask=None):
        """x: [b, t, f] -> [b, t, h].  Initial carry is taken from `state`
        when present (tBPTT / rnnTimeStep continuation), else zeros; the
        final carry is returned in the new state."""
        b = x.shape[0]
        dtype = params[next(iter(params))].dtype
        carry = {k: state[k] for k in self.carry_init(1, dtype)
                 if k in state}
        if not carry or next(iter(carry.values())).shape[0] != b:
            carry = self.carry_init(b, dtype)
        y, new_carry = self.apply_seq(params, x, carry, mask, compute_dtype)
        y = apply_dropout(y, self.dropout, training, rng)
        new_state = dict(state)
        new_state.update(new_carry)
        return y, new_state

    def apply_seq(self, params, x, carry, mask, compute_dtype):
        raise NotImplementedError

    def regularized_param_names(self):
        return ("W", "R")


def _time_major(x):
    return jnp.swapaxes(x, 0, 1)


@register_layer
@dataclasses.dataclass
class LSTM(BaseRecurrentConf):
    """LSTM without peepholes (``org.deeplearning4j.nn.conf.layers.LSTM``;
    native kernel ``libnd4j .../declarable/generic/nn/recurrent/lstmLayer.cpp``).

    Gate layout in the fused [.., 4h] projection: input, forget, cell(g),
    output.  ``forget_gate_bias_init`` defaults to 1.0 as upstream.
    """

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    # DL4J LSTM default activation is tanh (not the global default)
    activation: Optional[str] = "tanh"
    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"

    def init(self, key, dtype=jnp.float32):
        kx, kr = jax.random.split(key)
        h = self.n_out
        w = init_weights(kx, (self.n_in, 4 * h), self.n_in, 4 * h,
                         self.weight_init, dtype, self.weight_distribution)
        r = init_weights(kr, (h, 4 * h), h, 4 * h,
                         self.weight_init, dtype, self.weight_distribution)
        b = jnp.zeros((4 * h,), dtype)
        # forget-gate slice [h:2h] gets the bias init (DL4J
        # LSTMParamInitializer.setForgetGateBiasInit)
        b = b.at[h:2 * h].set(self.forget_gate_bias_init)
        return {"W": w, "R": r, "b": b}, {}

    def carry_init(self, batch, dtype):
        return {"rnn_h": jnp.zeros((batch, self.n_out), dtype),
                "rnn_c": jnp.zeros((batch, self.n_out), dtype)}

    def _gates(self, z, c_prev, params, sigma, act):
        h = self.n_out
        i = sigma(z[:, :h])
        f = sigma(z[:, h:2 * h])
        g = act(z[:, 2 * h:3 * h])
        o_pre = z[:, 3 * h:]
        return i, f, g, o_pre

    def apply_seq(self, params, x, carry, mask, compute_dtype):
        dtype = params["W"].dtype
        w, r, bias = params["W"], params["R"], params["b"]
        if compute_dtype is not None:
            x, w, r = (a.astype(compute_dtype) for a in (x, w, r))
        sigma = get_activation(self.gate_activation)
        act = get_activation(self.activation or "tanh")
        # ONE big MXU matmul for every timestep's input projection:
        xz = (x @ w).astype(dtype) + bias          # [b, t, 4h]
        xz_t = _time_major(xz)                     # [t, b, 4h]
        mask_t = None if mask is None else _time_major(mask)
        h0, c0 = carry["rnn_h"], carry["rnn_c"]

        def step(hc, inp):
            h_prev, c_prev = hc
            z_x, m = inp
            z = z_x + (h_prev.astype(w.dtype) @ r).astype(dtype)
            i, f, g, o_pre = self._gates(z, c_prev, params, sigma, act)
            c_new = f * c_prev + i * g
            o = sigma(self._peep_o(o_pre, c_new, params))
            h_new = o * act(c_new)
            if m is not None:
                mm = m[:, None].astype(h_new.dtype)
                h_new = h_new * mm + h_prev * (1 - mm)
                c_new = c_new * mm + c_prev * (1 - mm)
                y = h_new * mm
            else:
                y = h_new
            return (h_new, c_new), y

        if mask_t is None:
            (hT, cT), ys = lax.scan(lambda hc, zx: step(hc, (zx, None)),
                                    (h0, c0), xz_t)
        else:
            (hT, cT), ys = lax.scan(step, (h0, c0), (xz_t, mask_t))
        return _time_major(ys), {"rnn_h": hT, "rnn_c": cT}

    def _peep_o(self, o_pre, c_new, params):
        return o_pre


@register_layer
@dataclasses.dataclass
class GravesLSTM(LSTM):
    """Peephole LSTM per Graves (2013) — upstream ``GravesLSTM`` (the
    char-RNN baseline layer).  Peepholes: i,f see c_{t-1}; o sees c_t."""

    def init(self, key, dtype=jnp.float32):
        params, state = super().init(key, dtype)
        h = self.n_out
        params["P"] = jnp.zeros((3, h), dtype)  # p_i, p_f, p_o
        return params, state

    def _gates(self, z, c_prev, params, sigma, act):
        h = self.n_out
        p = params["P"].astype(z.dtype)
        i = sigma(z[:, :h] + p[0] * c_prev)
        f = sigma(z[:, h:2 * h] + p[1] * c_prev)
        g = act(z[:, 2 * h:3 * h])
        o_pre = z[:, 3 * h:]
        return i, f, g, o_pre

    def _peep_o(self, o_pre, c_new, params):
        return o_pre + params["P"].astype(o_pre.dtype)[2] * c_new


@register_layer
@dataclasses.dataclass
class GRU(BaseRecurrentConf):
    """GRU (libnd4j ``gruCell``; upstream exposes it via SameDiff ops).
    Gate layout [.., 3h]: reset, update, candidate."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    activation: Optional[str] = "tanh"
    gate_activation: str = "sigmoid"

    def init(self, key, dtype=jnp.float32):
        kx, kr = jax.random.split(key)
        h = self.n_out
        w = init_weights(kx, (self.n_in, 3 * h), self.n_in, 3 * h,
                         self.weight_init, dtype, self.weight_distribution)
        r = init_weights(kr, (h, 3 * h), h, 3 * h,
                         self.weight_init, dtype, self.weight_distribution)
        return {"W": w, "R": r, "b": jnp.zeros((3 * h,), dtype)}, {}

    def carry_init(self, batch, dtype):
        return {"rnn_h": jnp.zeros((batch, self.n_out), dtype)}

    def apply_seq(self, params, x, carry, mask, compute_dtype):
        dtype = params["W"].dtype
        w, r, bias = params["W"], params["R"], params["b"]
        if compute_dtype is not None:
            x, w, r = (a.astype(compute_dtype) for a in (x, w, r))
        sigma = get_activation(self.gate_activation)
        act = get_activation(self.activation or "tanh")
        h = self.n_out
        xz_t = _time_major((x @ w).astype(dtype) + bias)
        mask_t = None if mask is None else _time_major(mask)

        def step(h_prev, inp):
            z_x, m = inp
            hz = (h_prev.astype(w.dtype) @ r).astype(dtype)
            rg = sigma(z_x[:, :h] + hz[:, :h])
            ug = sigma(z_x[:, h:2 * h] + hz[:, h:2 * h])
            cand = act(z_x[:, 2 * h:] + rg * hz[:, 2 * h:])
            h_new = ug * h_prev + (1 - ug) * cand
            if m is not None:
                mm = m[:, None].astype(h_new.dtype)
                h_new = h_new * mm + h_prev * (1 - mm)
                y = h_new * mm
            else:
                y = h_new
            return h_new, y

        if mask_t is None:
            hT, ys = lax.scan(lambda hp, zx: step(hp, (zx, None)),
                              carry["rnn_h"], xz_t)
        else:
            hT, ys = lax.scan(step, carry["rnn_h"], (xz_t, mask_t))
        return _time_major(ys), {"rnn_h": hT}


@register_layer
@dataclasses.dataclass
class SimpleRnn(BaseRecurrentConf):
    """Elman RNN (``org.deeplearning4j.nn.conf.layers.recurrent.SimpleRnn``):
    h_t = act(x_t W + h_{t-1} R + b)."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    activation: Optional[str] = "tanh"

    def init(self, key, dtype=jnp.float32):
        kx, kr = jax.random.split(key)
        h = self.n_out
        w = init_weights(kx, (self.n_in, h), self.n_in, h,
                         self.weight_init, dtype, self.weight_distribution)
        r = init_weights(kr, (h, h), h, h,
                         self.weight_init, dtype, self.weight_distribution)
        return {"W": w, "R": r, "b": jnp.zeros((h,), dtype)}, {}

    def carry_init(self, batch, dtype):
        return {"rnn_h": jnp.zeros((batch, self.n_out), dtype)}

    def apply_seq(self, params, x, carry, mask, compute_dtype):
        dtype = params["W"].dtype
        w, r, bias = params["W"], params["R"], params["b"]
        if compute_dtype is not None:
            x, w, r = (a.astype(compute_dtype) for a in (x, w, r))
        act = get_activation(self.activation or "tanh")
        xz_t = _time_major((x @ w).astype(dtype) + bias)
        mask_t = None if mask is None else _time_major(mask)

        def step(h_prev, inp):
            z_x, m = inp
            h_new = act(z_x + (h_prev.astype(w.dtype) @ r).astype(dtype))
            if m is not None:
                mm = m[:, None].astype(h_new.dtype)
                h_new = h_new * mm + h_prev * (1 - mm)
                y = h_new * mm
            else:
                y = h_new
            return h_new, y

        if mask_t is None:
            hT, ys = lax.scan(lambda hp, zx: step(hp, (zx, None)),
                              carry["rnn_h"], xz_t)
        else:
            hT, ys = lax.scan(step, carry["rnn_h"], (xz_t, mask_t))
        return _time_major(ys), {"rnn_h": hT}


def reverse_sequence(x, mask):
    """Mask-aware time reversal: each example's VALID prefix is reversed
    in place, padding stays at the end (DL4J ``ReverseTimeSeriesVertex``
    with a mask; plain flip when unmasked)."""
    if mask is None:
        return jnp.flip(x, axis=1)
    t = x.shape[1]
    lengths = jnp.sum(mask.astype(jnp.int32), axis=1)          # [b]
    ar = jnp.arange(t)[None, :]                                # [1, t]
    idx = jnp.where(ar < lengths[:, None], lengths[:, None] - 1 - ar, ar)
    return jnp.take_along_axis(
        x, idx[..., None] if x.ndim == 3 else idx, axis=1)


@register_layer
@dataclasses.dataclass
class Bidirectional(BaseLayerConf):
    """Bidirectional wrapper (``...conf.layers.recurrent.Bidirectional``):
    runs the wrapped recurrent layer forward and (mask-aware) reversed,
    combining with mode CONCAT | ADD | MUL | AVERAGE."""

    layer: Optional[BaseRecurrentConf] = None
    mode: str = "concat"

    IS_RNN = True
    USES_MASK = True
    WANTED_KINDS = ("rnn",)
    OUTPUT_KIND = "rnn"

    def __post_init__(self):
        if isinstance(self.layer, dict):
            from deeplearning4j_tpu.nn.conf.base import layer_from_dict
            self.layer = layer_from_dict(self.layer)

    def to_dict(self):
        d = super().to_dict()
        d["layer"] = self.layer.to_dict()
        return d

    def resolve_defaults(self, global_conf):
        super().resolve_defaults(global_conf)
        self.layer.resolve_defaults(global_conf)

    def infer_shapes(self, input_shape):
        t, h = self.layer.infer_shapes(input_shape)
        return (t, 2 * h if self.mode == "concat" else h)

    def has_params(self):
        return True

    def init(self, key, dtype=jnp.float32):
        kf, kb = jax.random.split(key)
        pf, _ = self.layer.init(kf, dtype)
        pb, _ = self.layer.init(kb, dtype)
        return {"fwd": pf, "bwd": pb}, {}

    def regularized_param_names(self):
        # Path-addressed names into the nested {fwd, bwd} param dicts.
        inner = self.layer.regularized_param_names()
        return tuple(f"{d}/{n}" for d in ("fwd", "bwd") for n in inner)

    def carry_init(self, batch, dtype):
        return {}  # bidirectional layers cannot stream (need full sequence)

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None, mask=None):
        zero = self.layer.carry_init(x.shape[0], params["fwd"]["W"].dtype)
        yf, _ = self.layer.apply_seq(params["fwd"], x, zero, mask,
                                     compute_dtype)
        xr = reverse_sequence(x, mask)
        yb, _ = self.layer.apply_seq(params["bwd"], xr, zero, mask,
                                     compute_dtype)
        yb = reverse_sequence(yb, mask)
        mode = self.mode.lower()
        if mode == "concat":
            y = jnp.concatenate([yf, yb], axis=-1)
        elif mode == "add":
            y = yf + yb
        elif mode == "mul":
            y = yf * yb
        elif mode == "average":
            y = (yf + yb) * 0.5
        else:
            raise ValueError(f"Unknown Bidirectional mode {self.mode!r}")
        return apply_dropout(y, self.dropout, training, rng), state


@register_layer
@dataclasses.dataclass
class LastTimeStep(BaseLayerConf):
    """Wrapper reducing [b, t, h] to the LAST VALID timestep's [b, h]
    (``...conf.layers.recurrent.LastTimeStep``)."""

    layer: Optional[BaseLayerConf] = None

    USES_MASK = True
    WANTED_KINDS = ("rnn",)
    OUTPUT_KIND = "ff"

    @property
    def IS_RNN(self):
        # The wrapped recurrent layer writes a carry into this layer's
        # state dict, so models must strip it between batches too.
        return self.layer is not None and getattr(self.layer, "IS_RNN", False)

    def __post_init__(self):
        if isinstance(self.layer, dict):
            from deeplearning4j_tpu.nn.conf.base import layer_from_dict
            self.layer = layer_from_dict(self.layer)

    def to_dict(self):
        d = super().to_dict()
        if self.layer is not None:
            d["layer"] = self.layer.to_dict()
        return d

    def resolve_defaults(self, global_conf):
        super().resolve_defaults(global_conf)
        if self.layer is not None:
            self.layer.resolve_defaults(global_conf)

    def infer_shapes(self, input_shape):
        if self.layer is not None:
            t, h = self.layer.infer_shapes(input_shape)
            return (h,)
        return (input_shape[-1],)

    def has_params(self):
        return self.layer is not None and self.layer.has_params()

    def init(self, key, dtype=jnp.float32):
        return self.layer.init(key, dtype) if self.layer is not None else ({}, {})

    def regularized_param_names(self):
        return self.layer.regularized_param_names() if self.layer is not None \
            else ()

    def apply(self, params, state, x, *, training: bool, rng=None,
              compute_dtype=None, mask=None):
        if self.layer is not None:
            kwargs = {"mask": mask} if getattr(self.layer, "USES_MASK",
                                               False) else {}
            x, state = self.layer.apply(params, state, x, training=training,
                                        rng=rng, compute_dtype=compute_dtype,
                                        **kwargs)
        return last_time_step(x, mask), state


def last_time_step(x, mask):
    """[b, t, h] -> [b, h] at each example's last valid timestep."""
    if mask is None:
        return x[:, -1]
    lengths = jnp.sum(mask.astype(jnp.int32), axis=1)
    idx = jnp.maximum(lengths - 1, 0)
    return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0]


@register_layer
@dataclasses.dataclass
class RnnOutputLayer(OutputLayer):
    """Per-timestep output layer over [b, t, f]
    (``org.deeplearning4j.nn.conf.layers.RnnOutputLayer``): the dense
    projection broadcasts over time ([b, t, in] @ [in, out]); the base
    scorer already handles 3-D pre-activations per timestep with masks."""

    WANTED_KINDS = ("rnn",)
    OUTPUT_KIND = "rnn"
