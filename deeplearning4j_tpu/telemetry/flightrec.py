"""Per-host flight recorder + crash-forensics postmortem bundles.

When a replica dies, the only forensic record used to be whatever
scrape happened to run last — counters say HOW MUCH, never WHAT
HAPPENED LAST.  This module is the black box: a lock-cheap bounded
ring of structured events fed by the hot decision sites the stack
already has (admission/dispatch/placement, allocator spill/fetch,
watchdog transitions, migrations, scale actions), plus the bundle
writer that freezes the ring — with the tracer's OPEN spans, a final
metric snapshot and the SLO/alert state — into one atomic postmortem
document a later process can render as a timeline
(``scripts/postmortem.py``).

* **ring** — :meth:`FlightRecorder.record` appends one dict to a
  bounded ``collections.deque`` (appends are atomic under the GIL; no
  lock on the hot path) stamped with a process-monotonic ``seq``, a
  wall clock and a monotonic clock.  Overflow drops the OLDEST events
  — the last N decisions before a crash are exactly what a postmortem
  needs;

* **bundles** — :meth:`request_dump` (armed by :meth:`install_dump`)
  writes ``<shared_dir>/_postmortem/<host>-<pid>-<n>.json`` through
  ``resilience.atomic_publish_json`` — a reader sees a complete
  bundle or none.  Dump triggers in-tree: the decode server's
  watchdog recovery, ``ServingFleet.kill`` (chaos), cooperative
  preemption, and any explicit call;

* **black box persistence** — a SIGKILL runs no handlers, so
  ``install_dump(..., persist_interval_s=...)`` starts a daemon that
  periodically publishes the CURRENT ring + open spans to
  ``_flightrec/<host>.json`` (same atomic publish).  After the kill,
  :func:`salvage_bundles` promotes each black-box file whose
  (host, pid) never produced a real bundle into a
  ``reason="salvaged: ..."`` postmortem — the victim's last persisted
  events and still-open spans survive their process.

The recorder's own traffic is observable
(``flight_events_total{kind=}``, ``postmortem_bundles_total``), and
``record()`` stays cheap enough for per-request sites: one dict, one
deque append, one counter inc.
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

log = logging.getLogger("deeplearning4j_tpu")

#: bundle subdirectory under the shared dir (beside ``_telemetry``
#: and ``_rendezvous``, never inside them)
BUNDLE_DIRNAME = "_postmortem"
#: black-box ring snapshots (periodic persistence for SIGKILL cases)
BLACKBOX_DIRNAME = "_flightrec"


def _default_host_id() -> str:
    return f"{os.uname().nodename}-{os.getpid()}"


class FlightRecorder:
    """Bounded ring of structured events + the postmortem bundle
    writer.

    >>> fr = FlightRecorder(capacity=4096)
    >>> fr.record("dispatch", replica=1, reason="affinity")
    >>> fr.install_dump(shared_dir, host="host000")
    >>> fr.request_dump("watchdog: stuck tick")   # -> bundle path

    ``record`` is safe from any thread without taking the recorder's
    lock (deque appends are atomic); only the dump CONFIGURATION is
    lock-guarded.  ``enabled=False`` turns every method into a no-op
    (capacity stays allocated)."""

    def __init__(self, capacity: int = 4096, enabled: bool = True):
        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.enabled = bool(enabled)
        self._events: deque = deque(maxlen=self.capacity)
        self._seq = itertools.count()
        self._dump_seq = itertools.count()
        self._lock = threading.Lock()
        self._cfg: Optional[dict] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ctr = None             # lazy: telemetry imports this
        self._bundles = None         # module, not the reverse

    # -- the ring ------------------------------------------------------
    def record(self, kind: str, **fields) -> None:
        """Append one event.  ``fields`` must be JSON-serializable
        (ints/floats/strings — the hot sites pass ids and labels, not
        arrays)."""
        if not self.enabled:
            return
        ev = {"seq": next(self._seq), "wall": time.time(),
              "ts": time.monotonic(), "kind": str(kind)}
        ev.update(fields)
        self._events.append(ev)
        ctr = self._ctr
        if ctr is None:
            try:
                from deeplearning4j_tpu import telemetry
                ctr = self._ctr = telemetry.counter(
                    "flight_events_total",
                    "structured events recorded into the per-host "
                    "flight-recorder ring, by kind",
                    labelnames=("kind",))
            except Exception:     # partially-imported package: the
                return            # ring keeps the event regardless
        ctr.labels(kind=str(kind)).inc()

    def events(self, last: Optional[int] = None) -> List[Dict]:
        """Snapshot of the ring, oldest first (``last`` bounds the
        tail).  Deque iteration can raise under concurrent append —
        retry, then index-walk (the tracer's discipline)."""
        out = None
        for _ in range(8):
            try:
                out = list(self._events)
                break
            except RuntimeError:
                continue
        if out is None:
            out = []
            for i in range(len(self._events)):
                try:
                    out.append(self._events[i])
                except IndexError:
                    break
        if last is not None and len(out) > last:
            out = out[-int(last):]
        return out

    def clear(self) -> None:
        self._events.clear()

    # -- bundles -------------------------------------------------------
    def install_dump(self, directory, host: Optional[str] = None,
                     registry=None, tracer=None, alerts=None,
                     persist_interval_s: Optional[float] = None
                     ) -> "FlightRecorder":
        """Arm bundle writing: ``directory`` is the shared dir (the
        checkpoint/beacon dir is the natural choice), ``registry`` /
        ``tracer`` default to the process-wide ones at dump time,
        ``alerts`` is an optional :class:`~.slo.AlertEngine` whose
        state rides in every bundle.  ``persist_interval_s`` starts
        the black-box daemon (periodic ring snapshots a SIGKILL
        cannot suppress)."""
        host = str(host) if host is not None else _default_host_id()
        if os.sep in host:
            raise ValueError(f"host {host!r} must be a plain name")
        interval = (float(persist_interval_s)
                    if persist_interval_s else None)
        if interval is not None and interval <= 0:
            raise ValueError("persist_interval_s must be > 0")
        with self._lock:
            self._cfg = {"directory": str(directory), "host": host,
                         "registry": registry, "tracer": tracer,
                         "alerts": alerts}
            alive = (self._thread is not None
                     and self._thread.is_alive())
            if interval is not None and alive:
                # a NEW cadence replaces the running daemon — the
                # old interval silently sticking (a 50ms chaos-drill
                # cadence surviving into production) would hammer
                # the shared dir forever
                self._stop.set()
                self._thread = None
                alive = False
        if interval is not None and not alive:
            # a FRESH stop event re-arms after a close()/uninstall
            # (the old set() event would end the new daemon's first
            # wait and silently kill the black box); the thread
            # closes over ITS OWN event, so a concurrent re-arm can
            # never steal a running loop's stop signal
            stop = threading.Event()
            thread = threading.Thread(
                target=self._persist_loop, args=(interval, stop),
                name="dl4j-tpu-flightrec", daemon=True)
            with self._lock:
                self._stop = stop
                self._thread = thread
            thread.start()
        return self

    def uninstall_dump(self) -> None:
        """Disarm bundle writing AND stop the black-box daemon —
        scoped chaos drills and tests must not leave the
        process-default recorder pointed at a dead directory or a
        stray daemon spinning against it."""
        with self._lock:
            self._cfg = None
            self._stop.set()
            self._thread = None

    def _bundle_doc(self, cfg: dict, reason: str) -> dict:
        registry = cfg.get("registry")
        tracer = cfg.get("tracer")
        alerts = cfg.get("alerts")
        if registry is None or tracer is None:
            from deeplearning4j_tpu import telemetry
            registry = registry or telemetry.get_registry()
            tracer = tracer or telemetry.get_tracer()
        open_spans = [{"name": sp.name, "ts": sp.ts, "tid": sp.tid,
                       "bound": sp.bound, "args": dict(sp.args)}
                      for sp in tracer.open_spans()]
        doc = {"kind": "postmortem", "reason": str(reason),
               "host": cfg["host"], "pid": os.getpid(),
               "t": time.time(), "events": self.events(),
               "open_spans": open_spans,
               "metrics": registry.snapshot()}
        try:
            doc["slo"] = alerts.state() if alerts is not None else None
        except Exception:            # a torn engine must not cost the
            doc["slo"] = None        # bundle its events
        return doc

    def request_dump(self, reason: str, error: Optional[str] = None
                     ) -> Optional[str]:
        """Write one postmortem bundle NOW; returns its path, or None
        when no dump dir is installed (the hot sites call this
        unconditionally — unconfigured processes pay a lock peek).
        Never raises: a postmortem writer that crashes its caller
        would be the worst bug in the file."""
        with self._lock:
            cfg = self._cfg
        if cfg is None or not self.enabled:
            return None
        try:
            from deeplearning4j_tpu.resilience.coordination import (
                atomic_publish_json)
            doc = self._bundle_doc(cfg, reason)
            if error is not None:
                doc["error"] = str(error)
            path = os.path.join(
                cfg["directory"], BUNDLE_DIRNAME,
                f"{cfg['host']}-{os.getpid()}-"
                f"{next(self._dump_seq)}.json")
            atomic_publish_json(path, doc)
            if self._bundles is None:
                from deeplearning4j_tpu import telemetry
                self._bundles = telemetry.counter(
                    "postmortem_bundles_total",
                    "crash-forensics bundles this process published "
                    "(watchdog trips, chaos kills, preemptions, "
                    "explicit dumps)")
            self._bundles.inc()
            log.warning("flight recorder: postmortem bundle %s (%s)",
                        path, reason)
            return path
        except Exception:
            log.exception("flight recorder: bundle write failed (%s)",
                          reason)
            return None

    # -- black box persistence ----------------------------------------
    def _persist_once(self) -> Optional[str]:
        with self._lock:
            cfg = self._cfg
        if cfg is None:
            return None
        from deeplearning4j_tpu.resilience.coordination import (
            atomic_publish_json)
        doc = self._bundle_doc(cfg, "blackbox")
        path = os.path.join(cfg["directory"], BLACKBOX_DIRNAME,
                            f"{cfg['host']}.json")
        atomic_publish_json(path, doc)
        return path

    def _persist_loop(self, interval: float,
                      stop: threading.Event) -> None:
        while not stop.wait(interval):
            try:
                self._persist_once()
            except Exception:        # a shared-dir flake must never
                log.exception(       # kill the black box for good
                    "flight recorder: black-box persist failed")

    def close(self) -> None:
        """Stop the black-box daemon (one final persist included)."""
        with self._lock:
            stop = self._stop
            thread = self._thread
            self._thread = None
        stop.set()
        if thread is not None:
            thread.join(timeout=5)
            try:
                self._persist_once()
            except Exception:
                log.exception("flight recorder: final persist failed")


def list_bundles(directory) -> List[str]:
    """Postmortem bundle paths under ``directory``, oldest first."""
    bdir = os.path.join(str(directory), BUNDLE_DIRNAME)
    try:
        names = os.listdir(bdir)
    except OSError:
        return []
    paths = [os.path.join(bdir, n) for n in names
             if n.endswith(".json")]
    return sorted(paths, key=lambda p: (os.path.getmtime(p), p))


def load_bundle(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def salvage_bundles(directory) -> List[str]:
    """Promote black-box ring snapshots whose (host, pid) never wrote
    a real bundle into ``reason="salvaged: ..."`` postmortems — the
    SIGKILL path: the victim could not dump, but its black-box daemon
    left the last persisted ring + open spans behind.  Idempotent
    (an already-salvaged (host, pid) is skipped); returns the NEW
    bundle paths."""
    directory = str(directory)
    from deeplearning4j_tpu.resilience.coordination import (
        atomic_publish_json)
    covered = set()
    for path in list_bundles(directory):
        try:
            doc = load_bundle(path)
            covered.add((doc.get("host"), doc.get("pid")))
        except (OSError, ValueError):
            continue
    bbdir = os.path.join(directory, BLACKBOX_DIRNAME)
    try:
        names = sorted(os.listdir(bbdir))
    except OSError:
        return []
    out: List[str] = []
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            doc = load_bundle(os.path.join(bbdir, name))
        except (OSError, ValueError):
            continue                 # mid-replace: next pass gets it
        key = (doc.get("host"), doc.get("pid"))
        if key in covered:
            continue
        doc["reason"] = f"salvaged: {doc.get('reason', 'blackbox')}"
        doc["salvaged"] = True
        path = os.path.join(directory, BUNDLE_DIRNAME,
                            f"{doc.get('host', 'unknown')}-"
                            f"{doc.get('pid', 0)}-salvaged.json")
        atomic_publish_json(path, doc)
        out.append(path)
    return out
