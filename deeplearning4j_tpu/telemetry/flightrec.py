"""Per-host flight recorder + crash-forensics postmortem bundles.

When a replica dies, the only forensic record used to be whatever
scrape happened to run last — counters say HOW MUCH, never WHAT
HAPPENED LAST.  This module is the black box: a lock-cheap bounded
ring of structured events fed by the hot decision sites the stack
already has (admission/dispatch/placement, allocator spill/fetch,
watchdog transitions, migrations, scale actions), plus the bundle
writer that freezes the ring — with the tracer's OPEN spans, a final
metric snapshot and the SLO/alert state — into one atomic postmortem
document a later process can render as a timeline
(``scripts/postmortem.py``).

* **ring** — :meth:`FlightRecorder.record` appends one dict to a
  bounded ``collections.deque`` (appends are atomic under the GIL; no
  lock on the hot path) stamped with a process-monotonic ``seq``, a
  wall clock and a monotonic clock.  Overflow drops the OLDEST events
  — the last N decisions before a crash are exactly what a postmortem
  needs;

* **bundles** — :meth:`request_dump` (armed by :meth:`install_dump`)
  writes ``<shared_dir>/_postmortem/<host>-<pid>-<n>.json`` through
  ``resilience.atomic_publish_json`` — a reader sees a complete
  bundle or none.  Dump triggers in-tree: the decode server's
  watchdog recovery, ``ServingFleet.kill`` (chaos), cooperative
  preemption, and any explicit call;

* **black box persistence** — a SIGKILL runs no handlers, so
  ``install_dump(..., persist_interval_s=...)`` starts a daemon that
  periodically publishes the CURRENT ring + open spans to
  ``_flightrec/<host>.json`` (same atomic publish).  After the kill,
  :func:`salvage_bundles` promotes each black-box file whose
  (host, pid) never produced a real bundle into a
  ``reason="salvaged: ..."`` postmortem — the victim's last persisted
  events and still-open spans survive their process;

* **pre-crash metric history** (ISSUE 16) — every bundle and black
  box carries ``history``: the last ``history_s`` seconds of the
  process time-series store (``telemetry.get_tsdb()`` unless an
  explicit store is armed), downsampled per series, so the
  postmortem shows each metric's TRAJECTORY into the crash, not one
  final value;

* **retention** — the shared dir must not grow without bound across
  chaos drills and real incidents: ``install_dump(max_bundles=...,
  max_bundle_age_s=...)`` caps ``_postmortem/`` and ``_flightrec/``
  by count and age (oldest evicted first, one atomic unlink each,
  counted by ``postmortem_bundles_evicted_total``), applied after
  every bundle write and black-box persist; :func:`salvage_bundles`
  accepts the same caps so salvage respects the rotation policy.

The recorder's own traffic is observable
(``flight_events_total{kind=}``, ``postmortem_bundles_total``), and
``record()`` stays cheap enough for per-request sites: one dict, one
deque append, one counter inc.
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

log = logging.getLogger("deeplearning4j_tpu")

#: bundle subdirectory under the shared dir (beside ``_telemetry``
#: and ``_rendezvous``, never inside them)
BUNDLE_DIRNAME = "_postmortem"
#: black-box ring snapshots (periodic persistence for SIGKILL cases)
BLACKBOX_DIRNAME = "_flightrec"


def _default_host_id() -> str:
    return f"{os.uname().nodename}-{os.getpid()}"


class FlightRecorder:
    """Bounded ring of structured events + the postmortem bundle
    writer.

    >>> fr = FlightRecorder(capacity=4096)
    >>> fr.record("dispatch", replica=1, reason="affinity")
    >>> fr.install_dump(shared_dir, host="host000")
    >>> fr.request_dump("watchdog: stuck tick")   # -> bundle path

    ``record`` is safe from any thread without taking the recorder's
    lock (deque appends are atomic); only the dump CONFIGURATION is
    lock-guarded.  ``enabled=False`` turns every method into a no-op
    (capacity stays allocated)."""

    def __init__(self, capacity: int = 4096, enabled: bool = True):
        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.enabled = bool(enabled)
        self._events: deque = deque(maxlen=self.capacity)
        self._seq = itertools.count()
        self._dump_seq = itertools.count()
        self._lock = threading.Lock()
        self._cfg: Optional[dict] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ctr = None             # lazy: telemetry imports this
        self._bundles = None         # module, not the reverse

    # -- the ring ------------------------------------------------------
    def record(self, kind: str, **fields) -> None:
        """Append one event.  ``fields`` must be JSON-serializable
        (ints/floats/strings — the hot sites pass ids and labels, not
        arrays)."""
        if not self.enabled:
            return
        ev = {"seq": next(self._seq), "wall": time.time(),
              "ts": time.monotonic(), "kind": str(kind)}
        ev.update(fields)
        self._events.append(ev)
        ctr = self._ctr
        if ctr is None:
            try:
                from deeplearning4j_tpu import telemetry
                ctr = self._ctr = telemetry.counter(
                    "flight_events_total",
                    "structured events recorded into the per-host "
                    "flight-recorder ring, by kind",
                    labelnames=("kind",))
            except Exception:     # partially-imported package: the
                return            # ring keeps the event regardless
        ctr.labels(kind=str(kind)).inc()

    def events(self, last: Optional[int] = None) -> List[Dict]:
        """Snapshot of the ring, oldest first (``last`` bounds the
        tail).  Deque iteration can raise under concurrent append —
        retry, then index-walk (the tracer's discipline)."""
        out = None
        for _ in range(8):
            try:
                out = list(self._events)
                break
            except RuntimeError:
                continue
        if out is None:
            out = []
            for i in range(len(self._events)):
                try:
                    out.append(self._events[i])
                except IndexError:
                    break
        if last is not None and len(out) > last:
            out = out[-int(last):]
        return out

    def clear(self) -> None:
        self._events.clear()

    # -- bundles -------------------------------------------------------
    def install_dump(self, directory, host: Optional[str] = None,
                     registry=None, tracer=None, alerts=None,
                     persist_interval_s: Optional[float] = None,
                     tsdb=None, history_s: float = 300.0,
                     max_bundles: Optional[int] = 64,
                     max_bundle_age_s: Optional[float] = None
                     ) -> "FlightRecorder":
        """Arm bundle writing: ``directory`` is the shared dir (the
        checkpoint/beacon dir is the natural choice), ``registry`` /
        ``tracer`` default to the process-wide ones at dump time,
        ``alerts`` is an optional :class:`~.slo.AlertEngine` whose
        state rides in every bundle.  ``persist_interval_s`` starts
        the black-box daemon (periodic ring snapshots a SIGKILL
        cannot suppress).  ``tsdb`` is the time-series store whose
        last ``history_s`` seconds ride in every bundle as
        pre-crash metric history (the process-wide store by
        default); ``max_bundles`` / ``max_bundle_age_s`` cap the
        bundle and black-box dirs by count and age after every
        write (``None`` disables that axis)."""
        host = str(host) if host is not None else _default_host_id()
        if os.sep in host:
            raise ValueError(f"host {host!r} must be a plain name")
        interval = (float(persist_interval_s)
                    if persist_interval_s else None)
        if interval is not None and interval <= 0:
            raise ValueError("persist_interval_s must be > 0")
        history_s = float(history_s)
        if history_s <= 0:
            raise ValueError("history_s must be > 0")
        max_bundles = None if max_bundles is None else int(max_bundles)
        if max_bundles is not None and max_bundles < 1:
            # 0 would evict the bundle a crash just wrote — the one
            # file the whole module exists to keep
            raise ValueError("max_bundles must be >= 1 (or None)")
        max_bundle_age_s = (None if max_bundle_age_s is None
                            else float(max_bundle_age_s))
        if max_bundle_age_s is not None and max_bundle_age_s <= 0:
            raise ValueError("max_bundle_age_s must be > 0 (or None)")
        with self._lock:
            self._cfg = {"directory": str(directory), "host": host,
                         "registry": registry, "tracer": tracer,
                         "alerts": alerts, "tsdb": tsdb,
                         "history_s": history_s,
                         "max_bundles": max_bundles,
                         "max_bundle_age_s": max_bundle_age_s}
            alive = (self._thread is not None
                     and self._thread.is_alive())
            if interval is not None and alive:
                # a NEW cadence replaces the running daemon — the
                # old interval silently sticking (a 50ms chaos-drill
                # cadence surviving into production) would hammer
                # the shared dir forever
                self._stop.set()
                self._thread = None
                alive = False
        if interval is not None and not alive:
            # a FRESH stop event re-arms after a close()/uninstall
            # (the old set() event would end the new daemon's first
            # wait and silently kill the black box); the thread
            # closes over ITS OWN event, so a concurrent re-arm can
            # never steal a running loop's stop signal
            stop = threading.Event()
            thread = threading.Thread(
                target=self._persist_loop, args=(interval, stop),
                name="dl4j-tpu-flightrec", daemon=True)
            with self._lock:
                self._stop = stop
                self._thread = thread
            thread.start()
        return self

    def uninstall_dump(self) -> None:
        """Disarm bundle writing AND stop the black-box daemon —
        scoped chaos drills and tests must not leave the
        process-default recorder pointed at a dead directory or a
        stray daemon spinning against it."""
        with self._lock:
            self._cfg = None
            self._stop.set()
            self._thread = None

    def _bundle_doc(self, cfg: dict, reason: str) -> dict:
        registry = cfg.get("registry")
        tracer = cfg.get("tracer")
        alerts = cfg.get("alerts")
        if registry is None or tracer is None:
            from deeplearning4j_tpu import telemetry
            registry = registry or telemetry.get_registry()
            tracer = tracer or telemetry.get_tracer()
        open_spans = [{"name": sp.name, "ts": sp.ts, "tid": sp.tid,
                       "bound": sp.bound, "args": dict(sp.args)}
                      for sp in tracer.open_spans()]
        doc = {"kind": "postmortem", "reason": str(reason),
               "host": cfg["host"], "pid": os.getpid(),
               "t": time.time(), "events": self.events(),
               "open_spans": open_spans,
               "metrics": registry.snapshot()}
        try:
            doc["slo"] = alerts.state() if alerts is not None else None
        except Exception:            # a torn engine must not cost the
            doc["slo"] = None        # bundle its events
        try:
            tsdb = cfg.get("tsdb")
            if tsdb is None:
                from deeplearning4j_tpu import telemetry
                tsdb = telemetry.get_tsdb()
            doc["history"] = tsdb.dump_recent(
                window_s=cfg.get("history_s", 300.0))
        except Exception:            # same discipline as slo: history
            doc["history"] = None    # must not cost the bundle
        return doc

    def _prune(self, cfg: dict) -> None:
        try:
            prune_bundles(cfg["directory"], cfg.get("max_bundles"),
                          cfg.get("max_bundle_age_s"))
        except Exception:            # retention is best-effort; the
            log.exception(           # bundle already landed
                "flight recorder: bundle prune failed")

    def request_dump(self, reason: str, error: Optional[str] = None
                     ) -> Optional[str]:
        """Write one postmortem bundle NOW; returns its path, or None
        when no dump dir is installed (the hot sites call this
        unconditionally — unconfigured processes pay a lock peek).
        Never raises: a postmortem writer that crashes its caller
        would be the worst bug in the file."""
        with self._lock:
            cfg = self._cfg
        if cfg is None or not self.enabled:
            return None
        try:
            from deeplearning4j_tpu.resilience.coordination import (
                atomic_publish_json)
            doc = self._bundle_doc(cfg, reason)
            if error is not None:
                doc["error"] = str(error)
            path = os.path.join(
                cfg["directory"], BUNDLE_DIRNAME,
                f"{cfg['host']}-{os.getpid()}-"
                f"{next(self._dump_seq)}.json")
            atomic_publish_json(path, doc)
            if self._bundles is None:
                from deeplearning4j_tpu import telemetry
                self._bundles = telemetry.counter(
                    "postmortem_bundles_total",
                    "crash-forensics bundles this process published "
                    "(watchdog trips, chaos kills, preemptions, "
                    "explicit dumps)")
            self._bundles.inc()
            self._prune(cfg)
            log.warning("flight recorder: postmortem bundle %s (%s)",
                        path, reason)
            return path
        except Exception:
            log.exception("flight recorder: bundle write failed (%s)",
                          reason)
            return None

    # -- black box persistence ----------------------------------------
    def _persist_once(self) -> Optional[str]:
        with self._lock:
            cfg = self._cfg
        if cfg is None:
            return None
        from deeplearning4j_tpu.resilience.coordination import (
            atomic_publish_json)
        doc = self._bundle_doc(cfg, "blackbox")
        path = os.path.join(cfg["directory"], BLACKBOX_DIRNAME,
                            f"{cfg['host']}.json")
        atomic_publish_json(path, doc)
        self._prune(cfg)
        return path

    def _persist_loop(self, interval: float,
                      stop: threading.Event) -> None:
        while not stop.wait(interval):
            try:
                self._persist_once()
            except Exception:        # a shared-dir flake must never
                log.exception(       # kill the black box for good
                    "flight recorder: black-box persist failed")

    def close(self) -> None:
        """Stop the black-box daemon (one final persist included)."""
        with self._lock:
            stop = self._stop
            thread = self._thread
            self._thread = None
        stop.set()
        if thread is not None:
            thread.join(timeout=5)
            try:
                self._persist_once()
            except Exception:
                log.exception("flight recorder: final persist failed")


def list_bundles(directory) -> List[str]:
    """Postmortem bundle paths under ``directory``, oldest first."""
    bdir = os.path.join(str(directory), BUNDLE_DIRNAME)
    try:
        names = os.listdir(bdir)
    except OSError:
        return []
    paths = [os.path.join(bdir, n) for n in names
             if n.endswith(".json")]
    return sorted(paths, key=lambda p: (os.path.getmtime(p), p))


def load_bundle(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _prune_dir(dirpath: str, max_count: Optional[int],
               max_age_s: Optional[float],
               now: Optional[float] = None) -> List[str]:
    """Evict ``.json`` files beyond the count cap or older than the
    age cap, OLDEST first (mtime order — the same order
    :func:`list_bundles` presents).  Each eviction is one unlink, so
    a concurrent reader sees complete files or none; a file another
    process already removed is skipped, never an error."""
    if max_count is None and max_age_s is None:
        return []
    try:
        names = os.listdir(dirpath)
    except OSError:
        return []
    entries = []
    for name in names:
        if not name.endswith(".json"):
            continue
        path = os.path.join(dirpath, name)
        try:
            entries.append((os.path.getmtime(path), path))
        except OSError:
            continue                 # raced with another pruner
    entries.sort()
    now = time.time() if now is None else float(now)
    doomed = []
    if max_age_s is not None:
        cutoff = now - max_age_s
        doomed += [e for e in entries if e[0] < cutoff]
        entries = [e for e in entries if e[0] >= cutoff]
    if max_count is not None and len(entries) > max_count:
        doomed += entries[:len(entries) - max_count]
    removed: List[str] = []
    for _, path in doomed:
        try:
            os.remove(path)
            removed.append(path)
        except OSError:
            continue
    return removed


def prune_bundles(directory, max_bundles: Optional[int] = 64,
                  max_age_s: Optional[float] = None) -> List[str]:
    """Cap ``_postmortem/`` and ``_flightrec/`` under ``directory``
    by count and age; returns the evicted paths (oldest-first per
    dir).  Every eviction counts into
    ``postmortem_bundles_evicted_total`` — silent rotation would
    read as bundles that never happened."""
    directory = str(directory)
    removed: List[str] = []
    for sub in (BUNDLE_DIRNAME, BLACKBOX_DIRNAME):
        removed += _prune_dir(os.path.join(directory, sub),
                              max_bundles, max_age_s)
    if removed:
        try:
            from deeplearning4j_tpu import telemetry
            telemetry.counter(
                "postmortem_bundles_evicted_total",
                "postmortem bundles and black-box snapshots evicted "
                "by the retention policy (count/age caps)"
            ).inc(len(removed))
        except Exception:
            pass                     # partially-imported package
        log.info("flight recorder: retention evicted %d file(s) "
                 "under %s", len(removed), directory)
    return removed


def salvage_bundles(directory, max_bundles: Optional[int] = None,
                    max_age_s: Optional[float] = None) -> List[str]:
    """Promote black-box ring snapshots whose (host, pid) never wrote
    a real bundle into ``reason="salvaged: ..."`` postmortems — the
    SIGKILL path: the victim could not dump, but its black-box daemon
    left the last persisted ring + open spans behind.  Idempotent
    (an already-salvaged (host, pid) is skipped); returns the NEW
    bundle paths.  ``max_bundles`` / ``max_age_s`` apply the same
    rotation policy as the writer AFTER salvage, so a salvage sweep
    respects the retention caps instead of resurrecting evicted
    history past them."""
    directory = str(directory)
    from deeplearning4j_tpu.resilience.coordination import (
        atomic_publish_json)
    covered = set()
    for path in list_bundles(directory):
        try:
            doc = load_bundle(path)
            covered.add((doc.get("host"), doc.get("pid")))
        except (OSError, ValueError):
            continue
    bbdir = os.path.join(directory, BLACKBOX_DIRNAME)
    try:
        names = sorted(os.listdir(bbdir))
    except OSError:
        names = []       # no black boxes; retention below still runs
    out: List[str] = []
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            doc = load_bundle(os.path.join(bbdir, name))
        except (OSError, ValueError):
            continue                 # mid-replace: next pass gets it
        key = (doc.get("host"), doc.get("pid"))
        if key in covered:
            continue
        doc["reason"] = f"salvaged: {doc.get('reason', 'blackbox')}"
        doc["salvaged"] = True
        path = os.path.join(directory, BUNDLE_DIRNAME,
                            f"{doc.get('host', 'unknown')}-"
                            f"{doc.get('pid', 0)}-salvaged.json")
        atomic_publish_json(path, doc)
        out.append(path)
    if max_bundles is not None or max_age_s is not None:
        prune_bundles(directory, max_bundles, max_age_s)
    return out
