"""Process-wide, thread-safe metrics registry — Counter / Gauge /
Histogram with Prometheus text exposition and jsonl snapshots.

The fleet-operations counterpart of the training-only ``ui.StatsListener``
stream: TensorFlow's large-scale deployment and Google's TPU fleet papers
both treat monitoring as a first-class subsystem, and a serving system
cannot answer "are we saturated?" from a per-iteration training jsonl.
Design follows the Prometheus client data model (families -> labeled
children -> samples) reduced to what this repo needs:

* every child carries its own ``threading.Lock`` — ``inc``/``observe``
  from the ``ParallelInference`` worker, request threads, and the fit
  loop never race (a bare ``float +=`` spans several bytecodes under
  the GIL and CAN lose updates);
* ``render_prometheus()`` emits the text format any Prometheus/
  VictoriaMetrics scraper ingests (see ``exposition.start_metrics_server``
  for the stdlib scrape endpoint);
* ``snapshot()`` emits a plain-dict form that plugs into the existing
  ``ui.FileStatsStorage`` jsonl pipeline and ``ui.render_report``;
* ``merge_snapshot()`` folds a worker's snapshot into a driver registry
  (cross-worker aggregation: counters/histograms add, gauges last-write).

Host-side only: these are Python-dispatch-time metrics.  Time spent
INSIDE one compiled XLA program is visible only as the whole step's
wall time (use ``ui.ProfilerListener`` for per-op device traces).
"""
from __future__ import annotations

import math
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_INF = float("inf")

# Prometheus default buckets — latency-shaped, seconds.
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
# Ratio-shaped buckets (batch occupancy, padding waste): eighths of [0, 1].
RATIO_BUCKETS = tuple(i / 8 for i in range(1, 9))


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt_labels(labelnames: Sequence[str], labelvalues: Sequence[str],
                extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = list(zip(labelnames, labelvalues)) + list(extra)
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in pairs)
    return "{" + inner + "}"


def parse_series(series: str) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    """Invert the ``name{k="v",...}`` series strings ``snapshot()``
    emits back into ``(name, ((k, v), ...))``.  Values may contain
    commas/'='/escaped quotes (e.g. a mesh-shape label), so this
    parses the quoted escape grammar ``_fmt_labels`` writes instead of
    splitting on ','.  Shared by ``merge_snapshot`` and the fleet
    aggregator (``telemetry.fleet``)."""
    import re
    if "{" not in series:
        return series, ()
    name, _, rest = series.partition("{")
    unesc = lambda v: re.sub(
        r"\\(.)", lambda m: {"n": "\n"}.get(m.group(1), m.group(1)), v)
    pairs = [
        (k, unesc(v)) for k, v in re.findall(
            r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"',
            rest.rstrip("}"))]
    return name, tuple(pairs)


class _Child:
    """One labeled time series; all mutation under ``self._lock``."""

    def __init__(self):
        self._lock = threading.Lock()


class _CounterChild(_Child):
    def __init__(self):
        super().__init__()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _GaugeChild(_Child):
    def __init__(self):
        super().__init__()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _HistogramChild(_Child):
    def __init__(self, buckets: Sequence[float]):
        super().__init__()
        self._uppers = tuple(buckets)
        self._counts = [0] * (len(self._uppers) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            for i, ub in enumerate(self._uppers):
                if value <= ub:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def state(self) -> Tuple[Tuple[float, ...], List[int], float, int]:
        with self._lock:
            return self._uppers, list(self._counts), self._sum, self._count

    def percentile(self, q: float) -> float:
        """Bucket-derived quantile (q in [0, 1]) with linear interpolation
        inside the winning bucket — the p50/p95/p99 a dashboard derives
        from ``histogram_quantile``.  NaN when empty."""
        uppers, counts, _, total = self.state()
        if total == 0:
            return math.nan
        rank = q * total
        cum = 0.0
        lo = 0.0
        for i, ub in enumerate(uppers):
            prev = cum
            cum += counts[i]
            if cum >= rank:
                if counts[i] == 0:
                    return ub
                frac = (rank - prev) / counts[i]
                return lo + frac * (ub - lo)
            lo = ub
        return uppers[-1] if uppers else math.nan


_CHILD_TYPES = {"counter": _CounterChild, "gauge": _GaugeChild,
                "histogram": _HistogramChild}


class _Family:
    """A named metric with fixed label names; ``labels()`` creates/gets
    the child for one label-value tuple.  Unlabeled metrics delegate to
    a single ``()`` child so ``Counter.inc()`` works directly."""

    kind: str = ""

    def __init__(self, name: str, documentation: str,
                 labelnames: Sequence[str] = (), **kw):
        self.name = name
        self.documentation = documentation
        self.labelnames = tuple(labelnames)
        self._kw = kw
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}
        if not self.labelnames:
            self._children[()] = self._new_child()

    def _new_child(self) -> _Child:
        return _CHILD_TYPES[self.kind](**self._kw)

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass labels positionally OR by name")
            values = tuple(kv[n] for n in self.labelnames)
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, "
                f"got {values}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = self._new_child()
            return child

    def remove(self, *values, **kv) -> None:
        """Drop the child for one label-value tuple (prometheus-client
        parity): long-lived processes that cycle labeled resources
        (e.g. serving instances) must be able to retire dead series
        instead of leaking them into every scrape."""
        if kv:
            if values:
                raise ValueError("pass labels positionally OR by name")
            values = tuple(kv[n] for n in self.labelnames)
        values = tuple(str(v) for v in values)
        with self._lock:
            self._children.pop(values, None)

    def _items(self) -> List[Tuple[Tuple[str, ...], _Child]]:
        with self._lock:
            return sorted(self._children.items())

    # unlabeled convenience delegation ---------------------------------
    def _default(self) -> _Child:
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; call "
                ".labels(...) first")
        return self._children[()]


class Counter(_Family):
    kind = "counter"

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value


class Gauge(_Family):
    kind = "gauge"

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    @property
    def value(self) -> float:
        return self._default().value


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name, documentation, labelnames=(),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        buckets = tuple(sorted(float(b) for b in buckets))
        if not buckets:
            raise ValueError("histogram needs at least one bucket bound")
        super().__init__(name, documentation, labelnames, buckets=buckets)
        self.buckets = buckets

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def percentile(self, q: float) -> float:
        return self._default().percentile(q)

    @property
    def count(self) -> int:
        return self._default().state()[3]

    @property
    def sum(self) -> float:
        return self._default().state()[2]


_FAMILY_TYPES = {"counter": Counter, "gauge": Gauge,
                 "histogram": Histogram}


class MetricsRegistry:
    """Create-or-get metric families by name; render/snapshot them all.

    One process-wide default instance lives in ``telemetry`` (module
    functions ``counter``/``gauge``/``histogram`` register there), so
    instrumented modules across the codebase share one scrape surface;
    tests that need isolation construct their own registry."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _get_or_create(self, cls, name: str, documentation: str,
                       labelnames=(), **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}, not {cls.kind}")
                if tuple(labelnames) != fam.labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered with labels "
                        f"{fam.labelnames}, not {tuple(labelnames)}")
                want = kw.get("buckets")
                if want is not None and tuple(sorted(
                        float(b) for b in want)) != fam.buckets:
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {fam.buckets}; a second registration "
                        "with different buckets would silently mis-shape "
                        "its quantiles")
                return fam
            fam = cls(name, documentation, labelnames, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name, documentation="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, documentation, labelnames)

    def gauge(self, name, documentation="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, documentation, labelnames)

    def histogram(self, name, documentation="", labelnames=(),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, documentation,
                                   labelnames, buckets=buckets)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def families(self) -> List[_Family]:
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    # -- exposition ----------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus/OpenMetrics text format, one sample per series."""
        out: List[str] = []
        for fam in self.families():
            out.append(f"# HELP {fam.name} {fam.documentation}")
            out.append(f"# TYPE {fam.name} {fam.kind}")
            for lv, child in fam._items():
                base = _fmt_labels(fam.labelnames, lv)
                if fam.kind in ("counter", "gauge"):
                    out.append(f"{fam.name}{base} {child.value}")
                else:
                    uppers, counts, total, count = child.state()
                    cum = 0
                    for ub, c in zip(uppers, counts):
                        cum += c
                        lab = _fmt_labels(fam.labelnames, lv,
                                          (("le", repr(ub)),))
                        out.append(f"{fam.name}_bucket{lab} {cum}")
                    lab = _fmt_labels(fam.labelnames, lv,
                                      (("le", "+Inf"),))
                    out.append(f"{fam.name}_bucket{lab} {count}")
                    out.append(f"{fam.name}_sum{base} {total}")
                    out.append(f"{fam.name}_count{base} {count}")
        return "\n".join(out) + "\n"

    def snapshot(self) -> Dict:
        """JSON-ready state: counters/gauges as ``{series: value}``,
        histograms with count/sum/buckets and derived p50/p95/p99 —
        the record shape ``ui.FileStatsStorage`` appends and
        ``ui.render_report`` tabulates."""
        snap = {"timestamp": time.time(), "counters": {}, "gauges": {},
                "histograms": {}}
        for fam in self.families():
            for lv, child in fam._items():
                series = fam.name + _fmt_labels(fam.labelnames, lv)
                if fam.kind == "counter":
                    snap["counters"][series] = child.value
                elif fam.kind == "gauge":
                    snap["gauges"][series] = child.value
                else:
                    uppers, counts, total, count = child.state()
                    snap["histograms"][series] = {
                        "count": count, "sum": total,
                        "buckets": {repr(u): c
                                    for u, c in zip(uppers, counts)},
                        "inf": counts[-1],
                        "p50": child.percentile(0.50),
                        "p95": child.percentile(0.95),
                        "p99": child.percentile(0.99),
                    }
        return snap

    def merge_snapshot(self, snap: Dict) -> None:
        """Fold one worker's ``snapshot()`` into this registry —
        driver-side aggregation for multi-process training (the
        ``jax.distributed`` workers each run their own registry; ship
        snapshots over your control plane and merge here).  Counters
        and histograms accumulate; gauges take the incoming value."""
        split_series = parse_series
        for series, v in snap.get("counters", {}).items():
            name, pairs = split_series(series)
            fam = self.counter(name, labelnames=tuple(k for k, _ in pairs))
            child = fam.labels(*[val for _, val in pairs]) if pairs \
                else fam._default()
            child.inc(v)
        for series, v in snap.get("gauges", {}).items():
            name, pairs = split_series(series)
            fam = self.gauge(name, labelnames=tuple(k for k, _ in pairs))
            child = fam.labels(*[val for _, val in pairs]) if pairs \
                else fam._default()
            child.set(v)
        for series, h in snap.get("histograms", {}).items():
            name, pairs = split_series(series)
            uppers = tuple(float(u) for u in h["buckets"])
            fam = self.histogram(name,
                                 labelnames=tuple(k for k, _ in pairs),
                                 buckets=uppers or DEFAULT_BUCKETS)
            child = fam.labels(*[val for _, val in pairs]) if pairs \
                else fam._default()
            with child._lock:
                for i, u in enumerate(child._uppers):
                    child._counts[i] += h["buckets"].get(repr(u), 0)
                child._counts[-1] += h.get("inf", 0)
                child._sum += h["sum"]
                child._count += h["count"]

    def series_count(self) -> int:
        """Distinct exposed sample series (histogram buckets/sum/count
        each count, matching what a scraper stores)."""
        n = 0
        for fam in self.families():
            for _lv, child in fam._items():
                if fam.kind == "histogram":
                    n += len(child.state()[0]) + 3  # buckets + Inf/sum/cnt
                else:
                    n += 1
        return n
