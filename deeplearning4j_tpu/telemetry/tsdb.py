"""Embedded metrics time-series store — the fleet's ONE history
substrate.

PR 15 left three consumers each privately re-implementing "bounded
windowed history over an instantaneous scrape": the SLO engine's
``(t, good, bad)`` sample list, the backlog forecaster's deque, and
the autoscaler's previous-bucket dict.  Postmortem bundles froze only
a FINAL metric snapshot — "what did this series do over the last N
minutes" was unanswerable, on any host.  Fleet-scale TPU operation
lives on exactly that question (the fleet-resilience emphasis of
arXiv 2606.15870), so this module makes it first-class:

* **:class:`TimeSeriesStore`** — timestamped samples of every
  registered series, recorded once per scrape/beacon cycle
  (:meth:`TimeSeriesStore.record`) into bounded per-series rings.
  Two retention shapes per series:

  - **two-tier** (the default, what ``record`` uses): a raw recent
    window (:data:`RAW_WINDOW_S` / :data:`MAX_RAW_POINTS`) whose aged
    samples spill into a downsampled older tier (keep-newest per
    :data:`DOWN_INTERVAL_S` bucket) retained for :data:`RETENTION_S`;
    every collapsed/expired sample counts as an eviction;
  - **windowed** (``mode="slo"`` / ``mode="window"``): the exact
    bounded-window encodings the SLO engine and forecaster carried
    privately, now shared — same-instant keep-first + dense-head
    collapse + keep-one-at-or-before-horizon trim for burn math,
    plain strict-trim windows for trend fits and pairwise deltas.

* **range reads + functions** — :meth:`points` (bisect-indexed, like
  the engine history it replaces), :meth:`delta` / :meth:`rate` with
  worker-restart RESET detection (:func:`is_reset` — the one helper
  slo.py and the autoscaler now share), and
  :meth:`quantile_over_time` via the existing histogram-bucket math
  (:func:`window_quantile`, moved here from ``serving.autoscale``).

* **/query** — :meth:`query` backs the JSON endpoint beside
  ``/metrics``, ``/traces`` and ``/alerts``
  (``telemetry.exposition``): series selector + label matchers +
  ``[start, end]`` + optional function.  A ``FleetRegistry`` records
  its AGGREGATED view, so the store holds host-tagged series and the
  ``host="fleet"`` rollups the existing ``rollup_children`` rule
  produces.

* **crash forensics** — :meth:`dump_recent` renders the last N
  minutes of every series, downsampled, for the flight recorder's
  postmortem bundles (``telemetry.flightrec``): a crash ships its
  pre-crash metric HISTORY, not just a terminal snapshot.

One store-level lock guards all shared state; appends are O(1)
amortized and reads copy out under the lock — the recorder thread,
the control loops and HTTP readers never race.
"""
from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .registry import _fmt_labels, parse_series

#: two-tier retention defaults: raw samples kept this long ...
RAW_WINDOW_S = 300.0
#: ... and at most this many per series (a hot recorder cannot grow
#: a series unbounded inside the raw window)
MAX_RAW_POINTS = 2048
#: aged raw samples collapse to one per this interval ...
DOWN_INTERVAL_S = 10.0
#: ... and the downsampled tier is dropped past this age
RETENTION_S = 3600.0

#: query functions ``/query`` accepts
QUERY_FUNCS = ("range", "rate", "delta", "quantile")


def is_reset(prev: float, cur: float, eps: float = 1e-9) -> bool:
    """Worker-restart reset detection over cumulative totals: a
    counter that went DOWN did not un-count events — its process
    restarted and the new total shares no origin with the old one.
    The one encoding ``slo.AlertEngine``, ``serving.autoscale`` and
    this store's ``delta``/``rate`` all share."""
    return cur < prev - eps


def window_quantile(uppers: Tuple[float, ...], counts: Sequence[float],
                    q: float) -> float:
    """Interpolated quantile over one WINDOW's bucket counts (the
    registry's ``percentile`` over deltas instead of cumulative
    state).  ``counts`` includes the trailing +Inf bucket: overflow
    samples COUNT toward the rank and resolve to the top finite bound
    — exactly like ``_HistogramChild.percentile`` — because the worst
    waits are precisely the ones a control loop must not lose (an
    all-overflow meltdown window must read as maximal pressure, not
    as idle).  NaN when the window is empty."""
    total = sum(counts)
    if total <= 0:
        return math.nan
    rank = q * total
    cum = 0.0
    lo = 0.0
    for i, ub in enumerate(uppers):
        prev = cum
        cum += counts[i]
        if cum >= rank:
            if counts[i] == 0:
                return ub
            return lo + (rank - prev) / counts[i] * (ub - lo)
        lo = ub
    return uppers[-1] if uppers else math.nan


class _Series:
    """One series' ring state (mutated only under the store lock):
    ``raw`` and ``down`` are time-ordered ``(t, value)`` lists —
    window edges bisect into them.  ``mode`` fixes the retention
    shape at first append; ``uppers`` is histogram bucket metadata."""

    __slots__ = ("kind", "mode", "uppers", "raw", "down", "horizon_s",
                 "max_points")

    def __init__(self, kind: str, mode: Optional[str],
                 uppers: Optional[Tuple[float, ...]],
                 horizon_s: Optional[float],
                 max_points: Optional[int]):
        self.kind = kind
        self.mode = mode
        self.uppers = uppers
        self.raw: List[Tuple[float, Any]] = []
        self.down: List[Tuple[float, Any]] = []
        self.horizon_s = horizon_s
        self.max_points = max_points

    def merged(self) -> List[Tuple[float, Any]]:
        return self.down + self.raw


def _bisect_le(pts: List[Tuple[float, Any]], t: float) -> int:
    """Index of the newest point at-or-before ``t`` (clamped to the
    oldest — a young series reads its whole history as the window,
    the same rule the SLO engine's edge lookup used)."""
    return max(0, bisect.bisect_right(pts, t, key=lambda p: p[0]) - 1)


class TimeSeriesStore:
    """The embedded TSDB: per-series bounded rings + range reads.

    >>> store = TimeSeriesStore()
    >>> store.record(registry)            # one sample of every series
    >>> store.points('fleet_queue_depth', start=t0, end=t1)
    >>> store.rate('fleet_requests_total{outcome="admitted"}', t0, t1)
    >>> store.quantile_over_time(
    ...     'fleet_request_phase_seconds{phase="queue"}', 0.99, t0, t1)

    Timestamps are WALL clock (``time.time()``) so ranges line up
    with postmortem timelines and cross-host beacons; pass ``now=``
    to pin them in tests.  Values by ``kind``: ``counter``/``gauge``
    floats, ``histogram`` ``(counts_incl_inf, sum)`` tuples with the
    bucket bounds kept once per series, ``window`` whatever tuple the
    windowed consumer folds (the SLO engine's ``(good, bad)``)."""

    def __init__(self, raw_window_s: float = RAW_WINDOW_S,
                 max_raw_points: int = MAX_RAW_POINTS,
                 down_interval_s: float = DOWN_INTERVAL_S,
                 retention_s: float = RETENTION_S):
        self.raw_window_s = float(raw_window_s)
        self.max_raw_points = int(max_raw_points)
        self.down_interval_s = float(down_interval_s)
        self.retention_s = float(retention_s)
        self._lock = threading.Lock()
        self._series: Dict[str, _Series] = {}
        self._samples_total = 0
        self._evicted_total = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- writes --------------------------------------------------------
    def record(self, registry, now: Optional[float] = None) -> int:
        """Append one timestamped sample of EVERY series ``registry``
        currently exposes (two-tier retention); returns the number of
        series sampled.  Called once per scrape/beacon cycle — by the
        ``FleetRegistry`` on its aggregated view, by
        :meth:`start_recorder`'s daemon on a process registry."""
        now = time.time() if now is None else float(now)
        n = 0
        with self._lock:
            for fam in registry.families():
                for lv, child in fam._items():
                    key = fam.name + _fmt_labels(fam.labelnames, lv)
                    if fam.kind == "histogram":
                        uppers, counts, total, _cnt = child.state()
                        self._append_locked(
                            key, now, (tuple(counts), float(total)),
                            kind="histogram", uppers=uppers)
                    else:
                        self._append_locked(key, now, child.value,
                                            kind=fam.kind)
                    n += 1
        return n

    def append(self, series: str, t: float, value,
               kind: str = "gauge",
               uppers: Optional[Tuple[float, ...]] = None,
               mode: Optional[str] = None,
               horizon_s: Optional[float] = None,
               max_points: Optional[int] = None) -> None:
        """Append one sample.  ``mode=None`` (default) is two-tier
        retention; ``mode="slo"`` is the SLO engine's windowed
        encoding (same-instant keep-first, dense-head collapse,
        keep-one-at-or-before-``horizon_s`` trim); ``mode="window"``
        a plain bounded window (strict trim past ``horizon_s``,
        newest ``max_points`` kept) for trend fits and pairwise
        deltas.  A series' mode is fixed at first append."""
        with self._lock:
            self._append_locked(series, float(t), value, kind=kind,
                                uppers=uppers, mode=mode,
                                horizon_s=horizon_s,
                                max_points=max_points)

    def _append_locked(self, series, t, value, kind="gauge",
                       uppers=None, mode=None, horizon_s=None,
                       max_points=None) -> None:
        st = self._series.get(series)
        if st is None:
            st = self._series[series] = _Series(
                kind, mode, uppers, horizon_s, max_points)
        raw = st.raw
        if st.mode == "slo":
            if raw and t <= raw[-1][0]:
                return               # same instant (double-driven
                                     # consumer): keep the first sample
            self._samples_total += 1
            horizon = st.horizon_s or math.inf
            cap = st.max_points or MAX_RAW_POINTS
            if len(raw) >= 2 and t - raw[-2][0] < horizon / cap:
                # dense head: collapse the sub-gap intermediate point
                # — the newest totals are what every window's right
                # edge reads, the skipped point bought nothing
                raw[-1] = (t, value)
                self._evicted_total += 1
            else:
                raw.append((t, value))
            # keep ONE sample at-or-before the horizon so a full
            # window always has a left edge to difference against
            cut = 0
            n = len(raw)
            while n - cut > 2 and raw[cut + 1][0] < t - horizon:
                cut += 1
            if cut:
                del raw[:cut]
                self._evicted_total += cut
            return
        self._samples_total += 1
        raw.append((t, value))
        if st.mode == "window":
            horizon = st.horizon_s
            cut = 0
            if horizon is not None:
                n = len(raw)
                while cut < n and raw[cut][0] < t - horizon:
                    cut += 1
            if st.max_points is not None:
                cut = max(cut, len(raw) - st.max_points)
            if cut:
                del raw[:cut]
                self._evicted_total += cut
            return
        # two-tier: age/overflow raw samples spill downsampled
        while raw and (raw[0][0] < t - self.raw_window_s
                       or len(raw) > self.max_raw_points):
            s = raw.pop(0)
            down = st.down
            # FIXED bucket anchoring (floor of t / interval) — a
            # sliding same-as-last-kept comparison would chain: every
            # sample lands < interval after the one it replaced, and
            # the whole old tier collapses into a single point
            if down and (s[0] // self.down_interval_s
                         == down[-1][0] // self.down_interval_s):
                down[-1] = s         # keep-newest per bucket
                self._evicted_total += 1
            else:
                down.append(s)
        down = st.down
        cut = 0
        n = len(down)
        while cut < n and down[cut][0] < t - self.retention_s:
            cut += 1
        if cut:
            del down[:cut]
            self._evicted_total += cut

    def clear(self, series: str) -> None:
        """Drop one series' points (config kept) — the RESET re-prime
        the SLO engine applies when a restart breaks the cumulative
        origin.  Not an eviction: nothing aged out."""
        with self._lock:
            st = self._series.get(series)
            if st is not None:
                st.raw.clear()
                st.down.clear()

    # -- reads ---------------------------------------------------------
    def series(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def points(self, series: str, start: Optional[float] = None,
               end: Optional[float] = None) -> List[Tuple[float, Any]]:
        """``(t, value)`` samples in ``[start, end]`` (both edges
        inclusive; None = unbounded), oldest first."""
        with self._lock:
            st = self._series.get(series)
            pts = st.merged() if st is not None else []
        if start is not None:
            pts = pts[bisect.bisect_left(pts, start,
                                         key=lambda p: p[0]):]
        if end is not None:
            pts = pts[:bisect.bisect_right(pts, end,
                                           key=lambda p: p[0])]
        return pts

    def latest(self, series: str) -> Optional[Tuple[float, Any]]:
        with self._lock:
            st = self._series.get(series)
            if st is None:
                return None
            return st.raw[-1] if st.raw else (
                st.down[-1] if st.down else None)

    def edge(self, series: str, t: float) -> Optional[Tuple[float, Any]]:
        """The newest sample at-or-before ``t`` (the oldest retained
        sample when history starts later — a young series reads its
        whole history as the window)."""
        with self._lock:
            st = self._series.get(series)
            pts = st.merged() if st is not None else []
        if not pts:
            return None
        return pts[_bisect_le(pts, t)]

    def last_two(self, series: str) -> Optional[
            Tuple[Tuple[float, Any], Tuple[float, Any]]]:
        """The newest two samples (prev, cur) — the pairwise delta
        shape the autoscaler's windowed quantiles difference; None
        until two samples exist."""
        with self._lock:
            st = self._series.get(series)
            pts = st.merged() if st is not None else []
        if len(pts) < 2:
            return None
        return pts[-2], pts[-1]

    def span(self, series: str) -> float:
        """Seconds between the oldest and newest retained samples (0
        with fewer than 2) — the SLO engine's coverage gate."""
        with self._lock:
            st = self._series.get(series)
            pts = st.merged() if st is not None else []
        return pts[-1][0] - pts[0][0] if len(pts) > 1 else 0.0

    def kind(self, series: str) -> Optional[str]:
        with self._lock:
            st = self._series.get(series)
            return st.kind if st is not None else None

    # -- range functions ----------------------------------------------
    def delta(self, series: str, start: float, end: float
              ) -> Optional[float]:
        """Reset-aware increase of a cumulative series over
        ``[start, end]``: left edge = newest sample at-or-before
        ``start``; a reset segment's new total counts wholesale (the
        restarted worker re-counted from zero — the same fold the
        fleet aggregator applies).  None when no samples cover the
        range."""
        base = self.edge(series, start)
        if base is None:
            return None
        pts = self.points(series, start=base[0], end=end)
        if not pts:
            return None
        d = 0.0
        prev = pts[0][1]
        for _t, v in pts[1:]:
            d += v if is_reset(prev, v) else (v - prev)
            prev = v
        return max(0.0, d)

    def rate(self, series: str, start: float, end: float
             ) -> Optional[float]:
        """``delta`` per second over the samples actually covering
        the range; None below 2 samples (a rate needs a baseline)."""
        base = self.edge(series, start)
        if base is None:
            return None
        pts = self.points(series, start=base[0], end=end)
        if len(pts) < 2:
            return None
        span = pts[-1][0] - pts[0][0]
        if span <= 0:
            return None
        d = 0.0
        prev = pts[0][1]
        for _t, v in pts[1:]:
            d += v if is_reset(prev, v) else (v - prev)
            prev = v
        return max(0.0, d) / span

    def quantile_over_time(self, series: str, q: float, start: float,
                           end: float) -> Optional[float]:
        """Interpolated quantile of a HISTOGRAM series' observations
        that fell inside ``[start, end]``: reset-aware per-bucket
        window deltas fed to :func:`window_quantile`.  None when the
        series is not a histogram or no samples cover the range; NaN
        when the window saw no observations."""
        with self._lock:
            st = self._series.get(series)
            if st is None or st.kind != "histogram" or st.uppers is None:
                return None
            uppers = st.uppers
        base = self.edge(series, start)
        if base is None:
            return None
        pts = self.points(series, start=base[0], end=end)
        if not pts:
            return None
        window = [0.0] * len(pts[0][1][0])
        prev = pts[0][1][0]
        for _t, (counts, _s) in pts[1:]:
            if any(is_reset(p, c) for p, c in zip(prev, counts)):
                for i, c in enumerate(counts):
                    window[i] += c
            else:
                for i, (p, c) in enumerate(zip(prev, counts)):
                    window[i] += max(0.0, c - p)
            prev = counts
        return window_quantile(uppers, window, q)

    # -- the /query surface -------------------------------------------
    def query(self, series: str,
              matchers: Iterable[Tuple[str, str]] = (),
              start: Optional[float] = None,
              end: Optional[float] = None,
              func: str = "range",
              q: Optional[float] = None) -> Dict:
        """The ``/query`` endpoint's engine.  ``series`` selects by
        metric NAME (label ``matchers`` filter by equality) or, with
        a ``{`` present, by exact series key.  ``func``: ``range``
        returns ``[t, value]`` points, ``rate``/``delta`` a scalar
        per matched series (cumulative kinds only), ``quantile`` the
        bucket-interpolated ``q`` over the window.  Unknown selectors
        match nothing — an empty result, not an error (absence of
        history is an answer)."""
        if func not in QUERY_FUNCS:
            raise ValueError(
                f"unknown func {func!r}; one of {QUERY_FUNCS}")
        if func == "quantile" and (q is None or not 0.0 <= q <= 1.0):
            raise ValueError("func=quantile needs q in [0, 1]")
        want = tuple((str(k), str(v)) for k, v in matchers)
        matched: List[str] = []
        for key in self.series():
            if "{" in series:
                if key != series:
                    continue
            else:
                name, pairs = parse_series(key)
                if name != series:
                    continue
                have = dict(pairs)
                if any(have.get(k) != v for k, v in want):
                    continue
            matched.append(key)
        now = time.time()
        t0 = now - self.raw_window_s if start is None else float(start)
        t1 = now if end is None else float(end)
        results = []
        for key in matched:
            kind = self.kind(key)
            if func == "range":
                pts = self.points(key, start=t0, end=t1)
                results.append({"series": key, "kind": kind,
                                "points": [self._json_point(p, kind)
                                           for p in pts]})
            elif func in ("rate", "delta"):
                if kind == "histogram":
                    raise ValueError(
                        f"func={func} needs a scalar series; "
                        f"{key!r} is a histogram (use quantile)")
                v = (self.rate if func == "rate" else self.delta)(
                    key, t0, t1)
                results.append({"series": key, "kind": kind,
                                "value": v})
            else:
                v = self.quantile_over_time(key, q, t0, t1)
                if v is not None and math.isnan(v):
                    v = None
                results.append({"series": key, "kind": kind,
                                "value": v})
        return {"func": func, "start": t0, "end": t1,
                "matched": len(matched), "results": results}

    @staticmethod
    def _json_point(p: Tuple[float, Any], kind: Optional[str]):
        t, v = p
        if kind == "histogram":
            counts, total = v
            return [t, {"count": float(sum(counts)),
                        "sum": float(total)}]
        if isinstance(v, tuple):
            return [t, list(v)]
        return [t, v]

    # -- crash forensics ----------------------------------------------
    def dump_recent(self, window_s: float = 300.0,
                    max_points: int = 64) -> Dict:
        """The last ``window_s`` of every series, stride-downsampled
        to <= ``max_points`` each (newest sample always kept) — the
        pre-crash metric history a postmortem bundle ships
        (``telemetry.flightrec``)."""
        now = time.time()
        out: Dict[str, Dict] = {}
        for key in self.series():
            kind = self.kind(key)
            pts = self.points(key, start=now - float(window_s))
            if not pts:
                continue
            if len(pts) > max_points:
                stride = -(-len(pts) // max_points)
                pts = pts[::stride] + [pts[-1]]
            out[key] = {"kind": kind,
                        "points": [self._json_point(p, kind)
                                   for p in pts]}
        return {"window_s": float(window_s), "t": now, "series": out}

    def stats(self) -> Dict[str, float]:
        with self._lock:
            points = sum(len(st.raw) + len(st.down)
                         for st in self._series.values())
            return {"series": len(self._series),
                    "samples_total": self._samples_total,
                    "evicted_total": self._evicted_total,
                    "points": points}

    # -- recorder daemon ----------------------------------------------
    def start_recorder(self, registry=None, interval_s: float = 1.0
                       ) -> "TimeSeriesStore":
        """Sample ``registry`` (default: the process registry) every
        ``interval_s`` on a daemon thread — the standalone per-host
        shape; a ``FleetRegistry`` records its aggregated view per
        scrape instead."""
        if registry is None:
            from deeplearning4j_tpu import telemetry
            registry = telemetry.get_registry()
        # fresh stop event: re-armable after a close() (a set() event
        # would end the new loop on its first wait); the thread
        # closes over ITS OWN event
        stop = threading.Event()

        def loop():
            import logging
            log = logging.getLogger("deeplearning4j_tpu")
            while not stop.wait(interval_s):
                try:
                    self.record(registry)
                except Exception:
                    # one bad pass must not silence the history plane
                    log.exception("TimeSeriesStore recorder failed")

        thread = threading.Thread(target=loop,
                                  name="dl4j-tpu-tsdb-recorder",
                                  daemon=True)
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self          # already running
            self._stop = stop
            self._thread = thread
        thread.start()
        return self

    def close(self) -> None:
        with self._lock:
            stop = self._stop
            thread = self._thread
            self._thread = None
        stop.set()
        if thread is not None:
            thread.join(timeout=5.0)
