"""Continuous per-device profiling on the beacon plane (ISSUE 13).

The host-side registry answers "where does PYTHON time go"; XProf
(``ui.ProfilerListener``) answers "where does DEVICE time go" — but
its traces are host-local files a fleet scrape never sees.  This
module is the bridge, in the continuous-profiling shape the
MLPerf/XProf lineage uses: a LOW-OVERHEAD sampling profiler wraps the
hot dispatch sites (decode tick, speculative verify pass, prefill
chunk, optimizer step) with device-time measurement and folds samples
into ordinary registry families, so ``MetricsBeacon`` ships them and
the ONE fleet scrape gains
``fleet_device_phase_seconds{host=,device=,phase=}`` with rollups.

* **measurement** — :meth:`DeviceProfiler.measure` times the dispatch
  + host sync of a block.  Sites that already sync (the decode tick's
  ``np.asarray`` poll) pay nothing extra; async sites (prefill,
  optimizer step) hand their output to :meth:`_Measure.ready`, which
  ``jax.block_until_ready``-s it ONLY when this call is sampled —
  1-in-``every`` dispatches pays the sync, the rest stay fully async
  (the sampling that makes "continuous" affordable);
* **fold** — samples land in the per-``(device, phase)`` histogram;
  :meth:`top_ops` ranks phases by cumulative device seconds (count,
  total, p50/p99) — the top-K op summary a fleet dashboard shows;
* **on-demand XProf** — :meth:`request_xprof` arms a real
  ``jax.profiler`` trace capture around the next N sampled
  dispatches.  The RAW trace stays a host-local artifact (point
  XProf/TensorBoard at ``log_dir``); its SUMMARY (file count, bytes,
  captured wall seconds) lands in ``fleet_xprof_*`` series that
  beacon fleet-wide — an operator sees from the fleet scrape that the
  capture ran and where to fetch it.

Thread-safe: the sampling counters and the XProf arm/active state
mutate only under ``self._lock``; the registry families carry their
own per-child locks.  ``jax`` imports are lazy — constructing a
profiler (and ``observe``) never initializes a backend.
"""
from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from typing import Dict, List, Optional

from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

log = logging.getLogger("deeplearning4j_tpu")

#: the in-tree instrumented phases (callers may add their own)
PHASES = ("decode_tick", "verify", "prefill", "optimizer_step")


def _device_label() -> str:
    """``platform:id`` of the default device (one process profiles the
    device(s) it dispatches to; multi-chip splits arrive with the
    mesh-sharded tick)."""
    try:
        import jax
        dev = jax.devices()[0]
        return f"{dev.platform}:{dev.id}"
    except Exception:               # pragma: no cover - no backend
        return "unknown:0"


class _Measure:
    """The handle :meth:`DeviceProfiler.measure` yields.  ``sampled``
    tells the site whether THIS dispatch is being timed; ``ready``
    blocks on the given tree only then — the async fast path stays
    async."""

    __slots__ = ("sampled",)

    def __init__(self, sampled: bool):
        self.sampled = sampled

    def ready(self, tree) -> None:
        if self.sampled and tree is not None:
            import jax
            jax.block_until_ready(tree)


class DeviceProfiler:
    """Sampling device-time profiler feeding the fleet metric plane.

    >>> prof = telemetry.get_profiler()
    >>> with prof.measure("decode_tick"):
    ...     out = dispatch(...)      # site already host-syncs
    >>> with prof.measure("prefill") as m:
    ...     out = dispatch(...)
    ...     m.ready(out)             # sync only when sampled
    >>> prof.request_xprof("/tmp/xprof", dispatches=3)   # on demand
    >>> prof.top_ops(k=3)            # ranked device-time summary
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 sample_every: int = 1):
        if registry is None:
            from deeplearning4j_tpu import telemetry
            registry = telemetry.get_registry()
        self.registry = registry
        self.sample_every = max(1, int(sample_every))
        self._lock = threading.Lock()
        self._calls: Dict[str, int] = {}
        self._device: Optional[str] = None
        # XProf arm/active state under its OWN lock: start_trace can
        # take long (profiler backend init) and must never run under
        # — or make anyone wait on — the sampling lock every
        # measure() takes on the hot path
        self._xprof_lock = threading.Lock()
        self._xprof_dir: Optional[str] = None     # armed target
        self._xprof_starting = False              # claim flag
        self._xprof_active_dir: Optional[str] = None
        self._xprof_left = 0
        self._xprof_t0: Optional[float] = None
        self._hist = registry.histogram(
            "fleet_device_phase_seconds",
            "sampled device time per dispatch phase (dispatch -> "
            "host-sync complete): decode_tick, verify (speculative "
            "draft+verification), prefill (admission chunk), "
            "optimizer_step — the per-device timing the fleet scrape "
            "aggregates {host=,device=,phase=}",
            labelnames=("device", "phase"))
        self._skipped = registry.counter(
            "fleet_device_phase_skipped_total",
            "dispatches the sampling profiler let pass unmeasured "
            "(1-in-N sampling keeps async sites async)",
            labelnames=("phase",))
        self._xprof_captures = registry.counter(
            "fleet_xprof_captures_total",
            "on-demand jax.profiler trace captures completed on this "
            "host (the raw trace stays local; this summary beacons)")
        self._xprof_bytes = registry.gauge(
            "fleet_xprof_capture_bytes",
            "total bytes the last XProf capture wrote under its "
            "log_dir")
        self._xprof_files = registry.gauge(
            "fleet_xprof_capture_files",
            "files the last XProf capture wrote (trace shards, "
            "xplane protos)")
        self._xprof_seconds = registry.gauge(
            "fleet_xprof_capture_seconds",
            "wall seconds the last XProf capture window spanned")

    # -- measurement ---------------------------------------------------
    def device(self) -> str:
        with self._lock:
            if self._device is None:
                self._device = _device_label()
            return self._device

    @contextlib.contextmanager
    def measure(self, phase: str, every: Optional[int] = None,
                devices=None):
        """Time one dispatch of ``phase`` (1-in-``every`` sampling;
        defaults to the profiler-wide rate).  An armed XProf capture
        forces sampling so the capture window is always timed.

        ``devices`` (ISSUE 17): an iterable of ``platform:id`` labels
        — a MESH-SHARDED dispatch runs on every chip of the replica's
        slice simultaneously, so the one wall-time sample folds into
        EACH listed device's series (per-device phase attribution
        across the slice); None keeps the single default-device
        label."""
        phase = str(phase)
        every = self.sample_every if every is None else max(1, int(every))
        with self._lock:
            n = self._calls.get(phase, 0) + 1
            self._calls[phase] = n
        capturing = self._xprof_participate()
        sampled = capturing or (n % every == 0)
        m = _Measure(sampled)
        t0 = time.perf_counter() if sampled else 0.0
        try:
            yield m
        finally:
            if sampled:
                dt = time.perf_counter() - t0
                for dev in (devices if devices else (None,)):
                    self.observe(phase, dt, device=dev)
            else:
                self._skipped.labels(phase=phase).inc()
            if capturing:
                self._xprof_end()

    def observe(self, phase: str, seconds: float,
                device: Optional[str] = None) -> None:
        """Fold one device-time sample (the ``measure`` sink; also the
        direct entry for sites that time themselves)."""
        self._hist.labels(device=device or self.device(),
                          phase=str(phase)).observe(float(seconds))

    # -- summaries -----------------------------------------------------
    def top_ops(self, k: Optional[int] = None) -> List[dict]:
        """Phases ranked by cumulative device seconds across devices —
        the top-K summary ("which op class owns this device").  Reads
        the SAME histogram family the scrape exposes, so the local
        answer and the fleet answer can never disagree."""
        out = []
        for lv, child in self._hist._items():
            device, phase = lv
            _u, _c, total, count = child.state()
            if not count:
                continue
            out.append({"device": device, "phase": phase,
                        "seconds": total, "samples": count,
                        "p50": child.percentile(0.50),
                        "p99": child.percentile(0.99)})
        out.sort(key=lambda d: d["seconds"], reverse=True)
        return out if k is None else out[:int(k)]

    # -- on-demand XProf capture ---------------------------------------
    def request_xprof(self, log_dir, dispatches: int = 1) -> None:
        """Arm a ``jax.profiler`` trace capture around the next
        ``dispatches`` measured dispatches (any phase).  Idempotent
        while armed/active: a second request before the first capture
        finishes is ignored (one capture at a time — captures are
        heavyweight by design, which is why they are on-demand while
        the sampling histograms are continuous)."""
        with self._xprof_lock:
            if (self._xprof_dir is not None or self._xprof_starting
                    or self._xprof_t0 is not None):
                log.warning("DeviceProfiler: XProf capture already "
                            "armed/active; ignoring request")
                return
            self._xprof_dir = str(log_dir)
            self._xprof_left = max(1, int(dispatches))

    def xprof_armed(self) -> bool:
        with self._xprof_lock:
            return (self._xprof_dir is not None or self._xprof_starting
                    or self._xprof_t0 is not None)

    def _xprof_participate(self) -> bool:
        """Join the capture window: the FIRST measured dispatch after
        arming claims the start and runs ``start_trace`` OUTSIDE the
        locks (it can take long — other dispatch threads must never
        queue behind it; they simply don't participate until the
        trace is live).  Returns True while this dispatch is inside
        the window — the caller must balance with ``_xprof_end``."""
        with self._xprof_lock:
            if self._xprof_t0 is not None:
                return True               # window already open
            if self._xprof_dir is None or self._xprof_starting:
                return False
            log_dir = self._xprof_dir     # claim the start
            self._xprof_dir = None
            self._xprof_starting = True
        try:
            import jax
            jax.profiler.start_trace(log_dir)
        except Exception:
            log.exception("DeviceProfiler: start_trace failed; "
                          "disarming the capture")
            with self._xprof_lock:
                self._xprof_starting = False
            return False
        with self._xprof_lock:
            self._xprof_starting = False
            self._xprof_active_dir = log_dir
            self._xprof_t0 = time.perf_counter()
        return True

    def _xprof_end(self) -> None:
        with self._xprof_lock:
            if self._xprof_t0 is None:
                return
            self._xprof_left -= 1
            if self._xprof_left > 0:
                return
            log_dir = self._xprof_active_dir
            t0 = self._xprof_t0
            self._xprof_active_dir = None
            self._xprof_t0 = None
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:
            log.exception("DeviceProfiler: stop_trace failed")
            return
        self._summarize_capture(log_dir, time.perf_counter() - t0)

    def _summarize_capture(self, log_dir: str, wall_s: float) -> None:
        """The part of a capture that beacons: walk the trace dir and
        publish size/shape gauges (the raw artifact stays local)."""
        n_files = 0
        n_bytes = 0
        for root, _dirs, files in os.walk(log_dir):
            for name in files:
                n_files += 1
                try:
                    n_bytes += os.path.getsize(os.path.join(root, name))
                except OSError:
                    pass
        self._xprof_captures.inc()
        self._xprof_bytes.set(n_bytes)
        self._xprof_files.set(n_files)
        self._xprof_seconds.set(wall_s)
        log.info("DeviceProfiler: XProf capture -> %s (%d files, %d "
                 "bytes, %.3gs window)", log_dir, n_files, n_bytes,
                 wall_s)
