"""Lightweight nestable span tracer with Chrome-trace jsonl export.

Spans are host-side wall-time intervals (``with tracer.span("train/step")``)
recorded as Chrome Trace Event Format complete events (``"ph": "X"``) —
the schema ``about://tracing`` / Perfetto / ``chrome://tracing`` load
directly.  Nesting needs no explicit parent pointers: the viewers nest
same-thread events by timestamp containment, which a ``with``-stack
guarantees.  For per-op DEVICE timelines use ``ui.ProfilerListener``
(XProf); this tracer answers the host-side question XProf doesn't —
where Python time goes between program launches (data wait, dispatch,
queue drain, serve batching).

Beyond the ``with``-scoped form there are TRACKED spans
(:meth:`SpanTracer.begin` -> :class:`Span`), the request-tracing
primitive: a span opened on one thread may be ENDED on any other —
a serving request's decode phase opens on the scheduler thread and
closes on whichever thread retires the request (a watchdog-recovery
thread included).  The pre-tracked design orphaned exactly that case:
a span whose closing edge ran on a different thread was simply never
flushed, so every watchdog-recovered request lost its trace.  Tracked
spans also carry an optional OWNER binding (``bound=True``): a bound
span dies with its opening thread, and ``end_owned_by(tid)`` flushes
all of a superseded thread's bound spans (close-on-owner-death) — how
a hung decode dispatch's tick span still reaches the trace file, with
an ``error`` arg naming the recovery instead of vanishing.

Request-scoped tracing rides on one convention: spans that belong to a
request carry ``trace=<id>`` in their args (the id is minted at
``ServingFleet.submit`` and flows through every component that touches
the request).  ``events_for_trace(id)`` / ``export_chrome_trace(path,
trace_id=id)`` then emit ONE cross-component tree per request.

CROSS-WORKER traces (ISSUE 13): every closed event carries a
process-monotonic ``seq`` (the beacon-dedup key) and a wall-clock
``wall`` stamp (the only cross-host-comparable time — ``ts`` is
relative to each tracer's own ``perf_counter`` origin and MUST NOT be
compared across processes).  :meth:`SpanTracer.trace_events` is the
beacon tap — the trace-tagged tail ``telemetry.MetricsBeacon`` ships
beside the metric snapshot — and :class:`FleetTraceStore` is the
aggregator-side store that dedupes fragments by ``(host, trace, pid, seq)`` and
stitches N hosts' fragments into ONE submit->retire tree per trace id
(containment nesting within a host, wall-clock ordering across
hosts, explicit orphan policy for fragments whose root never arrived).

Thread-safe: the event buffer is a bounded ``deque`` (appends are
atomic), the tracked-span table mutates only under ``self._lock``,
each span records its opening thread's id, and a long-lived serving
process can't grow either without end.
"""
from __future__ import annotations

import collections
import contextlib
import itertools
import json
import logging
import os
import threading
import time
from typing import Dict, Iterator, List, Optional

log = logging.getLogger("deeplearning4j_tpu")


class Span:
    """One tracked in-flight span (see :meth:`SpanTracer.begin`).

    ``end()`` is idempotent and callable from ANY thread — the closing
    edge of a request phase legitimately runs on a different thread
    than the opening edge (scheduler vs. watchdog-recovery).  All
    bookkeeping lives in the tracer; the span itself is an immutable
    handle."""

    __slots__ = ("name", "args", "ts", "tid", "bound", "owner",
                 "_tracer", "_sid")

    def __init__(self, tracer, sid, name, ts, tid, bound, owner, args):
        self._tracer = tracer
        self._sid = sid
        self.name = name
        self.ts = ts
        self.tid = tid
        self.bound = bound
        self.owner = owner
        self.args = args

    def end(self, **extra) -> None:
        """Record the complete event (first call wins; later calls and
        calls on a no-op span are ignored)."""
        if self._tracer is not None:
            self._tracer._end(self._sid, extra)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, etype, exc, tb) -> bool:
        self.end(**({"error": etype.__name__} if etype else {}))
        return False


#: the disabled-tracer span: every method is a no-op
_NULL_SPAN = Span(None, -1, "", 0.0, 0, False, None, {})


class SpanTracer:
    """Record nested timed spans; export them for trace viewers.

    >>> tracer = SpanTracer()
    >>> with tracer.span("serve/batch", size=4):
    ...     with tracer.span("serve/forward"):
    ...         pass
    >>> sp = tracer.begin("request/decode", trace="r-1")   # tracked
    >>> sp.end(tokens=64)                                  # any thread
    >>> tracer.export_jsonl("trace.jsonl")
    """

    def __init__(self, max_events: int = 65536, enabled: bool = True):
        self.enabled = enabled
        self._events: collections.deque = collections.deque(
            maxlen=max_events)
        self._t0 = time.perf_counter_ns()
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self._eseq = itertools.count()   # closed-EVENT seq (beacon dedup)
        self._open: Dict[int, Span] = {}

    def _now_us(self) -> float:
        return (time.perf_counter_ns() - self._t0) / 1e3

    # -- tracked spans --------------------------------------------------
    def begin(self, name: str, bound: bool = False, owner=None,
              **args) -> Span:
        """Open a tracked span.  ``bound=True`` ties its lifetime to
        an OWNER: :meth:`end_owned_by` flushes it when that owner is
        superseded (hung dispatch, watchdog takeover).  ``owner``
        defaults to the opening thread's ident, but long-lived
        schedulers should pass a per-INCARNATION token (e.g. ``(id(
        self), epoch)``) — CPython recycles thread idents of dead
        threads, so a raw tid can collide with an unrelated thread
        started after the owner died.  Unbound spans outlive threads
        — a request phase ends wherever the request retires."""
        if not self.enabled:
            return _NULL_SPAN
        if bound and owner is None:
            owner = threading.get_ident()
        sp = Span(self, next(self._seq), name, self._now_us(),
                  threading.get_ident(), bound, owner, dict(args))
        with self._lock:
            self._open[sp._sid] = sp
        return sp

    def _end(self, sid: int, extra: Dict) -> None:
        with self._lock:
            sp = self._open.pop(sid, None)
        if sp is None:
            return                       # already ended (idempotent)
        args = dict(sp.args, **extra) if extra else sp.args
        # seq is the cross-worker dedup key (a beacon may deliver the
        # same tail any number of times); wall is the ONLY time base
        # comparable across hosts — ts is relative to this tracer's
        # private perf_counter origin
        self._events.append({
            "name": sp.name, "ph": "X", "ts": sp.ts,
            "dur": self._now_us() - sp.ts,
            "pid": os.getpid(), "tid": sp.tid, "args": args,
            "seq": next(self._eseq), "wall": time.time(),
        })

    def end_owned_by(self, owner, **extra) -> int:
        """Close-on-owner-death: end every OPEN BOUND span whose
        ``owner`` matches (watchdog recovery calls this with the
        superseded scheduler's incarnation token so its in-flight
        tick span flushes instead of orphaning).  Unbound (request)
        spans are left open — the recovered request's retire path
        still closes them into a complete trace.  Returns the number
        flushed."""
        if owner is None:
            return 0
        with self._lock:
            victims = [s._sid for s in self._open.values()
                       if s.bound and s.owner == owner]
        for sid in victims:
            self._end(sid, extra)
        return len(victims)

    def open_spans(self) -> List[Span]:
        """The currently-open tracked spans (tests / leak checks)."""
        with self._lock:
            return list(self._open.values())

    # -- scoped spans ---------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, owner=None, **args) -> Iterator[None]:
        """Time a block; records one complete ("X") event on exit.
        Exceptions propagate; the span still records with an
        ``"error"`` arg so a trace shows where a request died.
        Implemented over a BOUND tracked span (``owner`` as in
        :meth:`begin`), so a thread that hangs inside the block can
        still have the span flushed by :meth:`end_owned_by`."""
        if not self.enabled:
            yield
            return
        sp = self.begin(name, bound=True, owner=owner, **args)
        try:
            yield
        except BaseException as e:
            sp.end(error=type(e).__name__)
            raise
        finally:
            sp.end()

    def _snapshot_events(self) -> List[Dict]:
        """Copy the event buffer safely: deque APPENDS are atomic but
        ITERATION over a deque mutated mid-walk raises RuntimeError —
        and the callers here include the beacon thread, which must
        never die because a scheduler closed a span mid-copy."""
        for _ in range(8):
            try:
                return list(self._events)
            except RuntimeError:
                continue             # mutated mid-iteration: retry
        # pathological churn: index-walk instead — indexing a deque
        # never raises the mutation error (worst case a rotated entry
        # repeats or skips, which the seq-keyed consumers tolerate)
        out: List[Dict] = []
        for i in range(len(self._events)):
            try:
                out.append(self._events[i])
            except IndexError:
                break
        return out

    def events(self) -> List[Dict]:
        return self._snapshot_events()

    def events_for_trace(self, trace_id: str) -> List[Dict]:
        """Every recorded event carrying ``trace=<trace_id>`` in its
        args — ONE request's cross-component tree, whatever threads
        and components its phases ran on."""
        return [ev for ev in self._snapshot_events()
                if ev["args"].get("trace") == trace_id]

    def trace_events(self, limit: Optional[int] = None) -> List[Dict]:
        """The beacon tap: every CLOSED event carrying a ``trace`` arg
        (request-scoped spans only — ``serve/tick`` and friends stay
        host-local), most recent ``limit``.  Spans flushed by
        :meth:`end_owned_by` (watchdog recovery) go through the same
        ``_end`` path, so a recovered request's fragments reach the
        beacon stream exactly like normally-retired ones.  Duplicate
        delivery is the receiver's problem: ``FleetTraceStore``
        dedupes on ``(host, trace, pid, seq)``."""
        evs = [ev for ev in self._snapshot_events()
               if "trace" in ev["args"]]
        if limit is not None and len(evs) > limit:
            evs = evs[-int(limit):]
        return evs

    def clear(self) -> None:
        self._events.clear()
        with self._lock:
            self._open.clear()

    def export_jsonl(self, path: str,
                     trace_id: Optional[str] = None) -> str:
        """One Chrome trace event per line (``trace_id`` filters to one
        request's tree).  Perfetto/catapult accept newline-delimited
        event objects; ``export_chrome_trace`` writes the strict
        ``{"traceEvents": [...]}`` envelope instead."""
        d = os.path.dirname(str(path))
        if d:
            os.makedirs(d, exist_ok=True)
        evs = (self.events() if trace_id is None
               else self.events_for_trace(trace_id))
        with open(path, "w") as f:
            for ev in evs:
                f.write(json.dumps(ev) + "\n")
        return str(path)

    def export_chrome_trace(self, path: str,
                            trace_id: Optional[str] = None) -> str:
        d = os.path.dirname(str(path))
        if d:
            os.makedirs(d, exist_ok=True)
        evs = (self.events() if trace_id is None
               else self.events_for_trace(trace_id))
        with open(path, "w") as f:
            json.dump({"traceEvents": evs,
                       "displayTimeUnit": "ms"}, f)
        return str(path)


#: containment-nesting slack, in the tracer's microsecond time base —
#: a child's recorded bounds can exceed its parent's by scheduler
#: jitter between the two ``_end`` timestamps
_NEST_EPS_US = 1e-3


class FleetTraceStore:
    """Aggregator-side cross-worker trace store (ISSUE 13).

    N hosts beacon their closed request-scoped spans
    (:meth:`SpanTracer.trace_events`); this store dedupes and groups
    them by trace id, and :meth:`tree` stitches the per-host fragments
    into ONE submit->retire tree:

    * **dedup** — the push transport may deliver any tail any number
      of times; events are keyed ``(host, trace, pid, seq)`` and ingested once (pid = publisher incarnation: a restarted worker re-serving a trace is never deduped against its predecessor);
    * **nesting** — WITHIN a host, spans nest by interval containment
      in that host's private ``ts`` base (the ``with``-stack
      guarantee the Chrome viewers rely on, reconstructed);
    * **cross-host merge** — a fragment from another host (a
      migrated/handed-off request's local residence, rooted at its
      ``request/handoff`` span) attaches under the origin host's
      ``request`` root, ordered by the wall clock — NEVER by ``ts``,
      which is not comparable across processes;
    * **orphan policy** — fragments whose trace has no ``request``
      root yet (the root host's beacon lost, late, or never coming)
      stay queryable as ``orphans`` with ``complete=False``; the root
      arriving later (out-of-order delivery) promotes them into the
      tree on the next :meth:`tree` call — assembly is pure and
      re-runs per query, so arrival order can never corrupt a trace.

    Bounded THREE ways (an aggregator outlives every request it has
    ever seen): at most ``max_spans`` spans per trace, at most
    ``max_traces`` traces total (oldest-insertion evicted), and —
    ISSUE 15 — at most ``max_retired`` RETIRED traces (a trace whose
    ``request`` root arrived with a terminal ``outcome`` arg is
    complete; under sustained traffic these are the unbounded
    population, and they evict LRU BY RETIRE TIME well before the
    capacity bound would thrash live traces).  Every eviction counts
    into ``fleet_trace_store_evicted_total`` on the fleet scrape."""

    #: the root-span name ``ServingFleet.submit`` mints
    ROOT = "request"
    #: the local root of a fragment that CONTINUES another host's trace
    HANDOFF = "request/handoff"

    def __init__(self, max_traces: int = 512, max_spans: int = 512,
                 max_retired: Optional[int] = None):
        self.max_traces = int(max_traces)
        self.max_spans = int(max_spans)
        # default: half the capacity — retired traces must never be
        # able to crowd out the live ones the capacity bound protects
        self.max_retired = (int(max_retired) if max_retired is not None
                            else max(1, self.max_traces // 2))
        if not 0 < self.max_retired <= self.max_traces:
            raise ValueError(
                f"need 0 < max_retired ({self.max_retired}) <= "
                f"max_traces ({self.max_traces})")
        self._lock = threading.Lock()
        # trace -> retire wall time, in RETIREMENT-ARRIVAL order (the
        # LRU the retention cap evicts from); eviction tally for the
        # fleet scrape counter
        self._retired: "collections.OrderedDict[str, float]" = \
            collections.OrderedDict()
        self._evicted = 0
        # host -> trace -> {seq}: keyed per trace so evicting a trace
        # prunes its dedup state too — the store stays bounded however
        # long the aggregator lives (an evicted trace's tail still in
        # some beacon may re-ingest as a fresh trace; bounded churn,
        # never unbounded growth)
        self._seen: Dict[str, Dict[str, set]] = {}
        self._traces: "collections.OrderedDict[str, List[Dict]]" = \
            collections.OrderedDict()

    # -- ingest --------------------------------------------------------
    def ingest(self, host: str, events) -> int:
        """Fold one host's trace-event tail in; returns how many were
        NEW (idempotent under duplicate beacon delivery)."""
        host = str(host)
        n_new = 0
        with self._lock:
            seen = self._seen.setdefault(host, {})
            for ev in events or ():
                trace = ev.get("args", {}).get("trace")
                if trace is None:
                    continue
                # seqs are deduped per (host, trace, pid): seq spaces
                # are per-TRACER, and a restarted worker — new pid,
                # possibly the SAME stable host name, possibly
                # re-serving the SAME handed-off trace — restarts at
                # 0; its fragments must not be deduped against a
                # predecessor incarnation's seqs.  (Two tracers in
                # ONE process sharing a trace id still collide —
                # a process has one default tracer, so that shape
                # only arises in synthetic tests.)
                seq = ev.get("seq")
                if seq is None:       # pre-seq publisher: best-effort
                    seq = (ev.get("name"), ev.get("ts"), ev.get("tid"))
                key = (ev.get("pid"), seq)
                tseen = seen.setdefault(trace, set())
                if key in tseen:
                    continue
                tseen.add(key)
                spans = self._traces.get(trace)
                if spans is None:
                    spans = self._traces[trace] = []
                    while len(self._traces) > self.max_traces:
                        old = next(iter(self._traces))
                        self._evict_locked(old)
                if len(spans) < self.max_spans:
                    spans.append(dict(ev, host=host))
                    n_new += 1
                if ev.get("name") == self.ROOT \
                        and "outcome" in ev.get("args", {}):
                    # the submit-minted root closed with a terminal
                    # outcome: the trace is RETIRED — enter (or
                    # refresh, under duplicate delivery) the
                    # retention LRU and enforce its cap
                    self._retired[trace] = float(ev.get("wall", 0.0))
                    self._retired.move_to_end(trace)
                    while len(self._retired) > self.max_retired:
                        old = next(iter(self._retired))
                        self._evict_locked(old)
        return n_new

    def _evict_locked(self, trace: str) -> None:
        self._traces.pop(trace, None)
        self._retired.pop(trace, None)
        for hseen in self._seen.values():
            hseen.pop(trace, None)
        self._evicted += 1
        log.debug("FleetTraceStore evicted trace %s", trace)

    # -- query ---------------------------------------------------------
    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def events(self, trace_id: str) -> List[Dict]:
        with self._lock:
            return [dict(ev) for ev in self._traces.get(trace_id, ())]

    def summary(self) -> Dict:
        """Store-level stats for the fleet scrape: trace/span counts
        and how many traces are ROOTED (their ``request`` root has
        arrived).  Deliberately weaker than :meth:`tree`'s
        ``complete`` — which additionally demands zero orphan
        fragments and is O(spans^2) per trace, too heavy to recompute
        for every trace on every scrape."""
        with self._lock:
            traces = {t: list(evs) for t, evs in self._traces.items()}
            retired = len(self._retired)
            evicted = self._evicted
        rooted = sum(
            1 for evs in traces.values()
            if any(ev["name"] == self.ROOT for ev in evs))
        return {"traces": len(traces), "rooted": rooted,
                "spans": sum(len(evs) for evs in traces.values()),
                "retired": retired, "evicted": evicted}

    def tree(self, trace_id: str) -> Dict:
        """Stitch one trace's fragments into a submit->retire tree.

        Returns ``{"trace", "root", "orphans", "hosts", "spans",
        "complete"}``; ``root`` is None (and every fragment an
        orphan) while the ``request`` root has not arrived — the
        missing-parent policy: orphans are reported, never guessed
        into a fabricated hierarchy."""
        evs = self.events(trace_id)
        hosts = sorted({ev["host"] for ev in evs})
        # per-host containment forests (ts bases are host-private)
        top_by_host: Dict[str, List[Dict]] = {}
        for host in hosts:
            top_by_host[host] = _containment_forest(
                [ev for ev in evs if ev["host"] == host])
        roots = [n for tops in top_by_host.values() for n in tops
                 if n["name"] == self.ROOT]
        if len(roots) != 1:
            orphans = sorted(
                (n for tops in top_by_host.values() for n in tops),
                key=lambda n: n["wall"])
            return {"trace": trace_id, "root": None, "orphans": orphans,
                    "hosts": hosts, "spans": len(evs),
                    "complete": False}
        root = roots[0]
        orphans = []
        for host, tops in top_by_host.items():
            for node in tops:
                if node is root:
                    continue
                if host == root["host"]:
                    # same host but outside the root's interval: a
                    # fragment the root legitimately cannot own
                    orphans.append(node)
                else:
                    root["children"].append(node)
        root["children"].sort(key=lambda n: n["wall"])
        return {"trace": trace_id, "root": root, "orphans": orphans,
                "hosts": hosts, "spans": len(evs),
                "complete": not orphans}

    def render_json(self, trace_id: Optional[str] = None) -> str:
        """The ``/traces`` endpoint body: the store summary + trace
        ids, or ONE stitched tree when ``trace_id`` names it."""
        if trace_id is not None:
            return json.dumps(self.tree(trace_id))
        doc = dict(self.summary())
        doc["trace_ids"] = self.trace_ids()
        return json.dumps(doc)


def _containment_forest(evs: List[Dict]) -> List[Dict]:
    """Nest one host's events by interval containment; returns the
    top-level nodes.  Parent = the SMALLEST enclosing interval — the
    ``with``-stack structure the spans were recorded under."""
    nodes = [{"name": ev["name"], "host": ev["host"], "ts": ev["ts"],
              "dur": ev["dur"], "wall": ev.get("wall", 0.0),
              "args": dict(ev.get("args", {})), "children": []}
             for ev in evs]
    for i, node in enumerate(nodes):
        parent = None
        for j, cand in enumerate(nodes):
            if j == i:
                continue
            encloses = (cand["ts"] - _NEST_EPS_US <= node["ts"]
                        and node["ts"] + node["dur"]
                        <= cand["ts"] + cand["dur"] + _NEST_EPS_US)
            # identical intervals (duration tie): earlier-ingested
            # wins as parent — a symmetric rule here would cycle
            bigger = (cand["dur"] > node["dur"]
                      or (cand["dur"] == node["dur"] and j < i))
            if encloses and bigger:
                if parent is None or cand["dur"] < parent["dur"]:
                    parent = cand
        node["_parent"] = parent
    tops: List[Dict] = []
    for node in nodes:
        parent = node.pop("_parent")
        if parent is None:
            tops.append(node)
        else:
            parent["children"].append(node)
    for node in nodes:
        node["children"].sort(key=lambda n: n["ts"])
    tops.sort(key=lambda n: n["ts"])
    return tops
