"""Lightweight nestable span tracer with Chrome-trace jsonl export.

Spans are host-side wall-time intervals (``with tracer.span("train/step")``)
recorded as Chrome Trace Event Format complete events (``"ph": "X"``) —
the schema ``about://tracing`` / Perfetto / ``chrome://tracing`` load
directly.  Nesting needs no explicit parent pointers: the viewers nest
same-thread events by timestamp containment, which a ``with``-stack
guarantees.  For per-op DEVICE timelines use ``ui.ProfilerListener``
(XProf); this tracer answers the host-side question XProf doesn't —
where Python time goes between program launches (data wait, dispatch,
queue drain, serve batching).

Thread-safe: the event buffer is a bounded ``deque`` (appends are
atomic), each span carries the recording thread's id, and a long-lived
serving process can't grow the buffer without end.
"""
from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Dict, Iterator, List, Optional


class SpanTracer:
    """Record nested timed spans; export them for trace viewers.

    >>> tracer = SpanTracer()
    >>> with tracer.span("serve/batch", size=4):
    ...     with tracer.span("serve/forward"):
    ...         pass
    >>> tracer.export_jsonl("trace.jsonl")
    """

    def __init__(self, max_events: int = 65536, enabled: bool = True):
        self.enabled = enabled
        self._events: collections.deque = collections.deque(
            maxlen=max_events)
        self._t0 = time.perf_counter_ns()

    def _now_us(self) -> float:
        return (time.perf_counter_ns() - self._t0) / 1e3

    @contextlib.contextmanager
    def span(self, name: str, **args) -> Iterator[None]:
        """Time a block; records one complete ("X") event on exit.
        Exceptions propagate; the span still records with an
        ``"error"`` arg so a trace shows where a request died."""
        if not self.enabled:
            yield
            return
        start = self._now_us()
        try:
            yield
        except BaseException as e:
            args = dict(args, error=type(e).__name__)
            raise
        finally:
            self._events.append({
                "name": name, "ph": "X", "ts": start,
                "dur": self._now_us() - start,
                "pid": os.getpid(), "tid": threading.get_ident(),
                "args": args,
            })

    def events(self) -> List[Dict]:
        return list(self._events)

    def clear(self) -> None:
        self._events.clear()

    def export_jsonl(self, path: str) -> str:
        """One Chrome trace event per line.  Perfetto/catapult accept
        newline-delimited event objects; ``export_chrome_trace`` writes
        the strict ``{"traceEvents": [...]}`` envelope instead."""
        d = os.path.dirname(str(path))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            for ev in self.events():
                f.write(json.dumps(ev) + "\n")
        return str(path)

    def export_chrome_trace(self, path: str) -> str:
        d = os.path.dirname(str(path))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump({"traceEvents": self.events(),
                       "displayTimeUnit": "ms"}, f)
        return str(path)
