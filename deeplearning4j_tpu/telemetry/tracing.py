"""Lightweight nestable span tracer with Chrome-trace jsonl export.

Spans are host-side wall-time intervals (``with tracer.span("train/step")``)
recorded as Chrome Trace Event Format complete events (``"ph": "X"``) —
the schema ``about://tracing`` / Perfetto / ``chrome://tracing`` load
directly.  Nesting needs no explicit parent pointers: the viewers nest
same-thread events by timestamp containment, which a ``with``-stack
guarantees.  For per-op DEVICE timelines use ``ui.ProfilerListener``
(XProf); this tracer answers the host-side question XProf doesn't —
where Python time goes between program launches (data wait, dispatch,
queue drain, serve batching).

Beyond the ``with``-scoped form there are TRACKED spans
(:meth:`SpanTracer.begin` -> :class:`Span`), the request-tracing
primitive: a span opened on one thread may be ENDED on any other —
a serving request's decode phase opens on the scheduler thread and
closes on whichever thread retires the request (a watchdog-recovery
thread included).  The pre-tracked design orphaned exactly that case:
a span whose closing edge ran on a different thread was simply never
flushed, so every watchdog-recovered request lost its trace.  Tracked
spans also carry an optional OWNER binding (``bound=True``): a bound
span dies with its opening thread, and ``end_owned_by(tid)`` flushes
all of a superseded thread's bound spans (close-on-owner-death) — how
a hung decode dispatch's tick span still reaches the trace file, with
an ``error`` arg naming the recovery instead of vanishing.

Request-scoped tracing rides on one convention: spans that belong to a
request carry ``trace=<id>`` in their args (the id is minted at
``ServingFleet.submit`` and flows through every component that touches
the request).  ``events_for_trace(id)`` / ``export_chrome_trace(path,
trace_id=id)`` then emit ONE cross-component tree per request.

Thread-safe: the event buffer is a bounded ``deque`` (appends are
atomic), the tracked-span table mutates only under ``self._lock``,
each span records its opening thread's id, and a long-lived serving
process can't grow either without end.
"""
from __future__ import annotations

import collections
import contextlib
import itertools
import json
import os
import threading
import time
from typing import Dict, Iterator, List, Optional


class Span:
    """One tracked in-flight span (see :meth:`SpanTracer.begin`).

    ``end()`` is idempotent and callable from ANY thread — the closing
    edge of a request phase legitimately runs on a different thread
    than the opening edge (scheduler vs. watchdog-recovery).  All
    bookkeeping lives in the tracer; the span itself is an immutable
    handle."""

    __slots__ = ("name", "args", "ts", "tid", "bound", "owner",
                 "_tracer", "_sid")

    def __init__(self, tracer, sid, name, ts, tid, bound, owner, args):
        self._tracer = tracer
        self._sid = sid
        self.name = name
        self.ts = ts
        self.tid = tid
        self.bound = bound
        self.owner = owner
        self.args = args

    def end(self, **extra) -> None:
        """Record the complete event (first call wins; later calls and
        calls on a no-op span are ignored)."""
        if self._tracer is not None:
            self._tracer._end(self._sid, extra)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, etype, exc, tb) -> bool:
        self.end(**({"error": etype.__name__} if etype else {}))
        return False


#: the disabled-tracer span: every method is a no-op
_NULL_SPAN = Span(None, -1, "", 0.0, 0, False, None, {})


class SpanTracer:
    """Record nested timed spans; export them for trace viewers.

    >>> tracer = SpanTracer()
    >>> with tracer.span("serve/batch", size=4):
    ...     with tracer.span("serve/forward"):
    ...         pass
    >>> sp = tracer.begin("request/decode", trace="r-1")   # tracked
    >>> sp.end(tokens=64)                                  # any thread
    >>> tracer.export_jsonl("trace.jsonl")
    """

    def __init__(self, max_events: int = 65536, enabled: bool = True):
        self.enabled = enabled
        self._events: collections.deque = collections.deque(
            maxlen=max_events)
        self._t0 = time.perf_counter_ns()
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self._open: Dict[int, Span] = {}

    def _now_us(self) -> float:
        return (time.perf_counter_ns() - self._t0) / 1e3

    # -- tracked spans --------------------------------------------------
    def begin(self, name: str, bound: bool = False, owner=None,
              **args) -> Span:
        """Open a tracked span.  ``bound=True`` ties its lifetime to
        an OWNER: :meth:`end_owned_by` flushes it when that owner is
        superseded (hung dispatch, watchdog takeover).  ``owner``
        defaults to the opening thread's ident, but long-lived
        schedulers should pass a per-INCARNATION token (e.g. ``(id(
        self), epoch)``) — CPython recycles thread idents of dead
        threads, so a raw tid can collide with an unrelated thread
        started after the owner died.  Unbound spans outlive threads
        — a request phase ends wherever the request retires."""
        if not self.enabled:
            return _NULL_SPAN
        if bound and owner is None:
            owner = threading.get_ident()
        sp = Span(self, next(self._seq), name, self._now_us(),
                  threading.get_ident(), bound, owner, dict(args))
        with self._lock:
            self._open[sp._sid] = sp
        return sp

    def _end(self, sid: int, extra: Dict) -> None:
        with self._lock:
            sp = self._open.pop(sid, None)
        if sp is None:
            return                       # already ended (idempotent)
        args = dict(sp.args, **extra) if extra else sp.args
        self._events.append({
            "name": sp.name, "ph": "X", "ts": sp.ts,
            "dur": self._now_us() - sp.ts,
            "pid": os.getpid(), "tid": sp.tid, "args": args,
        })

    def end_owned_by(self, owner, **extra) -> int:
        """Close-on-owner-death: end every OPEN BOUND span whose
        ``owner`` matches (watchdog recovery calls this with the
        superseded scheduler's incarnation token so its in-flight
        tick span flushes instead of orphaning).  Unbound (request)
        spans are left open — the recovered request's retire path
        still closes them into a complete trace.  Returns the number
        flushed."""
        if owner is None:
            return 0
        with self._lock:
            victims = [s._sid for s in self._open.values()
                       if s.bound and s.owner == owner]
        for sid in victims:
            self._end(sid, extra)
        return len(victims)

    def open_spans(self) -> List[Span]:
        """The currently-open tracked spans (tests / leak checks)."""
        with self._lock:
            return list(self._open.values())

    # -- scoped spans ---------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, owner=None, **args) -> Iterator[None]:
        """Time a block; records one complete ("X") event on exit.
        Exceptions propagate; the span still records with an
        ``"error"`` arg so a trace shows where a request died.
        Implemented over a BOUND tracked span (``owner`` as in
        :meth:`begin`), so a thread that hangs inside the block can
        still have the span flushed by :meth:`end_owned_by`."""
        if not self.enabled:
            yield
            return
        sp = self.begin(name, bound=True, owner=owner, **args)
        try:
            yield
        except BaseException as e:
            sp.end(error=type(e).__name__)
            raise
        finally:
            sp.end()

    def events(self) -> List[Dict]:
        return list(self._events)

    def events_for_trace(self, trace_id: str) -> List[Dict]:
        """Every recorded event carrying ``trace=<trace_id>`` in its
        args — ONE request's cross-component tree, whatever threads
        and components its phases ran on."""
        return [ev for ev in self._events
                if ev["args"].get("trace") == trace_id]

    def clear(self) -> None:
        self._events.clear()
        with self._lock:
            self._open.clear()

    def export_jsonl(self, path: str,
                     trace_id: Optional[str] = None) -> str:
        """One Chrome trace event per line (``trace_id`` filters to one
        request's tree).  Perfetto/catapult accept newline-delimited
        event objects; ``export_chrome_trace`` writes the strict
        ``{"traceEvents": [...]}`` envelope instead."""
        d = os.path.dirname(str(path))
        if d:
            os.makedirs(d, exist_ok=True)
        evs = (self.events() if trace_id is None
               else self.events_for_trace(trace_id))
        with open(path, "w") as f:
            for ev in evs:
                f.write(json.dumps(ev) + "\n")
        return str(path)

    def export_chrome_trace(self, path: str,
                            trace_id: Optional[str] = None) -> str:
        d = os.path.dirname(str(path))
        if d:
            os.makedirs(d, exist_ok=True)
        evs = (self.events() if trace_id is None
               else self.events_for_trace(trace_id))
        with open(path, "w") as f:
            json.dump({"traceEvents": evs,
                       "displayTimeUnit": "ms"}, f)
        return str(path)
