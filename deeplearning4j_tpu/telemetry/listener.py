"""TelemetryListener — one-line bridge from the ``TrainingListener``
bus into the metrics registry.

``net.set_listeners(TelemetryListener(...))`` gives any existing fit
loop the registry series (loss gauge, step-time histogram, examples/s,
MFU) without touching its code; the structural fit-loop metrics
(data-wait vs step dispatch, iteration/epoch counters) are emitted by
``optimize.fit_loop`` itself and fire even without a listener.
"""
from __future__ import annotations

import time
from typing import Optional

from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

# The MFU denominator default — TPU v5e bf16 peak, matching bench.py.
V5E_PEAK_FLOPS = 197e12


class TelemetryListener(TrainingListener):
    """Stream per-iteration training telemetry into a registry.

    ``flops_per_example`` (fwd+bwd FLOPs for ONE example — e.g.
    ``zoo.Bert.flops_per_token_train() * seq_len``) turns measured
    examples/sec into the ``mfu`` gauge against ``peak_flops``; without
    it the gauge is left untouched (never a made-up number).

    ``storage`` (a ``ui.StatsStorage``) receives one registry snapshot
    record per epoch (``{"type": "telemetry_snapshot", ...}``) — the
    jsonl path into ``ui.render_report``'s telemetry table."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 storage=None, flops_per_example: Optional[float] = None,
                 peak_flops: float = V5E_PEAK_FLOPS):
        if registry is None:
            from deeplearning4j_tpu import telemetry
            registry = telemetry.get_registry()
        self.registry = registry
        self.storage = storage
        self.flops_per_example = flops_per_example
        self.peak_flops = float(peak_flops)
        self._loss = registry.gauge(
            "train_loss", "last training loss (host-read)")
        self._ex_per_sec = registry.gauge(
            "train_examples_per_sec", "examples/sec over the last iteration")
        self._mfu = registry.gauge(
            "mfu", "model FLOPs utilization vs peak_flops (needs "
            "flops_per_example)")
        self._step_s = registry.histogram(
            "train_step_seconds",
            "wall time between iteration_done events")
        self._last_t: Optional[float] = None

    def iteration_done(self, model, iteration, epoch, score):
        now = time.perf_counter()
        self._loss.set(float(score))
        if self._last_t is not None:
            dt = now - self._last_t
            self._step_s.observe(dt)
            bs = int(getattr(model, "last_batch_size", 0) or 0)
            if bs and dt > 0:
                eps = bs / dt
                self._ex_per_sec.set(eps)
                if self.flops_per_example:
                    self._mfu.set(eps * self.flops_per_example
                                  / self.peak_flops)
        self._last_t = now

    def on_epoch_end(self, model, epoch):
        if self.storage is not None:
            rec = {"type": "telemetry_snapshot", "epoch": epoch}
            rec.update(self.registry.snapshot())
            self.storage.put(rec)
