"""Fleet-wide metric plane: push-style beacons + one aggregated scrape.

Every series the stack emits is host-local — PR 9's router gauges,
PR 10's elastic-resume counters, PR 11's speculative acceptance rates
each live in the registry of the worker that emitted them, so no
controller (human or autoscaler) can see the fleet.  The TensorFlow
system paper and the TPU-generations study both make the point that
cross-worker visibility is the PREREQUISITE for resilience and
utilization at production scale, not an afterthought.  This module is
that plane, in three transport-agnostic pieces:

* **push transport** — :class:`MetricsBeacon`: a daemon thread that
  periodically serializes its registry's ``snapshot()`` into a
  per-host beacon file under ``<shared_dir>/_telemetry/`` with the
  SAME atomic-publish machinery the survivor rendezvous beacons use
  (``resilience.atomic_publish_json`` — a reader sees a previous
  complete snapshot or this one, never a torn write).  Where a
  ``jax.distributed`` mesh exists, :func:`exchange_snapshots` moves
  the same snapshots over a control collective instead of the
  filesystem (one padded-bytes allgather);

* **aggregation** — :class:`FleetRegistry`: merges N hosts' snapshots
  into ONE scrape-able view.  Counters and histograms are folded as
  MONOTONIC DELTAS per host (a worker that restarts mid-window resets
  its totals; a snapshot whose totals DECREASED is treated as a fresh
  epoch and re-counted from zero — never subtracted as a negative
  delta, the bug that silently corrupts count/sum consistency in
  naive merge-by-subtraction), gauges are last-write per host.  The
  built view tags every series ``{host=}``, adds fleet-level rollups
  (``host="fleet"``: counters/histograms summed — merged-bucket
  quantiles fall out of the histogram children — gauges summed, plus
  ``host="fleet_max"`` for peak-style gauges), and STALENESS-MARKS
  hosts whose beacon aged past ``stale_after_s``
  (``fleet_host_up{host=} == 0``; stale gauges leave the rollups,
  monotonic counters stay — a dead host's work happened);

* **exposition** — a :class:`FleetRegistry` quacks like a registry to
  ``telemetry.MetricsServer`` (``render_prometheus()`` refreshes from
  the beacon directory then renders), so the fleet view is one more
  ``/metrics`` endpoint any Prometheus can scrape.

The closed-loop consumer is ``serving.autoscale.Autoscaler``, which
evaluates this aggregated view against SLO targets and drives the
PR 10 ``add_replica``/``remove_replica`` actuators.

ISSUE 13 extends the plane beyond metrics: each beacon ships the
tracer's closed request-scoped spans beside the snapshot
(``SpanTracer.trace_events`` — seq-deduped, so duplicate delivery is
free), and :class:`FleetRegistry` feeds them into a
:class:`~deeplearning4j_tpu.telemetry.tracing.FleetTraceStore` so a
request that crossed hosts (migration, recovery, handoff) is ONE
stitched submit->retire tree queryable from the scrape endpoint
(``/traces``), with ``fleet_trace_store_*`` gauges on the scrape
making the store itself observable.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.telemetry.registry import (MetricsRegistry,
                                                   _escape_label,
                                                   parse_series)
from deeplearning4j_tpu.telemetry.tracing import (FleetTraceStore,
                                                  SpanTracer)

log = logging.getLogger("deeplearning4j_tpu")

#: subdirectory of the shared dir the metric beacons publish into
#: (namespaced beside — never inside — the rendezvous' ``_rendezvous``)
BEACON_DIRNAME = "_telemetry"


def _default_host_id() -> str:
    return f"{os.uname().nodename}-{os.getpid()}"


def _fmt_series(name: str, pairs: Tuple[Tuple[str, str], ...]) -> str:
    """Re-emit a ``(name, ((k, v), ...))`` pair as the quoted series
    grammar ``parse_series`` inverts (escaping included)."""
    if not pairs:
        return name
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in pairs)
    return name + "{" + inner + "}"


def beacon_path(directory, host: str) -> str:
    return os.path.join(str(directory), BEACON_DIRNAME,
                        f"{host}.json")


def resolve_view(source):
    """Resolve a signal source to a readable registry: a
    ``FleetRegistry``-shaped object (callable ``view``) is refreshed
    (when directory-backed) and aggregated; anything else is already
    a registry.  Shared by the fleet-aware readers (autoscaler, SLO
    engine) so the view-resolution protocol has ONE encoding —
    exposition's ``/traces`` handler deliberately refreshes WITHOUT
    building a view (the trace store, not the metric families, is
    its product)."""
    view = getattr(source, "view", None)
    if callable(view):
        if getattr(source, "directory", None) is not None:
            source.refresh()
        return view()
    return source


def rollup_children(fam):
    """The children a fleet-aware signal reader consumes from one
    metric family: against an AGGREGATED view (a ``host`` label is
    present) only the ``host="fleet"`` rollups — per-host series
    would double-count; against a plain process registry, every
    child.  THE one encoding of the rollup convention — the
    autoscaler and the SLO engine both read through it, so a change
    to the scheme cannot desynchronize them."""
    items = fam._items()
    if "host" in fam.labelnames:
        hidx = fam.labelnames.index("host")
        items = [(lv, c) for lv, c in items if lv[hidx] == "fleet"]
    return items


def publish_beacon(directory, host: Optional[str] = None,
                   registry: Optional[MetricsRegistry] = None,
                   snapshot: Optional[dict] = None,
                   trace_events: Optional[list] = None) -> str:
    """Serialize one registry snapshot into this host's beacon file
    (atomic publish).  Returns the beacon path.  The one-shot form of
    what :class:`MetricsBeacon` does on a cadence.  ``trace_events``
    (``SpanTracer.trace_events``) rides in the same document so closed
    request spans reach the aggregator's trace store with the metrics
    — one transport, one atomic publish."""
    from deeplearning4j_tpu.resilience.coordination import (
        atomic_publish_json)
    if host is None:
        host = _default_host_id()
    host = str(host)
    if os.sep in host:
        raise ValueError(f"host {host!r} must be a plain name")
    if snapshot is None:
        if registry is None:
            from deeplearning4j_tpu import telemetry
            registry = telemetry.get_registry()
        snapshot = registry.snapshot()
    path = beacon_path(directory, host)
    doc = {"host": host, "pid": os.getpid(), "t": time.time(),
           "snapshot": snapshot}
    if trace_events is not None:
        doc["traces"] = list(trace_events)
    atomic_publish_json(path, doc)
    return path


class MetricsBeacon:
    """Push this worker's registry to the shared dir every
    ``interval_s`` seconds (daemon thread), plus once at ``close()``
    so the final counter totals always land.

    >>> beacon = MetricsBeacon(shared_dir, host="host000").start()
    >>> ...                       # train / serve; snapshots flow
    >>> beacon.close()            # final publish + stop

    The beacon counts its own publishes
    (``fleet_beacon_publishes_total`` in the SOURCE registry), so the
    transport is visible in the very snapshots it ships."""

    def __init__(self, directory, host: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None,
                 interval_s: float = 2.0,
                 tracer: Optional[SpanTracer] = None,
                 trace_limit: int = 4096, tsdb=None):
        self.directory = str(directory)
        self.host = str(host) if host is not None else _default_host_id()
        if os.sep in self.host:
            raise ValueError(f"host {self.host!r} must be a plain name")
        if registry is None:
            from deeplearning4j_tpu import telemetry
            registry = telemetry.get_registry()
        self.registry = registry
        # trace transport (ISSUE 13): closed request-scoped spans ride
        # every beacon.  Defaults to the process tracer; trace_limit=0
        # turns the trace lane off (metrics-only beacon).
        if tracer is None and trace_limit:
            from deeplearning4j_tpu import telemetry
            tracer = telemetry.get_tracer()
        self.tracer = tracer
        self.trace_limit = int(trace_limit)
        # optional local history (ISSUE 16): with a store attached,
        # every publish also records the source registry into it, so
        # the beacon cadence doubles as the host's history cadence
        self.tsdb = tsdb
        self.interval_s = float(interval_s)
        if self.interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self._publishes = registry.counter(
            "fleet_beacon_publishes_total",
            "metric-beacon snapshots this worker published into the "
            "shared directory (the push transport's own heartbeat)")
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    def publish(self) -> str:
        """One immediate publish (also what the loop calls).

        The trace lane deliberately ships the FULL tagged tail every
        time (bounded by ``trace_limit``), not a since-last-publish
        delta: each publish REPLACES the beacon file, so an aggregator
        that starts late or polls slower than the publish cadence
        would permanently miss any span shipped only incrementally.
        Receivers dedupe by (host, trace, pid, seq), so re-delivery costs
        bytes, never correctness."""
        traces = (self.tracer.trace_events(self.trace_limit)
                  if self.tracer is not None and self.trace_limit
                  else None)
        path = publish_beacon(self.directory, self.host, self.registry,
                              trace_events=traces)
        self._publishes.inc()
        if self.tsdb is not None:
            self.tsdb.record(self.registry)
        return path

    def _publish_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.publish()
            except Exception:    # shared-dir flake, serialization
                # hiccup, tracer churn — the beacon is a host's ONLY
                # window into the fleet view; one bad publish must
                # never silence it permanently
                log.exception("MetricsBeacon publish failed; retrying "
                              "at the next interval")

    def start(self) -> "MetricsBeacon":
        self.publish()           # first beacon lands immediately
        thread = threading.Thread(target=self._publish_loop,
                                  name="dl4j-tpu-metrics-beacon",
                                  daemon=True)
        with self._lock:
            self._thread = thread
        thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
        try:
            self.publish()       # final totals always land
        except OSError:
            log.exception("MetricsBeacon final publish failed")

    def __enter__(self) -> "MetricsBeacon":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class _HostState:
    """One host's fold state (mutated only under the aggregator's
    lock): accumulated monotonic totals, the last RAW snapshot for
    delta/reset detection, gauge last-writes, and liveness."""

    __slots__ = ("counters", "hists", "gauges", "last_counters",
                 "last_hists", "last_seen", "last_t", "resets")

    def __init__(self):
        self.counters: Dict[str, float] = {}
        self.hists: Dict[str, dict] = {}
        self.gauges: Dict[str, float] = {}
        self.last_counters: Dict[str, float] = {}
        self.last_hists: Dict[str, dict] = {}
        self.last_seen = 0.0          # aggregator-clock receive time
        self.last_t = 0.0             # publisher's snapshot timestamp
        self.resets = 0


class FleetRegistry:
    """Merge N workers' snapshots into one scrape-able fleet view.

    >>> fr = FleetRegistry(shared_dir)        # file-beacon transport
    >>> fr.refresh()                          # poll the beacon dir
    >>> body = fr.render_prometheus()         # {host=}-tagged + rollups
    >>> view = fr.view()                      # a real MetricsRegistry
    >>> view.get("fleet_queue_wait_seconds").labels(
    ...     tenant="hot", host="fleet").percentile(0.99)

    ``ingest(host, snapshot)`` is the transport-agnostic entry — the
    directory poll and the collective exchange both end there.
    Counter/histogram RESETS (worker restart mid-window) are detected
    per series: a total that decreased starts a fresh epoch and folds
    in wholesale instead of as a negative delta."""

    def __init__(self, directory=None, stale_after_s: float = 10.0,
                 trace_store: Optional[FleetTraceStore] = None,
                 alerts=None, tsdb=None):
        self.directory = str(directory) if directory is not None else None
        self.stale_after_s = float(stale_after_s)
        self._lock = threading.Lock()
        self._hosts: Dict[str, _HostState] = {}
        # the cross-worker trace store: beacons' trace tails fold in
        # beside the metric snapshots (own lock, own dedup)
        self.traces = (trace_store if trace_store is not None
                       else FleetTraceStore())
        # SLO alert engine (ISSUE 15): attached, it evaluates against
        # every built view — the scrape IS its evaluation cadence —
        # and exports its burn/budget/state families into the view,
        # so /metrics and /alerts answer from the SAME aggregation
        self.alerts = alerts
        # embedded time-series store (ISSUE 16): every built view is
        # recorded — host-tagged series AND the host="fleet" rollups —
        # so /query answers range reads over the aggregation the
        # alerts and the autoscaler actually consumed.  Pass a shared
        # store to pool history with other recorders.
        if tsdb is None:
            from deeplearning4j_tpu.telemetry.tsdb import TimeSeriesStore
            tsdb = TimeSeriesStore()
        self.tsdb = tsdb

    # -- fold ----------------------------------------------------------
    def ingest(self, host: str, snapshot: dict,
               now: Optional[float] = None) -> None:
        """Fold one host's ``MetricsRegistry.snapshot()`` in.  Safe to
        call with the SAME snapshot repeatedly (deltas are zero) and
        with post-restart snapshots (reset detection)."""
        now = time.monotonic() if now is None else float(now)
        host = str(host)
        with self._lock:
            st = self._hosts.get(host)
            if st is None:
                st = self._hosts[host] = _HostState()
            self._fold_counters_locked(st, snapshot.get("counters", {}))
            self._fold_hists_locked(st, snapshot.get("histograms", {}))
            st.gauges = dict(snapshot.get("gauges", {}))
            st.last_seen = now
            st.last_t = float(snapshot.get("timestamp", 0.0))

    def _fold_counters_locked(self, st: _HostState,
                              raw: Dict[str, float]) -> None:
        for series, v in raw.items():
            v = float(v)
            prev = st.last_counters.get(series)
            if prev is None:
                delta = v
            elif v < prev - 1e-9:
                # RESET: the worker restarted mid-window — its totals
                # began again from zero.  Treat the snapshot as a
                # fresh epoch and fold it in wholesale; subtracting
                # would produce a negative delta and silently shrink
                # the fleet total.
                delta = v
                st.resets += 1
            else:
                delta = v - prev
            st.counters[series] = st.counters.get(series, 0.0) + delta
            st.last_counters[series] = v

    def _fold_hists_locked(self, st: _HostState,
                           raw: Dict[str, dict]) -> None:
        for series, h in raw.items():
            buckets = {u: float(c)
                       for u, c in h.get("buckets", {}).items()}
            cur = {"buckets": buckets, "inf": float(h.get("inf", 0)),
                   "sum": float(h.get("sum", 0.0)),
                   "count": float(h.get("count", 0))}
            prev = st.last_hists.get(series)
            acc = st.hists.get(series)
            if acc is None:
                acc = st.hists[series] = {
                    "buckets": {u: 0.0 for u in buckets},
                    "inf": 0.0, "sum": 0.0, "count": 0.0}
            if prev is None or cur["count"] < prev["count"] - 1e-9:
                # first sight, or a reset epoch: fold in wholesale
                # (count going BACKWARD can only mean the worker's
                # histogram began again — bucket-wise subtraction
                # would go negative and desync count vs sum)
                if prev is not None:
                    st.resets += 1
                delta = cur
            else:
                delta = {
                    "buckets": {
                        u: max(0.0, c - prev["buckets"].get(u, 0.0))
                        for u, c in buckets.items()},
                    "inf": max(0.0, cur["inf"] - prev["inf"]),
                    "sum": max(0.0, cur["sum"] - prev["sum"]),
                    "count": cur["count"] - prev["count"]}
            for u, c in delta["buckets"].items():
                acc["buckets"][u] = acc["buckets"].get(u, 0.0) + c
            acc["inf"] += delta["inf"]
            acc["sum"] += delta["sum"]
            acc["count"] += delta["count"]
            st.last_hists[series] = cur

    # -- transports ----------------------------------------------------
    def refresh(self, now: Optional[float] = None) -> List[str]:
        """Poll the beacon directory and ingest every host file;
        returns the hosts seen this pass.  Unreadable/torn files are
        skipped (the atomic publish makes them transient)."""
        if self.directory is None:
            raise ValueError("FleetRegistry was built without a beacon "
                             "directory; feed it via ingest()")
        bdir = os.path.join(self.directory, BEACON_DIRNAME)
        seen: List[str] = []
        try:
            names = sorted(os.listdir(bdir))
        except OSError:
            return seen
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(bdir, name)) as f:
                    doc = json.load(f)
                host = str(doc["host"])
                snap = doc["snapshot"]
            except (OSError, ValueError, KeyError):
                continue          # mid-replace or foreign file
            self.ingest(host, snap, now=now)
            traces = doc.get("traces")
            if traces:
                self.traces.ingest(host, traces)
            seen.append(host)
        return seen

    # -- view ----------------------------------------------------------
    def hosts(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Liveness ledger: ``{host: {stale, age_s, resets}}``."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            return {h: {"stale": (now - st.last_seen
                                  > self.stale_after_s),
                        "age_s": max(0.0, now - st.last_seen),
                        "resets": st.resets}
                    for h, st in self._hosts.items()}

    def view(self, now: Optional[float] = None) -> MetricsRegistry:
        """Build the aggregated registry: every host's series tagged
        ``{host=}``, plus ``host="fleet"`` rollups (counters and
        histograms summed across ALL hosts — monotonic work done is
        never un-counted; gauges summed across LIVE hosts only, with
        ``host="fleet_max"`` as the peak rollup) and the liveness
        meta-series."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            hosts = {h: (st.counters.copy(),
                         {s: {"buckets": a["buckets"].copy(),
                              "inf": a["inf"], "sum": a["sum"],
                              "count": a["count"]}
                          for s, a in st.hists.items()},
                         st.gauges.copy(),
                         now - st.last_seen > self.stale_after_s,
                         now - st.last_seen, st.resets)
                     for h, st in self._hosts.items()}
        view = MetricsRegistry()
        roll_c: Dict[str, float] = {}
        roll_h: Dict[str, dict] = {}
        roll_g_sum: Dict[str, float] = {}
        roll_g_max: Dict[str, float] = {}
        n_stale = 0
        up = view.gauge(
            "fleet_host_up",
            "1 while the host's beacon is fresher than stale_after_s; "
            "0 marks a stale host (its gauges leave the rollups)",
            labelnames=("host",))
        age = view.gauge(
            "fleet_beacon_age_seconds",
            "seconds since this host's beacon was last ingested",
            labelnames=("host",))
        resets = view.counter(
            "fleet_counter_resets_total",
            "snapshots whose totals DECREASED vs the previous one "
            "(worker restart mid-window) — folded as fresh epochs, "
            "never as negative deltas", labelnames=("host",))
        for h in sorted(hosts):
            counters, hists, gauges, stale, age_s, n_resets = hosts[h]
            up.labels(host=h).set(0.0 if stale else 1.0)
            age.labels(host=h).set(age_s)
            resets.labels(host=h).inc(n_resets)
            n_stale += bool(stale)
            snap = {"counters": {}, "gauges": {}, "histograms": {}}
            for series, v in counters.items():
                name, pairs = parse_series(series)
                snap["counters"][
                    _fmt_series(name, pairs + (("host", h),))] = v
                roll_c[series] = roll_c.get(series, 0.0) + v
            for series, a in hists.items():
                name, pairs = parse_series(series)
                snap["histograms"][
                    _fmt_series(name, pairs + (("host", h),))] = a
                r = roll_h.get(series)
                if r is None:
                    r = roll_h[series] = {"buckets": {}, "inf": 0.0,
                                          "sum": 0.0, "count": 0.0}
                for u, c in a["buckets"].items():
                    r["buckets"][u] = r["buckets"].get(u, 0.0) + c
                r["inf"] += a["inf"]
                r["sum"] += a["sum"]
                r["count"] += a["count"]
            for series, v in gauges.items():
                name, pairs = parse_series(series)
                snap["gauges"][
                    _fmt_series(name, pairs + (("host", h),))] = v
                if not stale:
                    roll_g_sum[series] = roll_g_sum.get(series, 0.0) + v
                    roll_g_max[series] = max(
                        roll_g_max.get(series, float("-inf")), v)
            self._merge_defensive(view, snap)
        roll = {"counters": {
                    _fmt_series(*_with_host(s, "fleet")): v
                    for s, v in roll_c.items()},
                "histograms": {
                    _fmt_series(*_with_host(s, "fleet")): a
                    for s, a in roll_h.items()},
                "gauges": {}}
        for s, v in roll_g_sum.items():
            roll["gauges"][_fmt_series(*_with_host(s, "fleet"))] = v
        for s, v in roll_g_max.items():
            roll["gauges"][_fmt_series(*_with_host(s, "fleet_max"))] = v
        self._merge_defensive(view, roll)
        view.gauge(
            "fleet_hosts_live",
            "hosts whose beacon is fresher than stale_after_s").set(
                len(hosts) - n_stale)
        view.gauge(
            "fleet_hosts_stale",
            "hosts whose beacon aged out (their gauges left the "
            "rollups; their counters remain)").set(n_stale)
        ts = self.traces.summary()
        view.gauge(
            "fleet_trace_store_traces",
            "distinct request trace ids the cross-worker trace store "
            "currently holds").set(ts["traces"])
        view.gauge(
            "fleet_trace_store_spans",
            "beaconed request spans held across all stored traces "
            "(deduped by (host, trace, pid, seq))").set(ts["spans"])
        view.gauge(
            "fleet_trace_store_rooted",
            "stored traces whose submit-minted root span has arrived "
            "(the rest are orphan fragments awaiting their root; a "
            "rooted trace can still report complete=false at /traces "
            "if stray same-host fragments fall outside the root)").set(
                ts["rooted"])
        view.counter(
            "fleet_trace_store_evicted_total",
            "trace trees the store evicted — retired-trace retention "
            "(LRU by retire time) plus the max_traces capacity bound "
            "— so sustained traffic cannot grow the aggregator "
            "without end").inc(ts["evicted"])
        if self.alerts is not None:
            self.alerts.evaluate(view, now=now)
            self.alerts.export(view)
        tstats = self.tsdb.stats()
        view.gauge(
            "fleet_tsdb_series",
            "distinct series the embedded time-series store currently "
            "holds history for (/query's universe)").set(
                tstats["series"])
        view.counter(
            "fleet_tsdb_samples_total",
            "timestamped samples the embedded time-series store has "
            "recorded across all series").inc(tstats["samples_total"])
        view.counter(
            "fleet_tsdb_evicted_total",
            "samples the store aged out or collapsed into the "
            "downsampled tier — bounded history, not unbounded "
            "growth").inc(tstats["evicted_total"])
        # record the finished view WALL-clocked (the ``now`` above is
        # monotonic staleness time): /query ranges line up with
        # postmortem timelines and cross-host wall stamps
        self.tsdb.record(view)
        return view

    @staticmethod
    def _merge_defensive(view: MetricsRegistry, snap: dict) -> None:
        """One host's labeled-series snapshot into the view, merged
        SERIES BY SERIES: a cross-host label-schema conflict (two
        workers registered the same family with different labels)
        must cost exactly the offending series, not the whole scrape
        — and a bulk merge that raised midway would have already
        half-applied (double-counting everything a retry re-adds)."""
        for kind in ("counters", "gauges", "histograms"):
            for series, v in snap.get(kind, {}).items():
                try:
                    view.merge_snapshot({kind: {series: v}})
                except ValueError:
                    view.counter(
                        "fleet_aggregate_conflicts_total",
                        "series dropped from the fleet view because "
                        "hosts disagree on a family's label schema"
                    ).inc()
                    log.warning("fleet aggregation: dropped "
                                "conflicting series %s", series)

    def render_prometheus(self) -> str:
        """Refresh (when directory-backed) and render the aggregated
        view — the method ``telemetry.MetricsServer`` calls, so a
        ``FleetRegistry`` can be served directly as a fleet-wide
        ``/metrics`` endpoint that re-aggregates per scrape."""
        if self.directory is not None:
            self.refresh()
        return self.view().render_prometheus()


def _with_host(series: str, host: str):
    name, pairs = parse_series(series)
    return name, pairs + (("host", host),)


def exchange_snapshots(registry: Optional[MetricsRegistry] = None,
                       host: Optional[str] = None,
                       max_bytes: int = 1 << 18) -> Dict[str, dict]:
    """Snapshot exchange over ``jax.distributed`` control collectives —
    the beacon transport for fleets that share a mesh but no
    filesystem.  Every process contributes its registry snapshot
    (JSON, zero-padded to ``max_bytes``) to one allgather; returns
    ``{host: snapshot}`` for ALL processes, ready to feed
    ``FleetRegistry.ingest``.  Single-process (no mesh) degenerates to
    just the local snapshot — callers need no special case."""
    import numpy as np
    if registry is None:
        from deeplearning4j_tpu import telemetry
        registry = telemetry.get_registry()
    if host is None:
        host = _default_host_id()
    doc = {"host": str(host), "snapshot": registry.snapshot()}
    import jax
    if jax.process_count() == 1:
        return {doc["host"]: doc["snapshot"]}
    payload = json.dumps(doc).encode()
    if len(payload) > max_bytes:
        raise ValueError(
            f"snapshot is {len(payload)} bytes > max_bytes="
            f"{max_bytes}; raise max_bytes (all processes must agree "
            "on it) or prune the registry")
    buf = np.zeros((max_bytes,), np.uint8)
    buf[:len(payload)] = np.frombuffer(payload, np.uint8)
    from jax.experimental import multihost_utils
    gathered = np.asarray(
        multihost_utils.process_allgather(buf))
    out: Dict[str, dict] = {}
    for row in gathered.reshape(-1, max_bytes):
        raw = bytes(row.tobytes().rstrip(b"\x00"))
        if not raw:
            continue
        peer = json.loads(raw.decode())
        out[str(peer["host"])] = peer["snapshot"]
    return out
