"""Unified telemetry: process-wide metrics registry + span tracing.

The production-observability subsystem the training-only ``ui`` stack
lacked (VERDICT r5 rec 10: serving-saturation visibility).  Three
pieces, all stdlib-only:

* ``registry``  — thread-safe Counter/Gauge/Histogram families with
  Prometheus text exposition, jsonl snapshots, and driver-side
  snapshot merging;
* ``tracing``   — nestable host-side spans exported as Chrome-trace
  jsonl (``about://tracing``/Perfetto-loadable), plus TRACKED spans
  (``begin``/``end`` from any thread, close-on-owner-death) carrying
  request trace ids across components;
* ``exposition``— stdlib ``http.server`` scrape endpoint;
* ``fleet``     — the cross-worker plane (ISSUE 12): per-host metric
  beacons pushed into a shared dir (or over ``jax.distributed``
  collectives) and ``FleetRegistry`` aggregation into ONE
  ``{host=}``-tagged scrape with rollups, reset detection and
  staleness marking;
* ``profiling`` — continuous per-device profiling (ISSUE 13): a
  sampling ``DeviceProfiler`` wraps the hot dispatch sites (decode
  tick, verify, prefill, optimizer step) with device-time measurement
  into ``fleet_device_phase_seconds{device=,phase=}`` plus an
  on-demand XProf capture trigger whose summary beacons fleet-wide;
  the beacons also ship closed request spans, which ``FleetRegistry``
  stitches into per-request trees in a ``FleetTraceStore``;
* ``slo``       — the plane's CONSUMER (ISSUE 15): declarative
  ``SLOSpec`` objectives over the already-emitted request series, an
  error-budget accountant, and a multi-window burn-rate
  ``AlertEngine`` whose state is ordinary metric families (beacons
  like everything else) and serves as JSON at ``/alerts``;
* ``flightrec`` — the per-host black box (ISSUE 15): a lock-cheap
  bounded ring of admission/dispatch/spill/watchdog/scale events;
  watchdog trips, chaos kills and preemptions freeze it — with the
  tracer's open spans, a metric snapshot, pre-crash metric HISTORY
  and the SLO state — into atomic postmortem bundles
  ``scripts/postmortem.py`` renders as a merged timeline;
* ``tsdb``      — the embedded time-series store (ISSUE 16): bounded
  per-series history rings (raw window + downsampled older tier)
  recorded each scrape/beacon cycle, range reads with
  ``rate``/``delta``/``quantile_over_time``, the ``/query`` endpoint
  beside ``/metrics``/``/traces``/``/alerts`` — and the ONE history
  substrate the SLO engine, the backlog forecaster and the
  autoscaler's windowed signals all read through.

Instrumented in-tree: ``optimize.fit_loop`` (step/data-wait split,
iteration/epoch/example counters), ``parallel.trainer`` and
``parallel.pipeline`` (per-worker step counters, dispatch spans, bubble
fraction), ``parallel.inference`` (latency histogram, queue depth,
batch occupancy, padding waste, shed/timeout counters),
``models.generation`` (tokens emitted, decode steps/s), and
``kernels.flash_attention`` (``flash_route_total{path=...}`` — silent
fallbacks off the flash path are a metric, not a debug deque).

Module-level ``counter``/``gauge``/``histogram`` register on ONE
process-default registry so every subsystem lands on the same scrape
surface; ``TelemetryListener`` bridges the registry into the existing
``set_listeners()`` machinery.
"""
from __future__ import annotations

from typing import Optional, Sequence

from deeplearning4j_tpu.telemetry.registry import (
    DEFAULT_BUCKETS, RATIO_BUCKETS, Counter, Gauge, Histogram,
    MetricsRegistry, parse_series)
from deeplearning4j_tpu.telemetry.tracing import (FleetTraceStore, Span,
                                                  SpanTracer)
from deeplearning4j_tpu.telemetry.exposition import (
    MetricsServer, start_metrics_server)
from deeplearning4j_tpu.telemetry.listener import TelemetryListener
from deeplearning4j_tpu.telemetry.fleet import (
    FleetRegistry, MetricsBeacon, exchange_snapshots, publish_beacon)
from deeplearning4j_tpu.telemetry.profiling import DeviceProfiler
from deeplearning4j_tpu.telemetry.flightrec import FlightRecorder
from deeplearning4j_tpu.telemetry.slo import (AlertEngine, CommandSink,
                                              SLOSpec, WebhookFileSink)
from deeplearning4j_tpu.telemetry.tsdb import TimeSeriesStore

_REGISTRY = MetricsRegistry()
_TRACER = SpanTracer()
_PROFILER = DeviceProfiler(_REGISTRY)
_FLIGHTREC = FlightRecorder()
_TSDB = TimeSeriesStore()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry every in-tree metric lives in."""
    return _REGISTRY


def get_tracer() -> SpanTracer:
    """The process-wide default span tracer."""
    return _TRACER


def get_profiler() -> DeviceProfiler:
    """The process-wide sampling device profiler (ISSUE 13) the hot
    dispatch sites report into."""
    return _PROFILER


def get_flight_recorder() -> FlightRecorder:
    """The process-wide flight recorder (ISSUE 15): the bounded ring
    of admission/dispatch/spill/watchdog/scale events the hot sites
    feed, and the postmortem-bundle writer the crash paths trip."""
    return _FLIGHTREC


def get_tsdb() -> TimeSeriesStore:
    """The process-wide embedded time-series store (ISSUE 16):
    recorded per scrape/beacon cycle, queried at ``/query``, and the
    pre-crash history source for postmortem bundles."""
    return _TSDB


def counter(name: str, documentation: str = "",
            labelnames: Sequence[str] = ()) -> Counter:
    return _REGISTRY.counter(name, documentation, labelnames)


def gauge(name: str, documentation: str = "",
          labelnames: Sequence[str] = ()) -> Gauge:
    return _REGISTRY.gauge(name, documentation, labelnames)


def histogram(name: str, documentation: str = "",
              labelnames: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return _REGISTRY.histogram(name, documentation, labelnames, buckets)


def span(name: str, **args):
    """``with telemetry.span("phase/thing"): ...`` on the default tracer."""
    return _TRACER.span(name, **args)


__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "SpanTracer",
    "Span", "MetricsServer", "start_metrics_server", "TelemetryListener",
    "FleetRegistry", "FleetTraceStore", "MetricsBeacon", "publish_beacon",
    "exchange_snapshots", "parse_series", "DeviceProfiler",
    "FlightRecorder", "AlertEngine", "SLOSpec", "WebhookFileSink",
    "CommandSink", "TimeSeriesStore",
    "DEFAULT_BUCKETS", "RATIO_BUCKETS",
    "get_registry", "get_tracer", "get_profiler", "get_flight_recorder",
    "get_tsdb", "counter", "gauge", "histogram", "span",
]
