"""SLO error-budget engine with multi-window burn-rate alerting.

PRs 12-13 built the *production* side of observability — the beacon
metric plane, the cross-worker trace store, device profiling — but
nothing CONSUMED it: no notion of an objective existed, and no alert
ever fired.  This module is the consumer, in the Google-SRE shape the
TPU-fleet retrospective (PAPERS: arXiv 2606.15870) credits for
multi-generation fleet resilience:

* **:class:`SLOSpec`** — a declarative objective over series the stack
  ALREADY emits.  Three objective kinds: ``availability`` (good/bad
  outcome counts from ``fleet_requests_total{tenant=,outcome=}``),
  ``latency`` (a phase of ``fleet_request_phase_seconds{phase=}``
  under ``threshold_s``, good/bad derived from the histogram buckets)
  and ``ttft`` (``generation_server_ttft_seconds`` under
  ``threshold_s``).  ``target`` is the good fraction (0.99 = "99% of
  requests good over ``window_s``");

* **error budget** — the complement of the target: over ``window_s``
  the service may spend ``(1 - target)`` of its traffic on bad
  events.  The accountant tracks the spent fraction
  (``fleet_slo_error_budget_remaining{slo=}``; <= 0 is EXHAUSTED —
  the router defers exhausted batch tenants' waiting work behind
  within-budget tenants, so interactive traffic is never shed first);

* **burn rate** — how fast the budget is being spent: ``bad_fraction
  / (1 - target)`` over a window (burn 1.0 = exactly on budget; burn
  14.4 over a 30-day window = the whole month's budget gone in 2
  days).  :class:`AlertEngine` evaluates each spec's burn over
  MULTI-WINDOW pairs (the SRE-book shape: a condition needs the burn
  over BOTH a short and a long window — the long window proves the
  burn is sustained, the short window makes the alert resolve quickly
  once the bleeding stops, and together they cannot flap on a load
  blip the way a single short window does);

* **alert state machine** — ``inactive -> pending -> firing ->
  resolved``: a met condition holds ``for_s`` before firing (pending),
  a firing alert needs the condition clear for ``clear_for_s`` before
  resolving, and every transition is counted
  (``fleet_slo_alert_transitions_total{slo=,to=}``).

The engine's own state is ordinary metric families
(``fleet_slo_burn_rate{slo=,window=}``, ``fleet_slo_alert_firing
{slo=}``, budget/state gauges), so a per-host engine BEACONS like any
other family and aggregates in ``FleetRegistry``; an engine attached
to a ``FleetRegistry`` (``FleetRegistry(alerts=engine)``) instead
evaluates against the AGGREGATED view on every scrape and exports its
families into it — either way the fleet scrape answers "which SLO is
burning".  The JSON surface is the ``/alerts`` endpoint beside
``/metrics`` and ``/traces`` (``telemetry.MetricsServer``).

Closed-loop consumers: ``serving.autoscale.Autoscaler`` treats a
firing burn-rate alert as a pre-warm signal STRONGER than the backlog
forecaster (a measured SLO burn beats a projection — the streak gate
opens immediately, cooldown still applies;
``fleet_autoscale_alert_prewarms_total`` counts scale-ups attributed
to the alert alone), and ``serving.router.ServingFleet`` reads
:meth:`AlertEngine.exhausted_tenants` each dispatch pass.
"""
from __future__ import annotations

import json
import logging
import math
import os
import subprocess
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.telemetry.registry import _fmt_labels
from deeplearning4j_tpu.telemetry.tsdb import TimeSeriesStore, is_reset

log = logging.getLogger("deeplearning4j_tpu")

OBJECTIVES = ("availability", "latency", "ttft")

#: alert states, in severity order (the state gauge's value)
STATES = ("inactive", "pending", "firing", "resolved")

#: default multi-window burn configs as FRACTIONS of ``window_s`` —
#: for the SRE-book 30-day budget window these are exactly the
#: canonical pairs: (5m, 1h, burn 14.4, page) and (30m, 6h, burn 6.0,
#: ticket).  Each entry: (short_frac, long_frac, burn_threshold,
#: severity).
DEFAULT_WINDOW_FRACS = ((1 / 8640, 1 / 720, 14.4, "page"),
                        (1 / 1440, 1 / 120, 6.0, "ticket"))


class SLOSpec:
    """One declarative objective (immutable config).

    >>> SLOSpec("inter-avail", objective="availability", target=0.999,
    ...         tenant="inter", window_s=30 * 86400)
    >>> SLOSpec("ttft", objective="latency", target=0.95,
    ...         phase="queue", threshold_s=0.25, window_s=3600)

    ``windows`` overrides the burn-rate pairs: an iterable of
    ``(short_s, long_s, burn_threshold, severity)`` tuples in SECONDS
    (default: the SRE fast/slow pairs scaled from ``window_s`` via
    :data:`DEFAULT_WINDOW_FRACS`).  ``for_s``/``clear_for_s`` are the
    state machine's hold times; ``min_events`` is the traffic floor
    below which a window reports burn 0 (one unlucky request on an
    idle service must not page).

    ``availability`` counts ``bad_outcomes`` (default expired +
    failed) against ``good_outcomes`` (default admitted) of
    ``counter_family``; ``latency`` thresholds one ``phase`` of
    ``histogram_family``; ``ttft`` thresholds the decode server's
    TTFT histogram.  ``threshold_s`` resolves to the largest
    histogram bucket bound <= the requested value (bucket math — an
    exact bound costs nothing, a between-bounds threshold is
    conservative)."""

    __slots__ = ("name", "objective", "target", "tenant", "phase",
                 "threshold_s", "window_s", "windows", "for_s",
                 "clear_for_s", "min_events", "counter_family",
                 "histogram_family", "good_outcomes", "bad_outcomes")

    def __init__(self, name: str, objective: str = "availability",
                 target: float = 0.99, tenant: Optional[str] = None,
                 phase: str = "total",
                 threshold_s: Optional[float] = None,
                 window_s: float = 30 * 86400.0,
                 windows: Optional[Iterable[Tuple]] = None,
                 for_s: float = 0.0, clear_for_s: float = 0.0,
                 min_events: int = 1,
                 counter_family: str = "fleet_requests_total",
                 histogram_family: str = "fleet_request_phase_seconds",
                 good_outcomes: Sequence[str] = ("admitted",),
                 bad_outcomes: Sequence[str] = ("expired", "failed")):
        self.name = str(name)
        if not self.name:
            raise ValueError("an SLOSpec needs a non-empty name")
        if objective not in OBJECTIVES:
            raise ValueError(f"unknown objective {objective!r}; must "
                             f"be one of {OBJECTIVES}")
        self.objective = objective
        self.target = float(target)
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"target={target} must be in (0, 1) — "
                             "1.0 leaves no error budget to burn")
        self.tenant = None if tenant is None else str(tenant)
        self.phase = str(phase)
        self.threshold_s = (None if threshold_s is None
                            else float(threshold_s))
        if objective in ("latency", "ttft") and self.threshold_s is None:
            raise ValueError(f"objective {objective!r} needs "
                             "threshold_s (the good/bad latency bar)")
        self.window_s = float(window_s)
        if self.window_s <= 0:
            raise ValueError("window_s must be > 0")
        if windows is None:
            windows = [(self.window_s * sf, self.window_s * lf, b, sev)
                       for sf, lf, b, sev in DEFAULT_WINDOW_FRACS]
        self.windows = tuple(
            (float(s), float(l), float(b), str(sev))
            for s, l, b, sev in windows)
        if not self.windows:
            raise ValueError("an SLOSpec needs >= 1 burn window")
        for s, l, b, _sev in self.windows:
            if not 0 < s <= l:
                raise ValueError(
                    f"burn window ({s:g}s, {l:g}s) needs 0 < short "
                    "<= long")
            if b <= 0:
                raise ValueError(f"burn threshold {b:g} must be > 0")
        self.for_s = float(for_s)
        self.clear_for_s = float(clear_for_s)
        self.min_events = max(1, int(min_events))
        self.counter_family = str(counter_family)
        self.histogram_family = str(histogram_family)
        self.good_outcomes = tuple(str(o) for o in good_outcomes)
        self.bad_outcomes = tuple(str(o) for o in bad_outcomes)

    @property
    def budget(self) -> float:
        """The error budget: the bad fraction the target allows."""
        return 1.0 - self.target

    def horizon_s(self) -> float:
        """How much sample history the engine must retain for this
        spec: the budget window and every burn window."""
        return max([self.window_s] + [l for _s, l, _b, _v in
                                      self.windows])


def burn_rate(good: float, bad: float, budget: float) -> float:
    """The SRE burn rate of one window's (good, bad) event counts:
    ``bad_fraction / budget``.  1.0 spends the budget exactly over
    the budget window; 0 when the window saw no traffic (no events,
    no burn)."""
    total = good + bad
    if total <= 0:
        return 0.0
    return (bad / total) / budget


def _children(fam):
    """The shared rollup-selection rule (host="fleet" children on
    aggregated views, every child on plain registries) — ONE encoding
    lives in ``telemetry.fleet.rollup_children``."""
    from deeplearning4j_tpu.telemetry.fleet import rollup_children
    return rollup_children(fam)


class _SpecState:
    """One spec's fold state (mutated only under the engine lock):
    last raw totals for reset detection and the alert state machine.
    The cumulative (good, bad) sample HISTORY lives in the engine's
    shared :class:`~deeplearning4j_tpu.telemetry.tsdb.TimeSeriesStore`
    (ISSUE 16) as ``fleet_slo_window_events{slo=}`` — one windowing/
    reset encoding for the whole observability plane instead of a
    private list here."""

    __slots__ = ("last_good", "last_bad", "state", "t_cond",
                 "t_clear", "t_fired", "last_burns", "remaining",
                 "transitions")

    def __init__(self):
        self.last_good = None
        self.last_bad = None
        self.state = "inactive"
        self.t_cond = None              # condition first true (pending)
        self.t_clear = None             # condition first false (firing)
        self.t_fired = None
        self.last_burns: Dict[str, float] = {}
        self.remaining = 1.0
        self.transitions: Dict[str, int] = {}


class AlertEngine:
    """Evaluate :class:`SLOSpec` burn rates against a metric view and
    run the alert state machines.

    >>> engine = AlertEngine([SLOSpec("avail", target=0.99)])
    >>> engine.evaluate()            # samples the process registry
    >>> engine.alerts()              # [{"slo", "state", "burns", ...}]
    >>> engine.exhausted_tenants()   # the router's defer signal

    ``source`` is where samples come from when :meth:`evaluate` gets
    no registry: a ``MetricsRegistry``, a ``FleetRegistry`` (its
    aggregated view), or None for the process default.  ``registry``
    is where the engine's OWN families register (default: the process
    registry, so a per-host engine's state beacons fleet-wide; pass a
    private registry for isolation).  :meth:`start` runs a daemon
    evaluation loop for standalone per-host use; an engine attached
    to a ``FleetRegistry`` or an ``Autoscaler`` is driven by its host
    instead."""

    def __init__(self, specs: Iterable[SLOSpec], source=None,
                 registry=None, interval_s: float = 5.0,
                 sinks: Iterable = (), history=None):
        self.specs: Tuple[SLOSpec, ...] = tuple(specs)
        if not self.specs:
            raise ValueError("AlertEngine needs >= 1 SLOSpec")
        names = [s.name for s in self.specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLOSpec names in {names}")
        self.source = source
        self.interval_s = float(interval_s)
        if self.interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        if registry is None:
            from deeplearning4j_tpu import telemetry
            registry = telemetry.get_registry()
        self.registry = registry
        # notification egress (ISSUE 16 / ROADMAP 4d): sinks fire on
        # pending->firing and firing->resolved transitions, exactly
        # once per transition; a failing sink degrades (counted,
        # logged), never raises into the evaluation loop
        self.sinks = tuple(sinks)
        # the shared history substrate (ISSUE 16): the (good, bad)
        # sample windows live in a TimeSeriesStore under
        # fleet_slo_window_events{slo=} — pass a shared store to pool
        # history with other recorders, default is engine-private
        self.history = history if history is not None \
            else TimeSeriesStore()
        self._lock = threading.Lock()
        self._st: Dict[str, _SpecState] = {s.name: _SpecState()
                                           for s in self.specs}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._burn = registry.gauge(
            "fleet_slo_burn_rate",
            "error-budget burn rate per SLO and window (bad_fraction "
            "/ budget over the window; 1.0 spends the budget exactly "
            "over the budget window)", labelnames=("slo", "window"))
        self._remaining = registry.gauge(
            "fleet_slo_error_budget_remaining",
            "fraction of the SLO's error budget left over its budget "
            "window (<= 0: exhausted — the router defers this "
            "tenant's batch work behind within-budget tenants)",
            labelnames=("slo",))
        self._firing = registry.gauge(
            "fleet_slo_alert_firing",
            "1 while the SLO's multi-window burn-rate alert is "
            "firing (the autoscaler's strongest pre-warm signal)",
            labelnames=("slo",))
        self._stateg = registry.gauge(
            "fleet_slo_alert_state",
            "alert state machine position: 0 inactive, 1 pending, "
            "2 firing, 3 resolved", labelnames=("slo",))
        self._trans = registry.counter(
            "fleet_slo_alert_transitions_total",
            "alert state transitions per SLO, labeled by the state "
            "entered", labelnames=("slo", "to"))
        self._notif = registry.counter(
            "fleet_alert_notifications_total",
            "alert notifications attempted per sink and result — "
            "fired on pending->firing and firing->resolved, exactly "
            "once per transition; errors degrade, never raise",
            labelnames=("sink", "result"))

    # -- sampling ------------------------------------------------------
    def _read_counts(self, reg, spec: SLOSpec
                     ) -> Optional[Tuple[float, float]]:
        """Cumulative (good, bad) event totals for one spec from one
        registry view; None when the family is absent entirely (no
        sample this pass — absence of traffic is NOT a reset)."""
        if spec.objective == "availability":
            fam = reg.get(spec.counter_family)
            if fam is None or fam.kind != "counter":
                return None
            tidx = (fam.labelnames.index("tenant")
                    if "tenant" in fam.labelnames else None)
            oidx = (fam.labelnames.index("outcome")
                    if "outcome" in fam.labelnames else None)
            if oidx is None:
                return None
            good = bad = 0.0
            for lv, child in _children(fam):
                if spec.tenant is not None and tidx is not None \
                        and lv[tidx] != spec.tenant:
                    continue
                if lv[oidx] in spec.bad_outcomes:
                    bad += child.value
                elif lv[oidx] in spec.good_outcomes:
                    good += child.value
            return good, bad
        name = (spec.histogram_family if spec.objective == "latency"
                else "generation_server_ttft_seconds")
        fam = reg.get(name)
        if fam is None or fam.kind != "histogram":
            return None
        pidx = (fam.labelnames.index("phase")
                if "phase" in fam.labelnames else None)
        good = total = 0.0
        for lv, child in _children(fam):
            if spec.objective == "latency" and pidx is not None \
                    and lv[pidx] != spec.phase:
                continue
            uppers, counts, _s, n = child.state()
            total += n
            cum = 0.0
            for ub, c in zip(uppers, counts):
                if ub > spec.threshold_s + 1e-12:
                    break
                cum += c
            good += cum
        # family present but no matching child yet = zero traffic —
        # a valid (0, 0) sample, NOT an absent family (the prime
        # sample an idle process takes before its first request)
        return good, max(0.0, total - good)

    #: retention bound per spec: the sample history is thinned to at
    #: most ~this many points over the spec's horizon (head samples
    #: closer together than horizon/MAX_SAMPLES collapse into the
    #: newest).  Burn math only needs window-edge deltas, so the
    #: approximation costs at most one thinning-gap of edge slack —
    #: and a 30-day budget window polled every 5s stays a few
    #: thousand tuples instead of half a million.
    MAX_SAMPLES = 8192

    def _series_key(self, spec: SLOSpec) -> str:
        """This spec's history series in the shared store."""
        return "fleet_slo_window_events" + _fmt_labels(
            ("slo",), (spec.name,))

    def _sample_locked(self, st: _SpecState, spec: SLOSpec,
                       now: float, counts) -> None:
        if counts is None:
            return
        good, bad = counts
        key = self._series_key(spec)
        if st.last_good is not None and (
                is_reset(st.last_good, good)
                or is_reset(st.last_bad, bad)):
            # reset epoch (worker restart / fresh view source): the
            # cumulative history no longer shares an origin with the
            # new totals — folding would manufacture negative deltas.
            # Re-prime instead; the budget window restarts with the
            # process, exactly like the fleet aggregator's rule.
            self.history.clear(key)
        st.last_good, st.last_bad = good, bad
        # mode="slo" is this engine's exact windowed encoding (same-
        # instant keep-first, dense-head collapse, keep-one-at-or-
        # before-horizon trim), now shared via the store
        self.history.append(key, now, (good, bad), kind="window",
                            mode="slo", horizon_s=spec.horizon_s(),
                            max_points=self.MAX_SAMPLES)

    def _window_counts(self, spec: SLOSpec, now: float, window_s: float
                       ) -> Tuple[float, float]:
        """(good, bad) DELTAS over the trailing window: latest sample
        minus the newest sample at or before ``now - window_s`` (the
        oldest retained sample when history is shorter — a young
        engine reads its whole history as the window).  The store's
        history is time-ordered, so the edge lookup bisects."""
        key = self._series_key(spec)
        last = self.history.latest(key)
        if last is None:
            return 0.0, 0.0
        g1, b1 = last[1]
        g0, b0 = self.history.edge(key, now - window_s)[1]
        return max(0.0, g1 - g0), max(0.0, b1 - b0)

    # -- evaluation ----------------------------------------------------
    def _source_registry(self):
        src = self.source
        if src is None:
            from deeplearning4j_tpu import telemetry
            return telemetry.get_registry()
        from deeplearning4j_tpu.telemetry.fleet import resolve_view
        return resolve_view(src)

    def evaluate(self, reg=None, now: Optional[float] = None
                 ) -> List[dict]:
        """One evaluation pass: sample every spec's cumulative counts
        from ``reg`` (default: the configured source), update burn
        rates, budgets and the state machines, publish the gauges,
        and return the alert list (:meth:`alerts`).  ``now`` is
        injectable for tests — the engine's clock is
        ``time.monotonic``."""
        if reg is None:
            reg = self._source_registry()
        now = time.monotonic() if now is None else float(now)
        transitions: List[Tuple[str, str]] = []
        with self._lock:
            for spec in self.specs:
                st = self._st[spec.name]
                self._sample_locked(st, spec, now,
                                    self._read_counts(reg, spec))
                burns: Dict[str, float] = {}
                condition = False
                # coverage: how long the sample history actually
                # spans — a window the engine has not yet OBSERVED
                # for its full length must not page (the young-engine
                # first-blip flap the multi-window shape exists to
                # prevent); its burn still REPORTS (the fraction seen
                # so far), it just cannot meet the condition
                span = self.history.span(self._series_key(spec))
                for short_s, long_s, thresh, _sev in spec.windows:
                    bs = burn_rate(
                        *self._window_counts(spec, now, short_s),
                        spec.budget)
                    gl, bl_bad = self._window_counts(spec, now, long_s)
                    bl = burn_rate(gl, bl_bad, spec.budget)
                    burns[f"{short_s:g}s"] = bs
                    burns[f"{long_s:g}s"] = bl
                    if (gl + bl_bad >= spec.min_events
                            and span >= long_s - 1e-9
                            and bs >= thresh and bl >= thresh):
                        condition = True
                st.last_burns = burns
                wg, wb = self._window_counts(spec, now, spec.window_s)
                total = wg + wb
                # budget CONSUMED so far: the observed bad fraction,
                # scaled by how much of the budget window the history
                # actually covers — the budget is an absolute
                # allowance over window_s, and extrapolating seconds
                # of data across a 30-day window would let ONE
                # startup failure mark a tenant exhausted (and the
                # router/autoscaler penalize it) off no evidence.
                # min_events floors it the same way it floors burns.
                if total >= spec.min_events:
                    coverage = min(1.0, span / spec.window_s) \
                        if spec.window_s > 0 else 1.0
                    spent = ((wb / total) / spec.budget) * coverage
                else:
                    spent = 0.0
                st.remaining = max(-1.0, 1.0 - spent)
                transitions += [
                    (spec.name, to)
                    for to in self._advance_locked(st, spec, now,
                                                   condition)]
            out = self._alerts_locked()
        # gauges published OUTSIDE the engine lock (family child locks
        # are their own; holding ours across them buys nothing)
        for a in out:
            name = a["slo"]
            for w, b in a["burns"].items():
                self._burn.labels(slo=name, window=w).set(b)
            self._remaining.labels(slo=name).set(a["budget_remaining"])
            self._firing.labels(slo=name).set(
                1.0 if a["state"] == "firing" else 0.0)
            self._stateg.labels(slo=name).set(
                float(STATES.index(a["state"])))
        for name, to in transitions:
            self._trans.labels(slo=name, to=to).inc()
        self._notify(transitions, out)
        return out

    def _notify(self, transitions: List[Tuple[str, str]],
                alerts: List[dict]) -> None:
        """Deliver pending->firing / firing->resolved transitions to
        every configured sink — exactly once per transition (the
        transitions list holds each state entry once), outside the
        engine lock.  A sink failure is counted and logged, never
        raised: egress must not kill the evaluation loop."""
        if not self.sinks:
            return
        notify = [(n, to) for n, to in transitions
                  if to in ("firing", "resolved")]
        if not notify:
            return
        byname = {a["slo"]: a for a in alerts}
        for name, to in notify:
            a = byname.get(name, {})
            event = {"t": time.time(), "slo": name, "to": to,
                     "state": a.get("state"),
                     "burns": a.get("burns", {}),
                     "budget_remaining": a.get("budget_remaining")}
            for sink in self.sinks:
                sname = getattr(sink, "name", type(sink).__name__)
                try:
                    sink.deliver(dict(event))
                    self._notif.labels(sink=sname, result="ok").inc()
                except Exception:
                    log.exception(
                        "alert sink %s failed delivering %s -> %s",
                        sname, name, to)
                    self._notif.labels(sink=sname,
                                       result="error").inc()

    def _advance_locked(self, st: _SpecState, spec: SLOSpec,
                        now: float, condition: bool) -> List[str]:
        """Advance one state machine; returns the states entered (0,
        1 or — pending that fires the same pass with ``for_s=0`` — 2
        of them)."""
        entered: List[str] = []

        def to(state: str) -> None:
            st.state = state
            st.transitions[state] = st.transitions.get(state, 0) + 1
            entered.append(state)

        if condition:
            st.t_clear = None
            if st.state in ("inactive", "resolved"):
                st.t_cond = now
                to("pending")
            if st.state == "pending" and now - st.t_cond >= spec.for_s:
                st.t_fired = now
                to("firing")
        else:
            if st.state == "pending":
                # never fired: a blip that cleared before for_s held
                # goes straight back (no resolved edge — resolved
                # means "it fired and stopped")
                st.t_cond = None
                to("inactive")
            elif st.state == "firing":
                if st.t_clear is None:
                    st.t_clear = now
                if now - st.t_clear >= spec.clear_for_s:
                    to("resolved")
        return entered

    # -- queries -------------------------------------------------------
    def _alerts_locked(self) -> List[dict]:
        out = []
        for spec in self.specs:
            st = self._st[spec.name]
            out.append({
                "slo": spec.name, "objective": spec.objective,
                "tenant": spec.tenant, "target": spec.target,
                "state": st.state, "burns": dict(st.last_burns),
                "budget_remaining": st.remaining,
                "exhausted": st.remaining <= 0.0,
                "t_fired": st.t_fired,
                "windows": [list(w) for w in spec.windows],
                "transitions": dict(st.transitions)})
        return out

    def alerts(self) -> List[dict]:
        """The last evaluation's alert state, one entry per spec."""
        with self._lock:
            return self._alerts_locked()

    def any_firing(self) -> bool:
        with self._lock:
            return any(st.state == "firing" for st in self._st.values())

    def budget_remaining(self, name: str) -> float:
        with self._lock:
            return self._st[name].remaining

    def exhausted_tenants(self) -> frozenset:
        """Tenants of specs whose error budget is spent — the
        router's dispatch-order defer signal (tenant-less specs never
        name anyone)."""
        with self._lock:
            return frozenset(
                spec.tenant for spec in self.specs
                if spec.tenant is not None
                and self._st[spec.name].remaining <= 0.0)

    # -- admission projection (ISSUE 18) -------------------------------
    def _projected_burn_locked(self, spec: SLOSpec, now: float
                               ) -> Tuple[float, bool]:
        """(projected burn, covered) for one spec, read from the
        shared TSDB history.  The projection is the short-window burn
        extrapolated by its trend against the long window — ``b_short
        + max(0, b_short - b_long)`` — the worst across the spec's
        burn windows.  Coverage-gated EXACTLY like the alert
        condition (min_events over the long window AND the history
        span covering it), so a young store projects (0, False) and
        can never reject: the same first-blip discipline the
        multi-window alert shape exists for."""
        key = self._series_key(spec)
        span = self.history.span(key)
        projected, covered = 0.0, False
        for short_s, long_s, _thresh, _sev in spec.windows:
            gl, bl_bad = self._window_counts(spec, now, long_s)
            if gl + bl_bad < spec.min_events or span < long_s - 1e-9:
                continue
            covered = True
            bs = burn_rate(*self._window_counts(spec, now, short_s),
                           spec.budget)
            bl = burn_rate(gl, bl_bad, spec.budget)
            projected = max(projected, bs + max(0.0, bs - bl))
        return projected, covered

    def projection(self, now: Optional[float] = None) -> List[dict]:
        """Per-spec projected burn for admission control and the
        degradation ladder — one entry per spec: ``{slo, tenant,
        projected_burn, covered, budget_remaining}``.  Pure read of
        the history already folded by :meth:`evaluate`; call that
        first (the engine loop does)."""
        now = time.monotonic() if now is None else float(now)
        out = []
        with self._lock:
            for spec in self.specs:
                p, cov = self._projected_burn_locked(spec, now)
                out.append({
                    "slo": spec.name, "tenant": spec.tenant,
                    "projected_burn": p, "covered": cov,
                    "budget_remaining": self._st[spec.name].remaining})
        return out

    def admission_decision(self, tenant: str,
                           now: Optional[float] = None) -> dict:
        """Map one tenant to ``admit`` / ``degrade`` / ``reject``
        BEFORE the fleet spends anything on the request.

        Reject is deliberately narrow: a spec NAMING this tenant must
        project burn at or above its worst (page-severity) threshold
        with the error budget already overdrawn — a tenant-less fleet
        SLO can only ever degrade (shared pain shapes everyone, it
        does not single anyone out).  ``retry_after_s`` comes from
        the budget-recovery slope: the overdraft slides out of the
        budget window at the rate it was burned in, so the wait is
        ``window_s * deficit / spent`` clamped to [shortest burn
        window, window_s]."""
        now = time.monotonic() if now is None else float(now)
        tenant = str(tenant)
        verdict = {"decision": "admit", "retry_after_s": 0.0,
                   "projected_burn": 0.0, "slo": None}
        with self._lock:
            for spec in self.specs:
                if spec.tenant is not None and spec.tenant != tenant:
                    continue
                projected, covered = self._projected_burn_locked(
                    spec, now)
                if not covered or projected <= 0.0:
                    continue
                st = self._st[spec.name]
                threshs = [t for _s, _l, t, _v in spec.windows]
                pages = [t for _s, _l, t, sev in spec.windows
                         if sev == "page"]
                hard = max(pages) if pages else max(threshs)
                if (spec.tenant == tenant and projected >= hard
                        and st.remaining <= 0.0):
                    spent = 1.0 - st.remaining
                    deficit = -st.remaining
                    shortest = min(s for s, _l, _t, _v in spec.windows)
                    retry = (spec.window_s * deficit / spent
                             if spent > 0 else shortest)
                    retry = min(max(retry, shortest), spec.window_s)
                    return {"decision": "reject",
                            "retry_after_s": retry,
                            "projected_burn": projected,
                            "slo": spec.name}
                if (projected >= min(threshs)
                        and projected > verdict["projected_burn"]):
                    verdict = {"decision": "degrade",
                               "retry_after_s": 0.0,
                               "projected_burn": projected,
                               "slo": spec.name}
        return verdict

    def state(self) -> dict:
        """The full engine snapshot — the ``/alerts`` document and
        the postmortem bundle's ``slo`` section."""
        alerts = self.alerts()
        return {"specs": len(self.specs), "alerts": alerts,
                "firing": sorted(a["slo"] for a in alerts
                                 if a["state"] == "firing"),
                "exhausted": sorted(
                    a["slo"] for a in alerts if a["exhausted"])}

    def render_json(self) -> str:
        return json.dumps(self.state())

    def export(self, view) -> None:
        """Write the engine's current families into ``view`` — how a
        ``FleetRegistry``-attached engine's state reaches the
        aggregated scrape (the view is rebuilt per scrape, so the
        export re-runs each time; counters re-inc from zero on the
        fresh view).  Children are tagged ``host="fleet"`` like every
        other rollup — and when per-host engines ALSO beacon these
        families (host-tagged, with a summed ``host="fleet"`` gauge
        rollup that is meaningless for rates), this export's
        aggregated-view evaluation OVERWRITES that rollup with the
        authoritative value instead of colliding on label schema."""
        for a in self.alerts():
            name = a["slo"]
            burn = view.gauge(self._burn.name, self._burn.documentation,
                              labelnames=("slo", "window", "host"))
            for w, b in a["burns"].items():
                burn.labels(slo=name, window=w, host="fleet").set(b)
            view.gauge(self._remaining.name,
                       self._remaining.documentation,
                       labelnames=("slo", "host")).labels(
                           slo=name, host="fleet").set(
                           a["budget_remaining"])
            view.gauge(self._firing.name, self._firing.documentation,
                       labelnames=("slo", "host")).labels(
                           slo=name, host="fleet").set(
                           1.0 if a["state"] == "firing" else 0.0)
            view.gauge(self._stateg.name, self._stateg.documentation,
                       labelnames=("slo", "host")).labels(
                           slo=name, host="fleet").set(
                           float(STATES.index(a["state"])))
            trans = view.counter(self._trans.name,
                                 self._trans.documentation,
                                 labelnames=("slo", "to", "host"))
            for to, n in a["transitions"].items():
                trans.labels(slo=name, to=to, host="fleet").inc(n)

    # -- standalone loop ----------------------------------------------
    def _loop(self, stop: threading.Event) -> None:
        while not stop.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception:
                # one bad pass must not silence the alerting plane
                log.exception("AlertEngine evaluation failed")

    def start(self) -> "AlertEngine":
        # fresh stop event: re-armable after a close() (a set() event
        # would end the new loop on its first wait); the thread
        # closes over ITS OWN event
        stop = threading.Event()
        thread = threading.Thread(target=self._loop, args=(stop,),
                                  name="dl4j-tpu-slo-alerts",
                                  daemon=True)
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self          # already running
            self._stop = stop
            self._thread = thread
        thread.start()
        return self

    def close(self) -> None:
        with self._lock:
            stop = self._stop
            thread = self._thread
            self._thread = None
        stop.set()
        if thread is not None:
            thread.join(timeout=max(5.0, 2 * self.interval_s))

    def __enter__(self) -> "AlertEngine":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class WebhookFileSink:
    """File-backed webhook egress (ROADMAP 4d): each notification
    appends ONE JSON line to ``path`` (a directory gets
    ``alerts.jsonl`` inside it — the shared-dir shape, beside the
    beacons and bundles).  The append is a single ``O_APPEND`` write
    of a complete line, so concurrent writers from several hosts
    interleave whole records, never torn ones — the same contract an
    HTTP webhook receiver's log would give, without inventing a
    network dependency this image doesn't have."""

    name = "webhook_file"

    def __init__(self, path: str):
        self.path = str(path)

    def deliver(self, event: dict) -> None:
        path = self.path
        if os.path.isdir(path):
            path = os.path.join(path, "alerts.jsonl")
        data = (json.dumps(event) + "\n").encode()
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                     0o644)
        try:
            os.write(fd, data)
        finally:
            os.close(fd)


class CommandSink:
    """Command egress: run ``argv`` once per notification with the
    event JSON on stdin (the pager/webhook-relay hook shape).  A
    non-zero exit or a hang past ``timeout_s`` raises — the engine's
    delivery loop counts it as ``result="error"`` and moves on."""

    name = "command"

    def __init__(self, argv: Sequence[str], timeout_s: float = 10.0):
        self.argv = [str(a) for a in argv]
        if not self.argv:
            raise ValueError("CommandSink needs a command to run")
        self.timeout_s = float(timeout_s)

    def deliver(self, event: dict) -> None:
        subprocess.run(self.argv, input=json.dumps(event).encode(),
                       stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL,
                       timeout=self.timeout_s, check=True)
