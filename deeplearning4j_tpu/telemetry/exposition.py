"""Stdlib Prometheus scrape endpoint — ``/metrics`` over ``http.server``.

No web framework exists in this image (and the ROADMAP's "no live UI
server" stance stands for dashboards); a scrape endpoint is different —
it is how a fleet's Prometheus/VictoriaMetrics reaches a training or
serving process, and ``ThreadingHTTPServer`` from the stdlib is enough:
a scrape is one GET returning one rendered string.

When the served registry carries a cross-worker trace store (a
``FleetRegistry`` — ISSUE 13), the same endpoint also answers
``/traces`` (store summary + trace ids) and ``/traces?id=<trace>``
(ONE stitched submit->retire tree as JSON) — the query surface the
trace store exists for.
"""
from __future__ import annotations

import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Background scrape server; ``port=0`` binds an ephemeral port
    (read it back from ``.port`` — what tests and the smoke script use).

    >>> srv = MetricsServer(registry, port=9464).start()
    >>> # curl localhost:9464/metrics
    >>> srv.close()
    """

    def __init__(self, registry: MetricsRegistry, port: int = 9464,
                 host: str = "127.0.0.1"):
        self.registry = registry
        self._httpd = ThreadingHTTPServer((host, port), self._handler())
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _handler(self):
        registry = self.registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                path = self.path.split("?")[0]
                traces = getattr(registry, "traces", None)
                if path == "/traces" and traces is not None:
                    # fold the latest beacons in first, like a scrape
                    refresh = getattr(registry, "refresh", None)
                    if callable(refresh) and getattr(
                            registry, "directory", None) is not None:
                        refresh()
                    q = urllib.parse.parse_qs(
                        urllib.parse.urlparse(self.path).query)
                    tid = q.get("id", [None])[0]
                    body = traces.render_json(tid).encode()
                    ctype = "application/json"
                elif path in ("/metrics", "/"):
                    body = registry.render_prometheus().encode()
                    ctype = CONTENT_TYPE
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # keep scrapes out of stderr
                pass

        return Handler

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="dl4j-tpu-metrics",
                                        daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def start_metrics_server(registry: MetricsRegistry, port: int = 9464,
                         host: str = "127.0.0.1") -> MetricsServer:
    """One-liner: start a daemon scrape endpoint for ``registry``."""
    return MetricsServer(registry, port=port, host=host).start()
