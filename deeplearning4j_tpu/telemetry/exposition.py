"""Stdlib Prometheus scrape endpoint — ``/metrics`` over ``http.server``.

No web framework exists in this image (and the ROADMAP's "no live UI
server" stance stands for dashboards); a scrape endpoint is different —
it is how a fleet's Prometheus/VictoriaMetrics reaches a training or
serving process, and ``ThreadingHTTPServer`` from the stdlib is enough:
a scrape is one GET returning one rendered string.

When the served registry carries a cross-worker trace store (a
``FleetRegistry`` — ISSUE 13), the same endpoint also answers
``/traces`` (store summary + trace ids) and ``/traces?id=<trace>``
(ONE stitched submit->retire tree as JSON) — the query surface the
trace store exists for.  When it carries an SLO alert engine
(``FleetRegistry(alerts=...)`` or a plain registry with an
``.alerts`` attribute — ISSUE 15), ``/alerts`` serves the engine's
state (burn rates, budgets, firing alerts) as JSON, evaluated against
the served view per request like a scrape.  When it carries an
embedded time-series store (every ``FleetRegistry``, or a plain
registry with a ``.tsdb`` attribute — ISSUE 16), ``/query`` answers
range reads: ``?series=<name>`` plus optional label matchers
(``tenant=inter``), ``start``/``end`` (unix seconds),
``func=range|rate|delta|quantile`` and ``q`` for quantiles.

Error discipline (ISSUE 15): unknown paths answer a REAL 404 with a
JSON body naming the endpoints, malformed queries answer 400 with a
JSON error, and a handler exception answers 500 with the error name —
a scrape surface must never push a stack trace down the wire.
"""
from __future__ import annotations

import json
import logging
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

log = logging.getLogger("deeplearning4j_tpu")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
JSON_TYPE = "application/json"


class MetricsServer:
    """Background scrape server; ``port=0`` binds an ephemeral port
    (read it back from ``.port`` — what tests and the smoke script use).

    >>> srv = MetricsServer(registry, port=9464).start()
    >>> # curl localhost:9464/metrics   (+ /traces, /alerts where
    >>> #                                the registry carries them)
    >>> srv.close()
    """

    def __init__(self, registry: MetricsRegistry, port: int = 9464,
                 host: str = "127.0.0.1"):
        self.registry = registry
        self._httpd = ThreadingHTTPServer((host, port), self._handler())
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _handler(self):
        registry = self.registry

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code: int, doc: dict) -> None:
                self._send(code, json.dumps(doc).encode(), JSON_TYPE)

            def _refresh(self) -> None:
                """Fold the latest beacons in first, like a scrape
                (directory-backed ``FleetRegistry`` only)."""
                refresh = getattr(registry, "refresh", None)
                if callable(refresh) and getattr(
                        registry, "directory", None) is not None:
                    refresh()

            def do_GET(self):
                try:
                    self._route()
                except (BrokenPipeError, ConnectionResetError):
                    pass             # client went away mid-write
                                     # (scrape timeout RST included)
                except Exception as e:
                    # never a stack trace down the wire: the scrape
                    # surface degrades to a typed JSON error
                    log.exception("metrics endpoint %s failed",
                                  self.path)
                    try:
                        self._send_json(500, {
                            "error": type(e).__name__,
                            "detail": str(e)})
                    except Exception:
                        pass

            def _route(self):
                parsed = urllib.parse.urlparse(self.path)
                path = parsed.path
                traces = getattr(registry, "traces", None)
                alerts = getattr(registry, "alerts", None)
                tsdb = getattr(registry, "tsdb", None)
                if path == "/query" and tsdb is not None:
                    self._query(tsdb, parsed)
                elif path == "/traces" and traces is not None:
                    q = urllib.parse.parse_qs(
                        parsed.query, keep_blank_values=True)
                    unknown = sorted(set(q) - {"id"})
                    ids = q.get("id", [])
                    if unknown or len(ids) > 1 or (ids and not ids[0]):
                        # malformed query: a 400 with a JSON body, not
                        # a silent default and never a stack trace
                        self._send_json(400, {
                            "error": "bad_query",
                            "detail": ("unknown parameter(s) "
                                       f"{unknown}" if unknown else
                                       "id must be given exactly once "
                                       "with a non-empty value"),
                            "usage": "/traces or /traces?id=<trace>"})
                        return
                    self._refresh()
                    body = traces.render_json(
                        ids[0] if ids else None).encode()
                    self._send(200, body, JSON_TYPE)
                elif path == "/alerts" and alerts is not None:
                    # evaluated against the served view per request —
                    # the scrape IS the evaluation cadence, exactly
                    # like a Prometheus rule group.  A FleetRegistry
                    # evaluates its attached engine INSIDE view(), so
                    # building the view is the whole pass — a second
                    # explicit evaluate would double the work and the
                    # sample density per scrape.
                    view = getattr(registry, "view", None)
                    if callable(view):
                        self._refresh()
                        view()
                    else:
                        alerts.evaluate(registry)
                    self._send(200, alerts.render_json().encode(),
                               JSON_TYPE)
                elif path in ("/metrics", "/"):
                    body = registry.render_prometheus().encode()
                    self._send(200, body, CONTENT_TYPE)
                else:
                    endpoints = ["/metrics"]
                    if traces is not None:
                        endpoints.append("/traces")
                    if alerts is not None:
                        endpoints.append("/alerts")
                    if tsdb is not None:
                        endpoints.append("/query")
                    self._send_json(404, {"error": "not_found",
                                          "endpoints": endpoints})

            _QUERY_USAGE = ("/query?series=<name>[&<label>=<value>...]"
                            "[&start=<unix_s>][&end=<unix_s>]"
                            "[&func=range|rate|delta|quantile]"
                            "[&q=<0..1>]")

            def _query(self, tsdb, parsed) -> None:
                """The TSDB range-read endpoint (ISSUE 16): reserved
                parameters select/shape the read, every OTHER
                parameter is a label equality matcher.  Malformed
                input answers 400 with a JSON error, matching the
                /traces discipline; an unknown series matches nothing
                and answers 200 with an empty result."""
                q = urllib.parse.parse_qs(parsed.query,
                                          keep_blank_values=True)
                bad = None
                repeated = sorted(k for k, v in q.items()
                                  if len(v) > 1)
                series = q.get("series", [""])[0]
                if repeated:
                    bad = f"repeated parameter(s) {repeated}"
                elif not series:
                    bad = ("series must be given exactly once with a "
                           "non-empty value")
                start = end = qq = None
                func = q.get("func", ["range"])[0]
                if bad is None:
                    try:
                        if "start" in q:
                            start = float(q["start"][0])
                        if "end" in q:
                            end = float(q["end"][0])
                        if "q" in q:
                            qq = float(q["q"][0])
                    except ValueError:
                        bad = "start/end/q must be numbers"
                if bad is not None:
                    self._send_json(400, {"error": "bad_query",
                                          "detail": bad,
                                          "usage": self._QUERY_USAGE})
                    return
                matchers = [(k, v[0]) for k, v in sorted(q.items())
                            if k not in ("series", "start", "end",
                                         "func", "q")]
                # a fleet view refreshes + records a fresh sample per
                # query, exactly like a scrape drives /alerts
                view = getattr(registry, "view", None)
                if callable(view):
                    self._refresh()
                    view()
                try:
                    doc = tsdb.query(series, matchers=matchers,
                                     start=start, end=end, func=func,
                                     q=qq)
                except ValueError as e:
                    # tsdb's own validation (unknown func, quantile
                    # without q, rate over a histogram): caller error,
                    # not a 500
                    self._send_json(400, {"error": "bad_query",
                                          "detail": str(e),
                                          "usage": self._QUERY_USAGE})
                    return
                self._send_json(200, doc)

            def log_message(self, *a):  # keep scrapes out of stderr
                pass

        return Handler

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="dl4j-tpu-metrics",
                                        daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def start_metrics_server(registry: MetricsRegistry, port: int = 9464,
                         host: str = "127.0.0.1") -> MetricsServer:
    """One-liner: start a daemon scrape endpoint for ``registry``."""
    return MetricsServer(registry, port=port, host=host).start()
