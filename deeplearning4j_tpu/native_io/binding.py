"""ctypes loader + typed wrappers for libdl4j_tpu_native."""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datavec.records import RecordReader

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_NAMES = ("libdl4j_tpu_native.so", "libdl4j_tpu_native.dylib")

_lib: Optional[ctypes.CDLL] = None


def _find_lib() -> Optional[str]:
    cands = [os.path.join(_NATIVE_DIR, "build", n) for n in _LIB_NAMES]
    env = os.environ.get("DL4J_TPU_NATIVE_LIB")
    if env:
        cands.insert(0, env)
    for c in cands:
        if os.path.exists(c):
            return c
    return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    path = _find_lib()
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.dl4j_csv_dims.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
    lib.dl4j_csv_dims.restype = ctypes.c_int
    lib.dl4j_csv_parse.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char,
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int]
    lib.dl4j_csv_parse.restype = ctypes.c_int
    lib.dl4j_u8_to_f32_scaled.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64, ctypes.c_float]
    lib.dl4j_u8_to_f32_scaled.restype = None
    _lib = lib
    return lib


def native_available() -> bool:
    return _load() is not None


def build_native(quiet: bool = True) -> str:
    """Build the CMake project in-tree; returns the library path."""
    build_dir = os.path.join(_NATIVE_DIR, "build")
    kw = dict(capture_output=quiet, check=True)
    subprocess.run(["cmake", "-B", build_dir, "-S", _NATIVE_DIR], **kw)
    subprocess.run(["cmake", "--build", build_dir, "-j"], **kw)
    path = _find_lib()
    if path is None:
        raise RuntimeError("native build produced no library")
    global _lib
    _lib = None  # force reload
    return path


def load_csv_native(path: str, skip_lines: int = 0, delimiter: str = ",",
                    n_threads: int = 0) -> np.ndarray:
    """Whole CSV -> float32 [rows, cols] through the native parser.
    Raises RuntimeError when the library isn't built (callers that want
    the fallback use NativeCSVRecordReader)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(
            "native library not built — run "
            "deeplearning4j_tpu.native_io.build_native()")
    n_threads = n_threads or (os.cpu_count() or 1)
    rows, cols = ctypes.c_int64(), ctypes.c_int64()
    rc = lib.dl4j_csv_dims(path.encode(), skip_lines,
                           delimiter.encode()[0:1] or b",",
                           ctypes.byref(rows), ctypes.byref(cols))
    if rc:
        raise IOError(f"dl4j_csv_dims({path!r}) failed rc={rc}")
    out = np.empty((rows.value, cols.value), np.float32)
    rc = lib.dl4j_csv_parse(
        path.encode(), skip_lines, delimiter.encode()[0:1] or b",",
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        rows.value, cols.value, int(n_threads))
    if rc:
        raise ValueError(
            f"dl4j_csv_parse({path!r}) failed rc={rc} (non-numeric cell "
            "or ragged row?)")
    return out


def u8_to_f32_scaled(arr: np.ndarray, scale: float = 1.0 / 255.0
                     ) -> np.ndarray:
    lib = _load()
    src = np.ascontiguousarray(arr, np.uint8)
    if lib is None:
        return src.astype(np.float32) * scale  # fallback
    dst = np.empty(src.shape, np.float32)
    lib.dl4j_u8_to_f32_scaled(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        src.size, ctypes.c_float(scale))
    return dst


class NativeCSVRecordReader(RecordReader):
    """Drop-in for ``CSVRecordReader`` on NUMERIC CSVs: parses the whole
    file natively, yields rows as float lists.  Falls back to the Python
    reader when the native library isn't available."""

    def __init__(self, path: str, skip_lines: int = 0,
                 delimiter: str = ",", n_threads: int = 0):
        self.path = path
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self.n_threads = n_threads
        self._matrix: Optional[np.ndarray] = None

    def matrix(self) -> np.ndarray:
        if self._matrix is None:
            if native_available():
                self._matrix = load_csv_native(
                    self.path, self.skip_lines, self.delimiter,
                    self.n_threads)
            else:
                from deeplearning4j_tpu.datavec.records import \
                    CSVRecordReader
                rows = list(CSVRecordReader(self.path, self.skip_lines,
                                            self.delimiter))
                self._matrix = np.asarray(rows, np.float32)
        return self._matrix

    def __iter__(self):
        for row in self.matrix():
            yield row.tolist()

    def reset(self):
        pass
