"""ctypes binding for the native IO core (``native/``).

The JavaCPP/JNI analogue, minus codegen: a plain C ABI
(``dl4j_csv_dims``/``dl4j_csv_parse``/``dl4j_u8_to_f32_scaled``) loaded
with ctypes.  Everything degrades gracefully to the pure-Python
``datavec`` path when the shared library hasn't been built —
``build_native()`` builds it with the repo's CMake project.
"""
from deeplearning4j_tpu.native_io.binding import (NativeCSVRecordReader,
                                                  build_native,
                                                  load_csv_native,
                                                  native_available,
                                                  u8_to_f32_scaled)

__all__ = ["native_available", "build_native", "load_csv_native",
           "NativeCSVRecordReader", "u8_to_f32_scaled"]
