"""BERT WordPiece tokenization (``org.deeplearning4j.text.tokenization
.tokenizerfactory.BertWordPieceTokenizerFactory`` [UNVERIFIED]) — the
tokenizer side of BASELINE config 4's SST-2 fine-tune pipeline.

Algorithm parity target is the canonical BERT basic+wordpiece pass
(whitespace clean, punctuation split, optional lowercase + accent
strip, then greedy longest-match-first subwords with the ``##``
continuation prefix and per-token UNK on failure); goldens in
``tests/test_wordpiece.py`` come from the installed ``transformers``
``BertTokenizer`` over a locally-written vocab file (no egress).
"""
from __future__ import annotations

import unicodedata
from typing import Dict, Iterable, List, Optional, Sequence, Union


def _is_punct(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) \
            or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _basic_tokens(text: str, lower: bool, strip_accents: bool
                  ) -> List[str]:
    out: List[str] = []
    for tok in text.strip().split():
        if lower:
            tok = tok.lower()
        if strip_accents:
            tok = "".join(c for c in unicodedata.normalize("NFD", tok)
                          if unicodedata.category(c) != "Mn")
        cur = ""
        for ch in tok:
            if _is_punct(ch):
                if cur:
                    out.append(cur)
                    cur = ""
                out.append(ch)
            else:
                cur += ch
        if cur:
            out.append(cur)
    return out


class BertWordPieceTokenizerFactory:
    """Greedy longest-match-first WordPiece over a BERT vocab.

    ``vocab`` is a path to a one-token-per-line vocab.txt (HF layout:
    line number == id) or an explicit token->id dict.
    """

    def __init__(self, vocab: Union[str, Dict[str, int]],
                 lower_case: bool = True, strip_accents: bool = True,
                 unk_token: str = "[UNK]", max_input_chars: int = 100):
        if isinstance(vocab, str):
            with open(vocab, encoding="utf-8") as f:
                tokens = [ln.rstrip("\n") for ln in f]
            vocab = {t: i for i, t in enumerate(tokens)}
        self.vocab: Dict[str, int] = dict(vocab)
        self.inv: Dict[int, str] = {i: t for t, i in self.vocab.items()}
        self.lower_case = lower_case
        self.strip_accents = strip_accents
        self.unk = unk_token
        self.max_input_chars = max_input_chars
        for special in ("[PAD]", "[CLS]", "[SEP]", unk_token):
            if special not in self.vocab:
                raise ValueError(f"vocab is missing {special!r}")

    def _wordpiece(self, token: str) -> List[str]:
        if len(token) > self.max_input_chars:
            return [self.unk]
        pieces, start = [], 0
        while start < len(token):
            end = len(token)
            cur = None
            while start < end:
                sub = token[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = sub
                    break
                end -= 1
            if cur is None:
                return [self.unk]       # whole-token UNK, not partial
            pieces.append(cur)
            start = end
        return pieces

    def tokenize(self, text: str) -> List[str]:
        out: List[str] = []
        for tok in _basic_tokens(text, self.lower_case,
                                 self.strip_accents):
            out.extend(self._wordpiece(tok))
        return out

    def encode(self, text: str, pair: Optional[str] = None,
               max_len: Optional[int] = None):
        """-> (ids, attention_mask, token_type_ids) with [CLS]/[SEP]
        framing, truncated (HF ``longest_first``: pop from the end of
        the LONGER segment, the PAIR on ties) and padded to
        ``max_len`` when given."""
        v = self.vocab
        if max_len is not None:
            floor = 2 if pair is None else 3
            if max_len < floor:
                raise ValueError(
                    f"max_len={max_len} cannot fit the special tokens "
                    f"([CLS]/[SEP] framing needs >= {floor} positions "
                    f"{'with a pair' if pair else ''})")
        conv = lambda toks: [v[t] for t in toks]
        a = self.tokenize(text)
        if pair is None:
            if max_len is not None and len(a) > max_len - 2:
                a = a[:max_len - 2]
            ids = [v["[CLS]"]] + conv(a) + [v["[SEP]"]]
            tt = [0] * len(ids)
        else:
            b = self.tokenize(pair)
            if max_len is not None:
                while len(a) + len(b) > max_len - 3:
                    (a if len(a) > len(b) else b).pop()
            ids = ([v["[CLS]"]] + conv(a) + [v["[SEP]"]]
                   + conv(b) + [v["[SEP]"]])
            tt = [0] * (len(a) + 2) + [1] * (len(b) + 1)
        if max_len is not None:
            pad = max_len - len(ids)
            mask = [1] * len(ids) + [0] * pad
            ids += [v["[PAD]"]] * pad
            tt += [0] * pad
        else:
            mask = [1] * len(ids)
        return ids, mask, tt

    def decode(self, ids: Iterable[int]) -> str:
        toks = [self.inv.get(int(i), self.unk) for i in ids]
        out = ""
        for t in toks:
            if t in ("[CLS]", "[SEP]", "[PAD]"):
                continue
            if t.startswith("##"):
                out += t[2:]
            else:
                out += (" " if out else "") + t
        return out
