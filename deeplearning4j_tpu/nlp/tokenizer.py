"""Tokenizer factories (``org.deeplearning4j.text.tokenization
.tokenizerfactory.{DefaultTokenizerFactory,…}`` + the
``CommonPreprocessor`` lowercase/strip-punctuation step)."""
from __future__ import annotations

import re
from typing import Callable, List, Optional

_PUNCT = re.compile(r"[^\w\s]", re.UNICODE)


def common_preprocessor(token: str) -> str:
    """``CommonPreprocessor``: lowercase + strip punctuation."""
    return _PUNCT.sub("", token.lower())


class DefaultTokenizerFactory:
    """Whitespace tokenization + optional token preprocessor."""

    def __init__(self, preprocessor: Optional[Callable[[str], str]]
                 = common_preprocessor):
        self.preprocessor = preprocessor

    def tokenize(self, sentence: str) -> List[str]:
        toks = sentence.split()
        if self.preprocessor:
            toks = [self.preprocessor(t) for t in toks]
        return [t for t in toks if t]


class RegexTokenizerFactory(DefaultTokenizerFactory):
    """Tokens = regex matches (``NGramTokenizerFactory`` relative:
    the reference's regex tokenizer family)."""

    def __init__(self, pattern: str = r"\w+", preprocessor=None):
        super().__init__(preprocessor)
        self.pattern = re.compile(pattern)

    def tokenize(self, sentence: str) -> List[str]:
        toks = self.pattern.findall(sentence)
        if self.preprocessor:
            toks = [self.preprocessor(t) for t in toks]
        return [t for t in toks if t]
