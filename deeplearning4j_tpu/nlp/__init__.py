"""NLP: Word2Vec / ParagraphVectors, tokenizers, vector serialization.

Reference: ``deeplearning4j-nlp-parent`` —
``org.deeplearning4j.models.word2vec.Word2Vec`` (skip-gram, hierarchical
softmax + negative sampling, custom threaded trainer),
``models.paragraphvectors.ParagraphVectors``,
``text.tokenization.tokenizerfactory.*``, ``WordVectorSerializer``.

TPU-first: instead of the reference's lock-free multithreaded HS trees,
training is BATCHED skip-gram with negative sampling — pair generation
on host, one jitted embedding-update step on device (the formulation
that keeps the MXU busy and needs no parameter locking at all).
"""
from deeplearning4j_tpu.nlp.tokenizer import (DefaultTokenizerFactory,
                                              RegexTokenizerFactory)
from deeplearning4j_tpu.nlp.word2vec import ParagraphVectors, Word2Vec
from deeplearning4j_tpu.nlp.fasttext import FastText
from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer
from deeplearning4j_tpu.nlp.wordpiece import BertWordPieceTokenizerFactory

__all__ = ["Word2Vec", "ParagraphVectors", "FastText",
           "BertWordPieceTokenizerFactory", "DefaultTokenizerFactory",
           "RegexTokenizerFactory", "WordVectorSerializer"]
